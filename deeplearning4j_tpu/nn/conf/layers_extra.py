"""Extended layer configurations (the reference's long tail).

Reference: `deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/layers/`
— Convolution3D, Subsampling1D/3D, Upsampling1D/3D, Cropping1D/2D/3D,
ZeroPadding1D/3D, SimpleRnn, LastTimeStep, TimeDistributed, MaskZeroLayer,
LocallyConnected1D/2D, PReLULayer, SpaceToDepth/Batch, RepeatVector,
ElementWiseMultiplicationLayer, MaskLayer, CnnLossLayer, RnnLossLayer,
CenterLossOutputLayer, Yolo2OutputLayer (objdetect), LearnedSelfAttention,
RecurrentAttention, FrozenLayer, variational/VariationalAutoencoder,
CapsuleLayer/PrimaryCapsules/CapsuleStrengthLayer, dropout variants
(conf/dropout/: GaussianDropout, GaussianNoise, AlphaDropout).

All are pure modules like conf/layers.py; see that file's module docstring.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ...ops import conv_ops, nn_ops, recurrent
from ..activations import get_activation
from ..losses import get_loss
from ..weights import init_weights
from .layers import (Layer, ConvolutionLayer, DenseLayer, OutputLayer,
                     _pair)


def _triple(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v),) * 3


# -- 3D convolution family -----------------------------------------------
@dataclasses.dataclass
class Convolution3D(Layer):
    """3D conv over NCDHW (reference conf/layers/Convolution3D.java)."""
    n_in: int = 0
    n_out: int = 0
    kernel_size: Sequence[int] = (3, 3, 3)
    stride: Sequence[int] = (1, 1, 1)
    padding: Union[str, Sequence[int]] = "SAME"
    activation: str = "identity"
    weight_init: str = "relu"
    has_bias: bool = True

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        kd, kh, kw = _triple(self.kernel_size)
        p = {"W": init_weights(key, (kd, kh, kw, n_in, self.n_out),
                               self.weight_init)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,))
        return p

    def forward(self, params, x, training=False, key=None):
        pad = self.padding if isinstance(self.padding, str) \
            else _triple(self.padding)
        out = conv_ops.conv3d(x, params["W"], params.get("b"),
                              strides=_triple(self.stride), padding=pad,
                              data_format="NCDHW")
        return get_activation(self.activation)(out)

    def output_type(self, input_type):
        c, d, h, w = input_type
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        if isinstance(self.padding, str) and self.padding.upper() == "SAME":
            return (self.n_out, -(-d // sd), -(-h // sh), -(-w // sw))
        pd, ph, pw = _triple(self.padding) if not isinstance(self.padding, str) \
            else (0, 0, 0)
        return (self.n_out, (d + 2 * pd - kd) // sd + 1,
                (h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1)


@dataclasses.dataclass
class Subsampling1DLayer(Layer):
    """1D pooling over [B, C, T] (reference Subsampling1DLayer.java)."""
    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = None
    padding: int = 0

    def forward(self, params, x, training=False, key=None):
        s = self.stride if self.stride is not None else self.kernel_size
        x4 = x[:, :, :, None]  # [B, C, T, 1] — reuse the 2D pools
        if self.pooling_type.lower() == "max":
            out = conv_ops.maxpool2d(x4, (self.kernel_size, 1), (s, 1),
                                     (self.padding, 0) if self.padding else "VALID",
                                     "NCHW")
        else:
            out = conv_ops.avgpool2d(x4, (self.kernel_size, 1), (s, 1),
                                     (self.padding, 0) if self.padding else "VALID",
                                     "NCHW")
        return out[:, :, :, 0]

    def output_type(self, input_type):
        c, t = input_type
        s = self.stride if self.stride is not None else self.kernel_size
        return (c, (t + 2 * self.padding - self.kernel_size) // s + 1)

    def has_params(self):
        return False


@dataclasses.dataclass
class Subsampling3DLayer(Layer):
    """3D pooling over NCDHW (reference Subsampling3DLayer.java)."""
    pooling_type: str = "max"
    kernel_size: Sequence[int] = (2, 2, 2)
    stride: Sequence[int] = None
    padding: Union[str, Sequence[int]] = "VALID"
    #: divisor counts padded cells (reference legacy); keras/TF exclude
    avg_include_pad: bool = True

    def forward(self, params, x, training=False, key=None):
        s = self.stride if self.stride is not None else self.kernel_size
        pad = self.padding if isinstance(self.padding, str) \
            else _triple(self.padding)
        if self.pooling_type.lower() == "max":
            return conv_ops.maxpool3d(x, _triple(self.kernel_size),
                                      _triple(s), pad, "NCDHW")
        return conv_ops.avgpool3d(x, _triple(self.kernel_size), _triple(s),
                                  pad, "NCDHW",
                                  include_pad=self.avg_include_pad)

    def output_type(self, input_type):
        c, d, h, w = input_type
        kd, kh, kw = _triple(self.kernel_size)
        s = self.stride if self.stride is not None else self.kernel_size
        sd, sh, sw = _triple(s)
        if isinstance(self.padding, str):
            if self.padding.upper() == "SAME":
                return (c, -(-d // sd), -(-h // sh), -(-w // sw))
            pd = ph = pw = 0
        else:
            pd, ph, pw = _triple(self.padding)
        return (c, (d + 2 * pd - kd) // sd + 1, (h + 2 * ph - kh) // sh + 1,
                (w + 2 * pw - kw) // sw + 1)

    def has_params(self):
        return False


@dataclasses.dataclass
class Upsampling1D(Layer):
    """Repeat along time (reference Upsampling1D.java)."""
    size: int = 2

    def forward(self, params, x, training=False, key=None):
        return jnp.repeat(x, self.size, axis=2)

    def output_type(self, input_type):
        c, t = input_type
        return (c, t * self.size)

    def has_params(self):
        return False


@dataclasses.dataclass
class Upsampling3D(Layer):
    size: Sequence[int] = (2, 2, 2)

    def forward(self, params, x, training=False, key=None):
        sd, sh, sw = _triple(self.size)
        return conv_ops.upsampling3d(x, sd, sh, sw, "NCDHW")

    def output_type(self, input_type):
        c, d, h, w = input_type
        sd, sh, sw = _triple(self.size)
        return (c, d * sd, h * sh, w * sw)

    def has_params(self):
        return False


# -- cropping / padding ---------------------------------------------------
@dataclasses.dataclass
class Cropping1D(Layer):
    cropping: Sequence[int] = (1, 1)

    def forward(self, params, x, training=False, key=None):
        a, b = self.cropping
        return x[:, :, a:x.shape[2] - b]

    def output_type(self, input_type):
        c, t = input_type
        return (c, t - sum(self.cropping))

    def has_params(self):
        return False


@dataclasses.dataclass
class Cropping2D(Layer):
    cropping: Sequence[int] = (1, 1, 1, 1)  # top,bottom,left,right

    def forward(self, params, x, training=False, key=None):
        t, b, l, r = self.cropping
        return x[:, :, t:x.shape[2] - b, l:x.shape[3] - r]

    def output_type(self, input_type):
        c, h, w = input_type
        t, b, l, r = self.cropping
        return (c, h - t - b, w - l - r)

    def has_params(self):
        return False


@dataclasses.dataclass
class Cropping3D(Layer):
    cropping: Sequence[int] = (1, 1, 1, 1, 1, 1)

    def forward(self, params, x, training=False, key=None):
        d0, d1, h0, h1, w0, w1 = self.cropping
        return x[:, :, d0:x.shape[2] - d1, h0:x.shape[3] - h1,
                 w0:x.shape[4] - w1]

    def output_type(self, input_type):
        c, d, h, w = input_type
        d0, d1, h0, h1, w0, w1 = self.cropping
        return (c, d - d0 - d1, h - h0 - h1, w - w0 - w1)

    def has_params(self):
        return False


@dataclasses.dataclass
class ZeroPadding1DLayer(Layer):
    padding: Sequence[int] = (1, 1)

    def forward(self, params, x, training=False, key=None):
        a, b = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (a, b)))

    def output_type(self, input_type):
        c, t = input_type
        return (c, t + sum(self.padding))

    def has_params(self):
        return False


@dataclasses.dataclass
class ZeroPadding3DLayer(Layer):
    padding: Sequence[int] = (1, 1, 1, 1, 1, 1)

    def forward(self, params, x, training=False, key=None):
        d0, d1, h0, h1, w0, w1 = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (d0, d1), (h0, h1), (w0, w1)))

    def output_type(self, input_type):
        c, d, h, w = input_type
        d0, d1, h0, h1, w0, w1 = self.padding
        return (c, d + d0 + d1, h + h0 + h1, w + w0 + w1)

    def has_params(self):
        return False


# -- recurrent ------------------------------------------------------------
@dataclasses.dataclass
class SimpleRnn(Layer):
    """Elman RNN over [B, F, T] (reference conf/layers/recurrent/SimpleRnn.java)."""
    n_in: int = 0
    n_out: int = 0
    activation: str = "tanh"
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        k1, k2 = jax.random.split(key)
        return {"Wx": init_weights(k1, (n_in, self.n_out), self.weight_init),
                "Wh": init_weights(k2, (self.n_out, self.n_out),
                                   self.weight_init),
                "b": jnp.zeros((self.n_out,))}

    accepts_mask = True

    def forward(self, params, x, training=False, key=None, mask=None):
        xt = jnp.swapaxes(x, 1, 2)
        h_seq, _ = recurrent.simple_rnn(xt, params["Wx"], params["Wh"],
                                        params["b"],
                                        activation=get_activation(self.activation),
                                        mask=mask)
        return jnp.swapaxes(h_seq, 1, 2)

    def output_type(self, input_type):
        return (self.n_out, input_type[1])


@dataclasses.dataclass
class GRU(Layer):
    """GRU over [B, F, T] (libnd4j gruCell op; capability superset — the
    reference layer API itself ships no GRU conf)."""
    n_in: int = 0
    n_out: int = 0
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        k1, k2 = jax.random.split(key)
        return {"Wru": init_weights(k1, (n_in + self.n_out, 2 * self.n_out),
                                    self.weight_init),
                "Wc": init_weights(k2, (n_in + self.n_out, self.n_out),
                                   self.weight_init),
                "bru": jnp.zeros((2 * self.n_out,)),
                "bc": jnp.zeros((self.n_out,))}

    accepts_mask = True

    def forward(self, params, x, training=False, key=None, mask=None):
        xt = jnp.swapaxes(x, 1, 2)
        h0 = jnp.zeros((x.shape[0], self.n_out), x.dtype)
        h_seq, _ = recurrent.gru(xt, h0, params["Wru"], params["Wc"],
                                 params["bru"], params["bc"], mask=mask)
        return jnp.swapaxes(h_seq, 1, 2)

    def output_type(self, input_type):
        return (self.n_out, input_type[1])


@dataclasses.dataclass
class GRUResetAfter(Layer):
    """GRU with the reset-gate applied AFTER the recurrent matmul and
    separate input/recurrent biases — the Keras `reset_after=True` (CuDNN)
    convention, which the fused-gate GRU above cannot express. Params use
    the ONNX/keras-transposed layout: W [3H, In], R [3H, H], b [6H] with
    gate rows (z, r, h). Runs over [B, F, T] like the other RNN layers."""
    n_in: int = 0
    n_out: int = 0
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        k1, k2 = jax.random.split(key)
        return {"W": init_weights(k1, (3 * self.n_out, n_in),
                                  self.weight_init),
                "R": init_weights(k2, (3 * self.n_out, self.n_out),
                                  self.weight_init),
                "b": jnp.zeros((6 * self.n_out,))}

    accepts_mask = True

    def forward(self, params, x, training=False, key=None, mask=None):
        xt = jnp.swapaxes(x, 1, 2)  # [B, T, F]
        h_seq, _ = recurrent.gru_onnx(xt, params["W"], params["R"],
                                      params["b"], linear_before_reset=1,
                                      time_major=False, mask=mask)
        return jnp.swapaxes(h_seq, 1, 2)

    def output_type(self, input_type):
        return (self.n_out, input_type[1])


@dataclasses.dataclass
class SpatialDropout(Layer):
    """Drop whole channels (reference conf/dropout/SpatialDropout.java):
    one mask entry per [B, C], broadcast over the trailing spatial/time
    dims."""
    rate: float = 0.5

    def forward(self, params, x, training=False, key=None):
        if not training or key is None or self.rate <= 0:
            return x
        keep = 1.0 - self.rate
        mask_shape = x.shape[:2] + (1,) * (x.ndim - 2)
        mask = jax.random.bernoulli(key, keep, mask_shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    def has_params(self):
        return False

    def needs_key(self):
        return True


@dataclasses.dataclass
class LayerNormalizationLayer(Layer):
    """Feature-axis layer norm with learned gamma/beta (the Keras
    LayerNormalization adapter target; SameDiff-side reference is the
    layer_norm op, `libnd4j/.../declarable/headers/nn.h` layer_norm).
    Normalizes over the channel axis (axis 1 for rank>=3, else last)."""
    n_out: int = 0  # inferred
    eps: float = 1e-3

    def init_params(self, key, input_type):
        c = self.n_out or input_type[0]
        return {"gamma": jnp.ones((c,)), "beta": jnp.zeros((c,))}

    def forward(self, params, x, training=False, key=None):
        axis = 1 if x.ndim >= 3 else -1
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axis, keepdims=True)
        var = jnp.var(xf, axis=axis, keepdims=True)
        norm = (xf - mean) / jnp.sqrt(var + self.eps)
        shape = [1] * x.ndim
        shape[axis] = params["gamma"].shape[0]
        out = norm * params["gamma"].reshape(shape) + \
            params["beta"].reshape(shape)
        return out.astype(x.dtype)


@dataclasses.dataclass
class LastTimeStep(Layer):
    """Wrapper: last time step of an RNN layer's [B, F, T] output
    (reference conf/layers/recurrent/LastTimeStep.java). With a mask, the
    underlying RNN carries state through masked steps, so [:, :, -1] IS
    the last VALID step's output (Keras return_sequences=False)."""
    underlying: Layer = None
    return_sequence = False

    @property
    def accepts_mask(self):
        return getattr(self.underlying, "accepts_mask", False)

    def init_params(self, key, input_type):
        return self.underlying.init_params(key, input_type)

    def forward(self, params, x, training=False, key=None, mask=None):
        if mask is not None:
            out = self.underlying.forward(params, x, training, key,
                                          mask=mask)
        else:
            out = self.underlying.forward(params, x, training, key)
        return out[:, :, -1]

    def output_type(self, input_type):
        t = self.underlying.output_type(input_type)
        return (t[0],)

    def has_params(self):
        return self.underlying.has_params()

    def needs_key(self):
        return self.underlying.needs_key()


@dataclasses.dataclass
class TimeDistributed(Layer):
    """Apply an FF layer at every timestep of [B, F, T]
    (reference conf/layers/recurrent/TimeDistributed.java)."""
    underlying: Layer = None

    def init_params(self, key, input_type):
        return self.underlying.init_params(key, (input_type[0],))

    def forward(self, params, x, training=False, key=None):
        b, f, t = x.shape
        flat = jnp.swapaxes(x, 1, 2).reshape(b * t, f)
        out = self.underlying.forward(params, flat, training, key)
        return jnp.swapaxes(out.reshape(b, t, -1), 1, 2)

    def output_type(self, input_type):
        inner = self.underlying.output_type((input_type[0],))
        return (inner[0], input_type[1])

    def has_params(self):
        return self.underlying.has_params()

    def needs_key(self):
        return self.underlying.needs_key()


@dataclasses.dataclass
class MaskZeroLayer(Layer):
    """Zero out all-zero (padding) timesteps after the wrapped RNN layer
    (reference conf/layers/util/MaskZeroLayer.java)."""
    underlying: Layer = None
    mask_value: float = 0.0

    def init_params(self, key, input_type):
        return self.underlying.init_params(key, input_type)

    def forward(self, params, x, training=False, key=None):
        # timestep is masked where every feature equals mask_value
        keep = jnp.any(x != self.mask_value, axis=1, keepdims=True)
        out = self.underlying.forward(params, x, training, key)
        return out * keep.astype(out.dtype)

    def output_type(self, input_type):
        return self.underlying.output_type(input_type)

    def has_params(self):
        return self.underlying.has_params()


# -- locally connected ----------------------------------------------------
@dataclasses.dataclass
class LocallyConnected2D(Layer):
    """Conv2D with unshared weights (reference conf/layers/LocallyConnected2D.java).

    Patch extraction + one einsum — a single batched contraction on the MXU
    instead of the reference's per-position loop.
    """
    n_in: int = 0
    n_out: int = 0
    kernel_size: Sequence[int] = (3, 3)
    stride: Sequence[int] = (1, 1)
    activation: str = "identity"
    weight_init: str = "xavier"
    has_bias: bool = True
    input_size: Sequence[int] = None  # (h, w), required if no InputType

    def _out_hw(self, input_type):
        h, w = (input_type[1], input_type[2]) if input_type is not None \
            else self.input_size
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        kh, kw = _pair(self.kernel_size)
        oh, ow = self._out_hw(input_type)
        p = {"W": init_weights(key, (oh * ow, n_in * kh * kw, self.n_out),
                               self.weight_init)}
        if self.has_bias:
            p["b"] = jnp.zeros((oh * ow, self.n_out))
        return p

    def forward(self, params, x, training=False, key=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID")  # [B, C*kh*kw, oh, ow]
        b, ck, oh, ow = patches.shape
        flat = patches.reshape(b, ck, oh * ow).transpose(0, 2, 1)  # [B,P,CK]
        out = jnp.einsum("bpc,pco->bpo", flat, params["W"])
        if self.has_bias:
            out = out + params["b"]
        out = get_activation(self.activation)(out)
        return out.transpose(0, 2, 1).reshape(b, self.n_out, oh, ow)

    def output_type(self, input_type):
        oh, ow = self._out_hw(input_type)
        return (self.n_out, oh, ow)


@dataclasses.dataclass
class LocallyConnected1D(Layer):
    """1D unshared conv over [B, C, T] (reference LocallyConnected1D.java)."""
    n_in: int = 0
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    activation: str = "identity"
    weight_init: str = "xavier"
    has_bias: bool = True

    def _out_t(self, input_type):
        return (input_type[1] - self.kernel_size) // self.stride + 1

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        ot = self._out_t(input_type)
        p = {"W": init_weights(key, (ot, n_in * self.kernel_size, self.n_out),
                               self.weight_init)}
        if self.has_bias:
            p["b"] = jnp.zeros((ot, self.n_out))
        return p

    def forward(self, params, x, training=False, key=None):
        patches = jax.lax.conv_general_dilated_patches(
            x[:, :, :, None], (self.kernel_size, 1), (self.stride, 1),
            "VALID")[:, :, :, 0]  # [B, C*k, ot]
        out = jnp.einsum("bct,tco->bto", patches, params["W"])
        if self.has_bias:
            out = out + params["b"]
        out = get_activation(self.activation)(out)
        return out.transpose(0, 2, 1)

    def output_type(self, input_type):
        return (self.n_out, self._out_t(input_type))


# -- elementwise / shape utilities ----------------------------------------
@dataclasses.dataclass
class PReLULayer(Layer):
    """Learned leaky-ReLU slope (reference conf/layers/PReLULayer.java).

    alpha is per-channel (1-D) or per-position (full batchless shape,
    channels-first — the keras PReLU-without-shared_axes case)."""
    n_in: int = 0  # number of features/channels (inferred)

    def init_params(self, key, input_type):
        n = self.n_in or input_type[0]
        return {"alpha": jnp.zeros((n,)) + 0.25}

    def forward(self, params, x, training=False, key=None):
        a = params["alpha"]
        if a.ndim > 1:
            a = a.reshape((1,) + a.shape)   # broadcast over batch
            return jnp.where(x >= 0, x, a * x)
        shape = [1] * x.ndim
        shape[1 if x.ndim >= 3 else -1] = a.shape[0]
        a = a.reshape(shape)
        return jnp.where(x >= 0, x, a * x)


@dataclasses.dataclass
class ElementWiseMultiplicationLayer(Layer):
    """out = activation(x * w + b) (reference ElementWiseMultiplicationLayer)."""
    n_in: int = 0
    n_out: int = 0
    activation: str = "identity"

    def init_params(self, key, input_type):
        n = self.n_in or input_type[0]
        return {"w": jnp.ones((n,)), "b": jnp.zeros((n,))}

    def forward(self, params, x, training=False, key=None):
        return get_activation(self.activation)(x * params["w"] + params["b"])


@dataclasses.dataclass
class RepeatVector(Layer):
    """[B, F] → [B, F, n] (reference conf/layers/misc/RepeatVector.java)."""
    n: int = 1

    def forward(self, params, x, training=False, key=None):
        return jnp.repeat(x[:, :, None], self.n, axis=2)

    def output_type(self, input_type):
        return (input_type[0], self.n)

    def has_params(self):
        return False


@dataclasses.dataclass
class MaskLayer(Layer):
    """Keras ``Masking`` / reference util/MaskLayer.java analog.

    Identity on activations, but EMITS the timestep keep-mask (True where
    any feature differs from ``mask_value``): MultiLayerNetwork threads it
    into downstream mask-aware RNN layers (``accepts_mask``), which skip
    masked steps Keras-style — state carries through, the emitted output
    repeats the previous valid step, last-step selection lands on the
    last valid step — and into a temporal loss head."""
    mask_value: float = 0.0
    emits_mask = True

    def forward(self, params, x, training=False, key=None):
        # Keras Masking ZEROES masked timesteps in its output (visible to
        # non-mask-aware consumers); for mask_value=0 this is an identity
        keep = jnp.any(x != self.mask_value, axis=1, keepdims=True)
        return x * keep.astype(x.dtype)

    def compute_mask(self, x):
        """[B, F, T] activations -> [B, T] keep-mask."""
        return jnp.any(x != self.mask_value, axis=1)

    def has_params(self):
        return False


@dataclasses.dataclass
class RescaleLayer(Layer):
    """y = x * scale + offset (keras preprocessing Rescaling)."""
    scale: float = 1.0
    offset: float = 0.0

    def forward(self, params, x, training=False, key=None):
        return x * self.scale + self.offset

    def has_params(self):
        return False


@dataclasses.dataclass
class ChannelNormalizationLayer(Layer):
    """Per-channel feature normalization (keras preprocessing
    Normalization with axis=channels): y = (x - mean) / max(sqrt(var),
    eps). mean/variance arrive as imported weights over channel axis 1
    (NHWC h5 weights adapted to the NCHW runtime layout)."""

    def init_params(self, key, input_type):
        c = input_type[0] if input_type else 1
        return {"mean": jnp.zeros((c,)), "variance": jnp.ones((c,))}

    def forward(self, params, x, training=False, key=None):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        mean = params["mean"].reshape(shape)
        std = jnp.maximum(jnp.sqrt(params["variance"].reshape(shape)),
                          1e-7)
        return (x - mean) / std


@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    """(reference conf/layers/SpaceToDepthLayer.java)."""
    block_size: int = 2

    def forward(self, params, x, training=False, key=None):
        b, c, h, w = x.shape
        s = self.block_size
        x = x.reshape(b, c, h // s, s, w // s, s)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(b, c * s * s, h // s, w // s)

    def output_type(self, input_type):
        c, h, w = input_type
        s = self.block_size
        return (c * s * s, h // s, w // s)

    def has_params(self):
        return False


@dataclasses.dataclass
class DepthToSpaceLayer(Layer):
    block_size: int = 2

    def forward(self, params, x, training=False, key=None):
        b, c, h, w = x.shape
        s = self.block_size
        x = x.reshape(b, s, s, c // (s * s), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
        return x.reshape(b, c // (s * s), h * s, w * s)

    def output_type(self, input_type):
        c, h, w = input_type
        s = self.block_size
        return (c // (s * s), h * s, w * s)

    def has_params(self):
        return False


@dataclasses.dataclass
class PermuteLayer(Layer):
    """Permute non-batch dims (reference keras layers/core/KerasPermute
    role; dims are 1-indexed over the feature dims, Keras-style)."""
    dims: tuple = (1,)

    def forward(self, params, x, training=False, key=None):
        return jnp.transpose(x, (0,) + tuple(int(d) for d in self.dims))

    def output_type(self, input_type):
        if input_type is None:
            return None
        return tuple(input_type[d - 1] for d in self.dims)

    def has_params(self):
        return False


@dataclasses.dataclass
class ReshapeLayer(Layer):
    """Reshape the non-batch dims (reference KerasReshape role)."""
    target_shape: tuple = ()

    def forward(self, params, x, training=False, key=None):
        return x.reshape((x.shape[0],) + tuple(int(s)
                                               for s in self.target_shape))

    def output_type(self, input_type):
        return tuple(int(s) for s in self.target_shape)

    def has_params(self):
        return False


# -- dropout/noise variants (reference conf/dropout/) ---------------------
@dataclasses.dataclass
class GaussianDropout(Layer):
    rate: float = 0.5

    def forward(self, params, x, training=False, key=None):
        if training and key is not None:
            return nn_ops.gaussian_dropout(x, self.rate, key, training=True)
        return x

    def has_params(self):
        return False

    def needs_key(self):
        return True


@dataclasses.dataclass
class GaussianNoise(Layer):
    stddev: float = 0.1

    def forward(self, params, x, training=False, key=None):
        if training and key is not None:
            return nn_ops.gaussian_noise(x, self.stddev, key, training=True)
        return x

    def has_params(self):
        return False

    def needs_key(self):
        return True


@dataclasses.dataclass
class AlphaDropout(Layer):
    rate: float = 0.5

    def forward(self, params, x, training=False, key=None):
        if training and key is not None:
            return nn_ops.alpha_dropout(x, self.rate, key, training=True)
        return x

    def has_params(self):
        return False

    def needs_key(self):
        return True


# -- loss heads -----------------------------------------------------------
@dataclasses.dataclass
class CnnLossLayer(Layer):
    """Per-pixel loss on [B, C, H, W] (reference CnnLossLayer.java)."""
    loss: Union[str, Callable] = "mcxent"
    activation: str = "softmax"

    def forward(self, params, x, training=False, key=None):
        # activations apply over the channel axis (axis 1 in NCHW)
        xt = jnp.moveaxis(x, 1, -1)
        return jnp.moveaxis(get_activation(self.activation)(xt), -1, 1)

    def compute_loss(self, labels, output, mask=None):
        c = output.shape[1]
        lab = jnp.moveaxis(labels, 1, -1).reshape(-1, c)
        out = jnp.moveaxis(output, 1, -1).reshape(-1, c)
        m = mask.reshape(-1) if mask is not None else None
        return get_loss(self.loss)(lab, out, m)

    def has_params(self):
        return False


@dataclasses.dataclass
class RnnLossLayer(Layer):
    """Per-timestep loss on [B, C, T] (reference RnnLossLayer.java)."""
    loss: Union[str, Callable] = "mcxent"
    activation: str = "softmax"

    def forward(self, params, x, training=False, key=None):
        xt = jnp.swapaxes(x, 1, 2)
        return jnp.swapaxes(get_activation(self.activation)(xt), 1, 2)

    def compute_loss(self, labels, output, mask=None):
        c = output.shape[1]
        lab = jnp.swapaxes(labels, 1, 2).reshape(-1, c)
        out = jnp.swapaxes(output, 1, 2).reshape(-1, c)
        m = mask.reshape(-1) if mask is not None else None
        return get_loss(self.loss)(lab, out, m)

    def has_params(self):
        return False


@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference CenterLossOutputLayer.java).

    Keeps per-class feature centers as non-trainable state updated by EMA
    (`alpha`), loss = mcxent + lambda/2 * ||f - c_y||^2."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_params(self, key, input_type):
        p = super().init_params(key, input_type)
        n_in = self.n_in or input_type[0]
        p["state_centers"] = jnp.zeros((self.n_out, n_in))
        return p

    def forward(self, params, x, training=False, key=None):
        return super().forward(params, x, training, key)

    def new_state(self, params, x, labels=None):
        """EMA update of class centers toward the batch class means
        (reference CenterLossOutputLayer center update with rate alpha)."""
        centers = params["state_centers"]
        if labels is None:
            return {"state_centers": centers}
        counts = jnp.sum(labels, axis=0)                      # [n_out]
        sums = jnp.einsum("bc,bf->cf", labels, x)             # [n_out, n_in]
        means = sums / jnp.maximum(counts[:, None], 1.0)
        observed = (counts > 0)[:, None]
        new = jnp.where(observed,
                        centers - self.alpha * (centers - means), centers)
        return {"state_centers": new}

    def compute_loss(self, labels, output, mask=None):
        # without features only the softmax term is computable; the full loss
        # goes through compute_loss_ext (called by MLN/CG, which thread the
        # layer's input features through the trace — no hidden state)
        return get_loss(self.loss)(labels, output, mask)

    def compute_loss_ext(self, params, labels, output, features, mask=None):
        """Full center loss: mcxent + lambda/2 * mean ||f - c_y||^2."""
        base = get_loss(self.loss)(labels, output, mask)
        if features is None:
            return base
        cls_centers = jnp.matmul(labels, params["state_centers"])  # [B, n_in]
        center = jnp.mean(jnp.sum((features - cls_centers) ** 2, axis=-1))
        return base + 0.5 * self.lambda_ * center


@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (reference objdetect/Yolo2OutputLayer.java).

    Input [B, A*(5+C), H, W]; labels [B, 4+C, H, W] (reference label format:
    normalized box corners + one-hot class, zero where no object).
    """
    anchors: Sequence[Tuple[float, float]] = ((1.0, 1.0),)
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    def forward(self, params, x, training=False, key=None):
        return x

    def has_params(self):
        return False

    def compute_loss(self, labels, output, mask=None):
        B, _, H, W = output.shape
        A = len(self.anchors)
        C = labels.shape[1] - 4
        pred = output.reshape(B, A, 5 + C, H, W)
        tx, ty = jax.nn.sigmoid(pred[:, :, 0]), jax.nn.sigmoid(pred[:, :, 1])
        tw, th = pred[:, :, 2], pred[:, :, 3]
        conf = jax.nn.sigmoid(pred[:, :, 4])
        cls = jax.nn.softmax(pred[:, :, 5:], axis=2)

        obj = (jnp.sum(labels[:, :4], axis=1, keepdims=True) > 0)  # [B,1,H,W]
        obj = obj.astype(output.dtype)
        # label box center/size from corner format
        x1, y1, x2, y2 = (labels[:, i] for i in range(4))
        cx, cy = (x1 + x2) / 2 * W % 1.0, (y1 + y2) / 2 * H % 1.0
        bw, bh = (x2 - x1) * W, (y2 - y1) * H

        coord = 0.0
        for a, (aw, ah) in enumerate(self.anchors):
            coord = coord + jnp.sum(obj[:, 0] * (
                (tx[:, a] - cx) ** 2 + (ty[:, a] - cy) ** 2
                + (tw[:, a] - jnp.log(jnp.maximum(bw / aw, 1e-6))) ** 2
                + (th[:, a] - jnp.log(jnp.maximum(bh / ah, 1e-6))) ** 2))
        conf_loss = jnp.sum(obj * (conf - 1.0) ** 2) + \
            self.lambda_noobj * jnp.sum((1 - obj) * conf ** 2)
        cls_loss = jnp.sum(obj[:, :, None] *
                           (cls - labels[:, None, 4:]) ** 2)
        n = jnp.maximum(jnp.sum(obj), 1.0)
        return (self.lambda_coord * coord + conf_loss + cls_loss) / n


@dataclasses.dataclass
class Cnn3DLossLayer(Layer):
    """Per-voxel loss on [B, C, D, H, W] (reference Cnn3DLossLayer.java)."""
    loss: Union[str, Callable] = "mcxent"
    activation: str = "softmax"

    def forward(self, params, x, training=False, key=None):
        xt = jnp.moveaxis(x, 1, -1)
        return jnp.moveaxis(get_activation(self.activation)(xt), -1, 1)

    def compute_loss(self, labels, output, mask=None):
        c = output.shape[1]
        lab = jnp.moveaxis(labels, 1, -1).reshape(-1, c)
        out = jnp.moveaxis(output, 1, -1).reshape(-1, c)
        return get_loss(self.loss)(lab, out,
                                   mask.reshape(-1) if mask is not None else None)

    def has_params(self):
        return False


# -- attention ------------------------------------------------------------
@dataclasses.dataclass
class LearnedSelfAttentionLayer(Layer):
    """Attention with learned queries → fixed n_queries output timesteps
    (reference LearnedSelfAttentionLayer.java)."""
    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    n_queries: int = 1
    head_size: int = None
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        hs = self.head_size or (self.n_out // self.n_heads)
        ks = jax.random.split(key, 5)
        return {"Q": init_weights(ks[0], (self.n_queries, n_in), self.weight_init),
                "Wq": init_weights(ks[1], (n_in, self.n_heads, hs), self.weight_init),
                "Wk": init_weights(ks[2], (n_in, self.n_heads, hs), self.weight_init),
                "Wv": init_weights(ks[3], (n_in, self.n_heads, hs), self.weight_init),
                "Wo": init_weights(ks[4], (self.n_heads * hs, self.n_out),
                                   self.weight_init)}

    def forward(self, params, x, training=False, key=None):
        xt = jnp.swapaxes(x, 1, 2)  # [B, T, F]
        q = jnp.broadcast_to(params["Q"],
                             (x.shape[0],) + params["Q"].shape)  # [B, nq, F]
        out = nn_ops.multi_head_dot_product_attention(
            q, xt, xt, params["Wq"], params["Wk"], params["Wv"], params["Wo"])
        return jnp.swapaxes(out, 1, 2)  # [B, n_out, n_queries]

    def output_type(self, input_type):
        return (self.n_out, self.n_queries)


@dataclasses.dataclass
class RecurrentAttentionLayer(Layer):
    """Recurrent cell whose input is augmented with attention over the full
    sequence (reference RecurrentAttentionLayer.java) — lax.scan over time,
    attention via one batched matmul per step."""
    n_in: int = 0
    n_out: int = 0
    activation: str = "tanh"
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        k1, k2, k3 = jax.random.split(key, 3)
        return {"Wx": init_weights(k1, (n_in, self.n_out), self.weight_init),
                "Wh": init_weights(k2, (self.n_out, self.n_out), self.weight_init),
                "Wa": init_weights(k3, (n_in, self.n_out), self.weight_init),
                "b": jnp.zeros((self.n_out,))}

    def forward(self, params, x, training=False, key=None):
        xt = jnp.swapaxes(x, 1, 2)  # [B, T, F]
        act = get_activation(self.activation)
        proj = jnp.einsum("btf,fo->bto", xt, params["Wa"])  # attention values

        def step(h, x_t):
            scores = jnp.einsum("bo,bto->bt", h, proj) / math.sqrt(self.n_out)
            attn = jax.nn.softmax(scores, axis=-1)
            a_t = jnp.einsum("bt,bto->bo", attn, proj)
            h_new = act(x_t @ params["Wx"] + h @ params["Wh"] + a_t
                        + params["b"])
            return h_new, h_new

        h0 = jnp.zeros((x.shape[0], self.n_out), x.dtype)
        _, h_seq = jax.lax.scan(step, h0, jnp.swapaxes(xt, 0, 1))
        return jnp.transpose(h_seq, (1, 2, 0))  # [B, n_out, T]

    def output_type(self, input_type):
        return (self.n_out, input_type[1])


# -- frozen (transfer learning) -------------------------------------------
@dataclasses.dataclass
class FrozenLayer(Layer):
    """Wrapper excluding inner params from training (reference
    layers/FrozenLayer.java). Inner params are stored under `state_` keys,
    which every network treats as non-trainable."""
    underlying: Layer = None

    PREFIX = "state_frozen__"

    def init_params(self, key, input_type):
        inner = self.underlying.init_params(key, input_type)
        return {self.PREFIX + k: v for k, v in inner.items()}

    @classmethod
    def wrap_params(cls, inner_params):
        """Freeze an existing param dict (used by TransferLearning)."""
        return {cls.PREFIX + k if not k.startswith(cls.PREFIX) else k: v
                for k, v in inner_params.items()}

    def forward(self, params, x, training=False, key=None):
        inner = {k[len(self.PREFIX):]: v for k, v in params.items()
                 if k.startswith(self.PREFIX)}
        # frozen layers run in inference mode (reference FrozenLayer semantics)
        return self.underlying.forward(inner, x, training=False, key=key)

    def output_type(self, input_type):
        return self.underlying.output_type(input_type)

    def has_params(self):
        return self.underlying.has_params()


# -- variational autoencoder ----------------------------------------------
@dataclasses.dataclass
class VariationalAutoencoder(Layer):
    """VAE pretrain layer (reference layers/variational/VariationalAutoencoder.java).

    forward() yields the latent mean (the reference's supervised-path
    behavior); elbo_loss() is the unsupervised pretrain objective with a
    gaussian reconstruction distribution.
    """
    n_in: int = 0
    n_out: int = 0                     # latent size
    encoder_layer_sizes: Sequence[int] = (64,)
    decoder_layer_sizes: Sequence[int] = (64,)
    activation: str = "lrelu"
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        p = {}
        sizes = [n_in] + list(self.encoder_layer_sizes)
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            p[f"eW{i}"] = init_weights(k, (a, b), self.weight_init)
            p[f"eb{i}"] = jnp.zeros((b,))
        key, k1, k2 = jax.random.split(key, 3)
        p["Wmu"] = init_weights(k1, (sizes[-1], self.n_out), self.weight_init)
        p["bmu"] = jnp.zeros((self.n_out,))
        p["Wlv"] = init_weights(k2, (sizes[-1], self.n_out), self.weight_init)
        p["blv"] = jnp.zeros((self.n_out,))
        dsizes = [self.n_out] + list(self.decoder_layer_sizes)
        for i, (a, b) in enumerate(zip(dsizes[:-1], dsizes[1:])):
            key, k = jax.random.split(key)
            p[f"dW{i}"] = init_weights(k, (a, b), self.weight_init)
            p[f"db{i}"] = jnp.zeros((b,))
        key, k = jax.random.split(key)
        p["Wout"] = init_weights(k, (dsizes[-1], n_in), self.weight_init)
        p["bout"] = jnp.zeros((n_in,))
        return p

    def _encode(self, params, x):
        act = get_activation(self.activation)
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        mu = h @ params["Wmu"] + params["bmu"]
        logvar = h @ params["Wlv"] + params["blv"]
        return mu, logvar

    def _decode(self, params, z):
        act = get_activation(self.activation)
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["Wout"] + params["bout"]

    def forward(self, params, x, training=False, key=None):
        return self._encode(params, x)[0]

    def reconstruct(self, params, x):
        return self._decode(params, self._encode(params, x)[0])

    def elbo_loss(self, params, x, key):
        mu, logvar = self._encode(params, x)
        eps = jax.random.normal(key, mu.shape, mu.dtype)
        z = mu + jnp.exp(0.5 * logvar) * eps
        recon = self._decode(params, z)
        rec_loss = jnp.sum((recon - x) ** 2, axis=-1)
        kl = -0.5 * jnp.sum(1 + logvar - mu ** 2 - jnp.exp(logvar), axis=-1)
        return jnp.mean(rec_loss + kl)

    def output_type(self, input_type):
        return (self.n_out,)

    def needs_key(self):
        return False


# -- capsules -------------------------------------------------------------
def _squash(s, axis=-1, eps=1e-8):
    n2 = jnp.sum(s * s, axis=axis, keepdims=True)
    return (n2 / (1 + n2)) * s / jnp.sqrt(n2 + eps)


@dataclasses.dataclass
class PrimaryCapsules(Layer):
    """Conv → capsule reshape + squash (reference conf/layers/PrimaryCapsules.java)."""
    n_in: int = 0
    capsules: int = 8          # capsules per spatial position
    capsule_dimensions: int = 8
    kernel_size: Sequence[int] = (9, 9)
    stride: Sequence[int] = (2, 2)
    weight_init: str = "relu"

    def init_params(self, key, input_type):
        n_in = self.n_in or input_type[0]
        kh, kw = _pair(self.kernel_size)
        cout = self.capsules * self.capsule_dimensions
        return {"W": init_weights(key, (kh, kw, n_in, cout), self.weight_init),
                "b": jnp.zeros((cout,))}

    def forward(self, params, x, training=False, key=None):
        out = conv_ops.conv2d(x, params["W"], params["b"],
                              strides=_pair(self.stride), padding="VALID",
                              data_format="NCHW")
        b = out.shape[0]
        caps = out.reshape(b, self.capsule_dimensions, -1)
        caps = jnp.swapaxes(caps, 1, 2)  # [B, n_caps_total, dim]
        return _squash(caps)

    def output_type(self, input_type):
        c, h, w = input_type
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return (self.capsules * oh * ow, self.capsule_dimensions)


@dataclasses.dataclass
class CapsuleLayer(Layer):
    """Dynamic-routing capsule layer (reference conf/layers/CapsuleLayer.java)."""
    input_capsules: int = 0
    input_capsule_dimensions: int = 0
    capsules: int = 10
    capsule_dimensions: int = 16
    routings: int = 3
    weight_init: str = "xavier"

    def init_params(self, key, input_type):
        n_caps = self.input_capsules or input_type[0]
        in_dim = self.input_capsule_dimensions or input_type[1]
        return {"W": init_weights(
            key, (n_caps, self.capsules, self.capsule_dimensions, in_dim),
            self.weight_init)}

    def forward(self, params, x, training=False, key=None):
        # x: [B, in_caps, in_dim]; prediction vectors u_hat [B,in,out,out_dim]
        u_hat = jnp.einsum("bid,iokd->biok", x, params["W"])
        b_logits = jnp.zeros(u_hat.shape[:3], x.dtype)
        # fixed small routing iteration count → unrolled, XLA-friendly
        for _ in range(self.routings):
            c = jax.nn.softmax(b_logits, axis=2)
            s = jnp.einsum("bio,biok->bok", c, u_hat)
            v = _squash(s)
            b_logits = b_logits + jnp.einsum("biok,bok->bio", u_hat, v)
        return v

    def output_type(self, input_type):
        return (self.capsules, self.capsule_dimensions)


@dataclasses.dataclass
class CapsuleStrengthLayer(Layer):
    """Capsule length per class (reference CapsuleStrengthLayer.java)."""

    def forward(self, params, x, training=False, key=None):
        return jnp.sqrt(jnp.sum(x * x, axis=-1) + 1e-8)

    def output_type(self, input_type):
        return (input_type[0],)

    def has_params(self):
        return False
