from .config import (InputType, MultiLayerConfiguration,  # noqa: F401
                     NeuralNetConfiguration)
