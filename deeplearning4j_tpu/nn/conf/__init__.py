from .config import (InputType, MultiLayerConfiguration,  # noqa: F401
                     NeuralNetConfiguration)
from .constraints import (MaxNormConstraint, MinMaxNormConstraint,  # noqa: F401
                          NonNegativeConstraint, UnitNormConstraint)
from .weightnoise import DropConnect, WeightNoise  # noqa: F401
