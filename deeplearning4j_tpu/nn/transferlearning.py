"""Transfer learning: fine-tune configs, frozen feature extractors, head
replacement.

Reference: `deeplearning4j-nn/.../transferlearning/TransferLearning.java`
(Builder + GraphBuilder), `FineTuneConfiguration.java`, plus
`FrozenLayer` wrappers — VERDICT round-1 missing #9.

TPU note: freezing is purely structural (params moved under `state_*` keys,
which every train step already excludes from grads) — no special-cased
backward pass; XLA simply never computes those gradients.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax

from ..learning import IUpdater
from .conf import layers as L
from .conf.config import MultiLayerConfiguration
from .conf.layers_extra import FrozenLayer
from .multilayer import MultiLayerNetwork


@dataclasses.dataclass
class FineTuneConfiguration:
    """Reference FineTuneConfiguration: overrides applied net-wide."""
    updater: Optional[IUpdater] = None
    seed: Optional[int] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def updater(self, u):
            self._kw["updater"] = u
            return self

        def seed(self, s):
            self._kw["seed"] = int(s)
            return self

        def l1(self, v):
            self._kw["l1"] = float(v)
            return self

        def l2(self, v):
            self._kw["l2"] = float(v)
            return self

        def weight_decay(self, v):
            self._kw["weight_decay"] = float(v)
            return self

        def build(self) -> "FineTuneConfiguration":
            return FineTuneConfiguration(**self._kw)

    @staticmethod
    def builder() -> "FineTuneConfiguration.Builder":
        return FineTuneConfiguration.Builder()

    def apply_to(self, conf: MultiLayerConfiguration):
        if self.updater is not None:
            conf.updater = self.updater
        if self.seed is not None:
            conf.seed = self.seed
        if self.l1 is not None:
            conf.l1 = self.l1
        if self.l2 is not None:
            conf.l2 = self.l2
        if self.weight_decay is not None:
            conf.weight_decay = self.weight_decay


class TransferLearning:
    """Reference TransferLearning entry: `TransferLearning.Builder(net)`
    (MultiLayerNetwork) / `TransferLearning.GraphBuilder(graph)`."""

    class GraphBuilder:
        """ComputationGraph transfer learning (reference
        TransferLearning.GraphBuilder): freeze up to a vertex, replace a
        layer vertex's nOut, fine-tune config."""

        def __init__(self, graph):
            graph._check_init()
            self._src = graph
            self._ftc: Optional[FineTuneConfiguration] = None
            self._frozen_until: Optional[str] = None
            self._nout_replace = {}

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def set_feature_extractor(self, vertex_name: str):
            """Freeze vertex_name and everything topologically before it."""
            self._frozen_until = vertex_name
            return self

        def n_out_replace(self, vertex_name: str, n_out: int,
                          weight_init: str = "xavier"):
            self._nout_replace[vertex_name] = (int(n_out), weight_init)
            return self

        def build(self):
            import jax as _jax
            from .graph.computation_graph import (ComputationGraph,
                                                  LayerVertex)
            src = self._src
            conf = copy.deepcopy(src.conf)
            params = {n: dict(p) for n, p in src._params.items()}

            # nOut replacement re-inits that vertex + direct consumers
            types = src.conf.vertex_output_types()
            key = _jax.random.key(conf.seed + 13)
            for name, (n_out, w_init) in self._nout_replace.items():
                v = conf.vertices[name]
                layer = v.layer if isinstance(v, LayerVertex) else v
                layer.n_out = n_out
                if hasattr(layer, "weight_init"):
                    layer.weight_init = w_init
                in_types = [types.get(i)
                            for i in conf.vertex_inputs[name]]
                key, k1 = _jax.random.split(key)
                params[name] = v.init_params(k1, in_types)
                out_type = layer.output_type(in_types[0]
                                             if in_types else None)
                for consumer, ins in conf.vertex_inputs.items():
                    if name in ins and consumer in conf.vertices:
                        cv = conf.vertices[consumer]
                        cl = cv.layer if isinstance(cv, LayerVertex) else cv
                        if hasattr(cl, "n_in"):
                            cl.n_in = n_out
                        if cv.has_params():
                            key, k2 = _jax.random.split(key)
                            params[consumer] = cv.init_params(
                                k2, [out_type])

            # freeze the feature extractor sub-DAG
            if self._frozen_until is not None:
                order = conf.topological_order()
                cutoff = order.index(self._frozen_until)
                for name in order[:cutoff + 1]:
                    if name in conf.inputs or name not in conf.vertices:
                        continue
                    v = conf.vertices[name]
                    if isinstance(v, LayerVertex) and v.layer.has_params():
                        v.layer = FrozenLayer(underlying=v.layer)
                        params[name] = FrozenLayer.wrap_params(params[name])

            if self._ftc is not None:
                self._ftc.apply_to(conf)
            net = ComputationGraph(conf)
            net.init(params=params)
            return net

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            net._check_init()
            self._src = net
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._nout_replace = {}     # layer idx -> (n_out, weight_init)
            self._remove_from: Optional[int] = None
            self._appended: List[L.Layer] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference setFeatureExtractor)."""
            self._freeze_until = int(layer_idx)
            return self

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: str = "xavier"):
            """Replace a layer's output size, re-initializing its params and
            the next layer's input weights (reference nOutReplace)."""
            self._nout_replace[int(layer_idx)] = (int(n_out), weight_init)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            self._remove_from = len(self._src.layers) - int(n)
            return self

        def add_layer(self, layer: L.Layer):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._src
            layers = [copy.deepcopy(l) for l in src.layers]
            params = [dict(p) for p in src._params]
            if self._remove_from is not None:
                layers = layers[:self._remove_from]
                params = params[:self._remove_from]

            # nOut replacement: re-init that layer + fix next layer's n_in
            types = src.conf.layer_input_types()
            key = jax.random.key(src.conf.seed + 7)
            for idx, (n_out, w_init) in sorted(self._nout_replace.items()):
                if idx >= len(layers):
                    continue
                layer = layers[idx]
                layer.n_out = n_out
                if hasattr(layer, "weight_init"):
                    layer.weight_init = w_init
                key, k1, k2 = jax.random.split(key, 3)
                params[idx] = layer.init_params(k1, types[idx])
                if idx + 1 < len(layers):
                    nxt = layers[idx + 1]
                    if hasattr(nxt, "n_in"):
                        nxt.n_in = n_out
                    params[idx + 1] = nxt.init_params(
                        k2, layer.output_type(types[idx]))

            # appended layers initialize from the current tail's output type
            cur_type = None
            if layers:
                cur_type = layers[-1].output_type(
                    types[len(layers) - 1] if len(layers) - 1 < len(types)
                    else None)
            for new_layer in self._appended:
                key, k = jax.random.split(key)
                layers.append(new_layer)
                params.append(new_layer.init_params(k, cur_type)
                              if new_layer.has_params() else {})
                cur_type = new_layer.output_type(cur_type)

            # freeze the feature extractor
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    if layers[i].has_params():
                        layers[i] = FrozenLayer(underlying=layers[i])
                        params[i] = FrozenLayer.wrap_params(params[i])

            conf = copy.deepcopy(src.conf)
            conf.layers = layers
            conf.preprocessors = {i: p for i, p in conf.preprocessors.items()
                                  if i < len(layers)}
            if self._ftc is not None:
                self._ftc.apply_to(conf)
            net = MultiLayerNetwork(conf)
            net.init(params=params)
            return net
