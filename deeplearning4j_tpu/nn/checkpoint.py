"""Sharded checkpointing (orbax-backed) for MLN/ComputationGraph/pytrees.

Reference: `ModelSerializer.java` (zip of config+params+updater) and
`CheckpointListener.java` retention policies. SURVEY §5 names orbax-style
*sharded* checkpointing as the behavior to preserve on TPU: the reference's
host-gather zip cannot survive real multi-host model sizes — each host must
write only its own shards, and restore must re-shard onto a possibly
*different* mesh (elastic restart).

This module wraps `orbax.checkpoint.CheckpointManager`:
- save: per-shard OCDBT write of params + updater state + iteration/epoch
- restore: target shardings come from the freshly-distributed net, so a
  checkpoint taken on mesh A restores onto mesh B (reshape/resize) exactly
- retention: keep-last-K like the reference CheckpointListener
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _manager(directory: str, keep_last: Optional[int] = None):
    import orbax.checkpoint as ocp
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep_last,
                                             create=True))


def _net_state(net) -> dict:
    state = {"params": net._params, "iteration": net._iteration,
             "epoch": net._epoch}
    if net._updater_state is not None:
        state["updater"] = net._updater_state
    return state


class ShardedCheckpointer:
    """Save/restore a network's full training state with sharded I/O."""

    def __init__(self, directory: str, keep_last: Optional[int] = None):
        self.directory = directory
        self._mngr = _manager(directory, keep_last)

    # -- generic pytree API ----------------------------------------------
    def save_tree(self, step: int, tree: Any):
        import orbax.checkpoint as ocp
        self._mngr.save(step, args=ocp.args.StandardSave(tree))
        self._mngr.wait_until_finished()

    def restore_tree(self, step: Optional[int] = None,
                     target: Any = None) -> Any:
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if target is None:
            return self._mngr.restore(step)
        abstract = jax.tree_util.tree_map(_abstractify, target)
        return self._mngr.restore(step,
                                  args=ocp.args.StandardRestore(abstract))

    # -- network API ------------------------------------------------------
    def save(self, step: int, net):
        """Checkpoint params + updater state + iteration (sharded write)."""
        self.save_tree(step, _net_state(net))

    def restore(self, net, step: Optional[int] = None):
        """Restore in-place onto the net's CURRENT placement — call
        `net.distribute(new_mesh)` first to restore onto a reshaped mesh."""
        state = self.restore_tree(step, target=_net_state(net))
        net._params = state["params"]
        if "updater" in state:
            net._updater_state = state["updater"]
        net._iteration = int(state["iteration"])
        net._epoch = int(state["epoch"])
        net._train_step = None  # recompile against restored placements
        return net

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def close(self):
        self._mngr.close()


def _abstractify(x):
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


class ShardedCheckpointListener:
    """CheckpointListener variant writing sharded orbax checkpoints
    (reference `optimize/listeners/CheckpointListener.java` policies)."""

    def __init__(self, directory: str, save_every_n_iterations: int = None,
                 save_every_n_epochs: int = None, keep_last: int = 3):
        self.ckpt = ShardedCheckpointer(directory, keep_last=keep_last)
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs

    def iteration_done(self, model, iteration, loss=None):
        if self.every_iter and iteration > 0 and \
                iteration % self.every_iter == 0:
            self.ckpt.save(iteration, model)

    def on_epoch_end(self, epoch, model):
        if self.every_epoch and epoch % self.every_epoch == 0:
            self.ckpt.save(model._iteration, model)
