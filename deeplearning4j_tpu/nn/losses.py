"""Loss functions for the layer API.

Reference: `org/nd4j/linalg/lossfunctions/LossFunctions.java` enum + ILossFunction
impls. Names match the reference (MCXENT, MSE, XENT, ...). Each loss is
`f(labels, preactivation_output_after_activation, mask) -> scalar mean loss`.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp


def _masked_mean(per_example, mask):
    if mask is None:
        return jnp.mean(per_example)
    while mask.ndim < per_example.ndim:
        mask = mask[..., None]
    mask = jnp.broadcast_to(mask, per_example.shape)
    return jnp.sum(per_example * mask) / jnp.maximum(jnp.sum(mask), 1e-12)


def mcxent(labels, output, mask=None, eps=1e-7):
    """Multi-class cross entropy on softmax output (reference LossMCXENT)."""
    per = -jnp.sum(labels * jnp.log(output + eps), axis=-1)
    return _masked_mean(per, mask)


def xent(labels, output, mask=None, eps=1e-7):
    """Binary cross entropy on sigmoid output (reference LossBinaryXENT)."""
    per = -(labels * jnp.log(output + eps) + (1 - labels) * jnp.log(1 - output + eps))
    return _masked_mean(per, mask)


def mse(labels, output, mask=None):
    per = jnp.mean(jnp.square(labels - output), axis=-1)
    return _masked_mean(per, mask)


def l1(labels, output, mask=None):
    per = jnp.mean(jnp.abs(labels - output), axis=-1)
    return _masked_mean(per, mask)


def l2(labels, output, mask=None):
    per = jnp.sum(jnp.square(labels - output), axis=-1)
    return _masked_mean(per, mask)


def hinge(labels, output, mask=None):
    signed = 2 * labels - 1
    per = jnp.mean(jnp.maximum(0.0, 1.0 - signed * output), axis=-1)
    return _masked_mean(per, mask)


def squared_hinge(labels, output, mask=None):
    signed = 2 * labels - 1
    per = jnp.mean(jnp.square(jnp.maximum(0.0, 1.0 - signed * output)), axis=-1)
    return _masked_mean(per, mask)


def poisson(labels, output, mask=None, eps=1e-7):
    per = jnp.mean(output - labels * jnp.log(output + eps), axis=-1)
    return _masked_mean(per, mask)


def cosine_proximity(labels, output, mask=None):
    num = jnp.sum(labels * output, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(output, axis=-1)
    return _masked_mean(-num / jnp.maximum(den, 1e-12), mask)


def kld(labels, output, mask=None, eps=1e-7):
    per = jnp.sum(labels * (jnp.log(labels + eps) - jnp.log(output + eps)), axis=-1)
    return _masked_mean(per, mask)


def mean_absolute_percentage_error(labels, output, mask=None, eps=1e-7):
    per = jnp.mean(jnp.abs((labels - output) / (jnp.abs(labels) + eps)), axis=-1) * 100
    return _masked_mean(per, mask)


def mean_squared_logarithmic_error(labels, output, mask=None):
    per = jnp.mean(jnp.square(jnp.log1p(labels) - jnp.log1p(output)), axis=-1)
    return _masked_mean(per, mask)


def negative_log_likelihood(labels, output, mask=None, eps=1e-7):
    return mcxent(labels, output, mask, eps)


def wasserstein(labels, output, mask=None):
    return _masked_mean(jnp.mean(labels * output, axis=-1), mask)


def sparse_mcxent(labels, output, mask=None, eps=1e-7):
    """labels are int class indices (reference LossSparseMCXENT)."""
    lp = jnp.log(output + eps)
    per = -jnp.take_along_axis(lp, labels[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    return _masked_mean(per, mask)


_LOSSES = {
    "mcxent": mcxent,
    "negativeloglikelihood": negative_log_likelihood,
    "xent": xent,
    "mse": mse,
    "squared_loss": mse,
    "l1": l1,
    "mae": l1,
    "l2": l2,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "kl_divergence": kld,
    "reconstruction_crossentropy": xent,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "wasserstein": wasserstein,
    "sparse_mcxent": sparse_mcxent,
}


def get_loss(loss: Union[str, Callable]) -> Callable:
    if callable(loss):
        return loss
    try:
        return _LOSSES[loss.lower()]
    except KeyError:
        raise ValueError(f"unknown loss {loss!r}; known: {sorted(_LOSSES)}") \
            from None
