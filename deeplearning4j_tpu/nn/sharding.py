"""Layer-API sharding: per-layer parameter PartitionSpecs over the Mesh.

Closes VERDICT round-1 weak #4: TP/FSDP existed only inside the hand-built
BERT (`models/bert.py`); the DL4J-parity surface — MultiLayerNetwork /
ComputationGraph — could not use tp>1/fsdp>1 meshes at all.

TPU-first design: rather than hand-writing Megatron column/row-parallel
layer variants (the CUDA-framework pattern), every layer exposes a
PartitionSpec rule for its parameters; `net.distribute(mesh)` places params
with those NamedShardings and shards the batch over (data, fsdp). The
*same* jitted train step then compiles under GSPMD, which propagates the
shardings through the forward/backward and inserts the ICI collectives —
the "annotate shardings, let XLA partition" recipe. Numerics are identical
to single-device execution (one logical program).

Reference counterpart: none — the reference is DP-only (SURVEY §2.4); this
is the TPU-first differentiator demanded there.
"""
from __future__ import annotations

import math
from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA, FSDP, TENSOR


def default_leaf_spec(key: str, arr) -> P:
    """Heuristic spec: replicate state/bias/small params; matrices get
    row-FSDP + column-TP (Dense W (in,out) -> P('fsdp','tensor'))."""
    if key.startswith("state_") or getattr(arr, "ndim", 0) < 2:
        return P()
    nd = arr.ndim
    return P(*((FSDP,) + (None,) * (nd - 2) + (TENSOR,)))


def conv_leaf_spec(key: str, arr) -> P:
    """Conv kernels are HWIO: shard in-channels on fsdp, out-channels on
    tensor; spatial dims replicated."""
    if key.startswith("state_") or getattr(arr, "ndim", 0) < 2:
        return P()
    if arr.ndim == 4:
        return P(None, None, FSDP, TENSOR)
    if arr.ndim == 5:
        return P(None, None, None, FSDP, TENSOR)
    return default_leaf_spec(key, arr)


def layer_param_specs(layer, params):
    """Spec pytree matching `params` (handles nested dicts, e.g.
    Bidirectional's fwd/bwd sub-dicts). Layers may override `param_specs`."""
    rule = getattr(layer, "param_specs", None)
    if callable(rule):
        custom = rule(params)
        if custom is not None:
            return custom
    leaf_rule = conv_leaf_spec if _is_conv_like(layer) else default_leaf_spec

    def walk(prefix, node):
        if isinstance(node, dict):
            return {k: walk(k, v) for k, v in node.items()}
        return leaf_rule(prefix, node)

    return {k: walk(k, v) for k, v in params.items()}


def _is_conv_like(layer) -> bool:
    from .conf import layers as L
    return isinstance(layer, L.ConvolutionLayer)


def valid_sharding(mesh: Mesh, spec: P, shape) -> NamedSharding:
    """NamedSharding with divisibility fallback: any spec axis whose mesh
    size does not divide the dim is dropped (replicated) — sharding is an
    optimization, never a correctness constraint."""
    cleaned = []
    for i, ax in enumerate(tuple(spec)):
        if ax is None or i >= len(shape):
            cleaned.append(None)
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        size = math.prod(mesh.shape[a] for a in axes)
        cleaned.append(ax if size > 1 and shape[i] % size == 0 else None)
    return NamedSharding(mesh, P(*cleaned))


def shard_layer_params(mesh: Mesh, layer, params):
    """Place one layer's param dict according to its specs."""
    specs = layer_param_specs(layer, params)

    def place(node, spec):
        if isinstance(node, dict):
            return {k: place(v, spec[k]) for k, v in node.items()}
        return jax.device_put(node, valid_sharding(mesh, spec, node.shape))

    return {k: place(v, specs[k]) for k, v in params.items()}


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch axis over data(+fsdp) — ZeRO-style: fsdp contributes to DP for
    activations while sharding params."""
    spec = []
    if mesh.shape.get(DATA, 1) > 1 or mesh.shape.get(FSDP, 1) > 1:
        spec = [(DATA, FSDP)]
    return NamedSharding(mesh, P(*spec))


def shard_batch_value(mesh: Mesh, x):
    sh = batch_sharding(mesh)
    n = math.prod(mesh.shape[a] for a in (DATA, FSDP))
    if x.shape and x.shape[0] % n == 0:
        return jax.device_put(x, sh)
    return jax.device_put(x, NamedSharding(mesh, P()))
