"""Runtime services: memory-workspace shims (the XLA-arena-backed
MemoryWorkspace API surface, `workspace.py`), the shape-bucketed compiled
inference engine (`inference.py`), the KV-cached generative decode engine
with continuous batching (`generation.py`), and the persistent AOT
executable cache (`compile_cache.py`) that makes process restarts start
warm."""
from . import compile_cache
from .generation import DecodeEngine, is_generative_model, sample_tokens
from .inference import (InferenceEngine, bucket_for, bucket_ladder,
                        counted_jit, maybe_pad_tree, pad_batch, slice_batch)
from .workspace import (DummyWorkspace, LayerWorkspaceMgr, MemoryWorkspace,
                        Nd4jWorkspaceManager, WorkspaceConfiguration,
                        workspace_manager)

__all__ = ["DummyWorkspace", "LayerWorkspaceMgr", "MemoryWorkspace",
           "Nd4jWorkspaceManager", "WorkspaceConfiguration",
           "workspace_manager", "InferenceEngine", "DecodeEngine",
           "is_generative_model", "sample_tokens", "bucket_ladder",
           "bucket_for", "pad_batch", "slice_batch", "maybe_pad_tree",
           "counted_jit", "compile_cache"]
