"""Runtime services: memory-workspace shims (the XLA-arena-backed
MemoryWorkspace API surface). See `workspace.py`."""
from .workspace import (DummyWorkspace, LayerWorkspaceMgr, MemoryWorkspace,
                        Nd4jWorkspaceManager, WorkspaceConfiguration,
                        workspace_manager)

__all__ = ["DummyWorkspace", "LayerWorkspaceMgr", "MemoryWorkspace",
           "Nd4jWorkspaceManager", "WorkspaceConfiguration",
           "workspace_manager"]
