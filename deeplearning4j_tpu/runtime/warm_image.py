"""``python -m deeplearning4j_tpu.runtime.warm_image`` — pre-bake a
model's full executable ladder into a relocatable artifact directory.

An autoscaling fleet's worst compile bill comes due at the worst time:
a traffic spike spawns replica N+1, which pays a full XLA compile per
bucket before it can serve. The push-on-drain / pull-on-boot flow
(``serving.lifecycle``) amortizes that across *running* replicas, but a
brand-new cluster or CI image has no predecessor to inherit from. This
CLI closes that gap: bake once at image-build time, serve warm forever.

The bake runs the exact warmup the serving path runs — for predict
models the engine's bucket ladder against an example request, for
generative models the full prefill ladder x batch ladder + decode step
(``DecodeEngine.warmup``) — with the compile cache pointed at the
output directory in the **remote-store layout**::

    <output>/objects/<aa>/<sha>.bin|.json   content-addressed executables
    <output>/manifests/<name>.warmup.json   warmup manifest (predict)
    <output>/xla/...                        jax backstop (accelerators)

Because the layout is exactly what :class:`~.compile_cache.RemoteStore`
reads, deployment is one env var: bake into the CI image (or push the
directory to the bucket your fleet mounts) and point
``DL4J_TPU_REMOTE_CACHE`` at it — every replica's boot-time pull
(``lifecycle.restore_on_boot``) then downloads the ladder instead of
compiling it. The artifact is relocatable: cache keys are content
hashes of the lowered program + platform, never absolute paths.

Bake on hardware matching the fleet (platform, device kind, device
count, jax version are all folded into the cache key — a CPU bake warms
nothing on TPU). Donated-KV decode steps are raw-store-ineligible by
design (see ``compile_cache``); on accelerators the baked ``xla/``
backstop still covers them, on CPU they recompile on boot — bounded at
one prefill per bucket plus one decode executable.

Example::

    python -m deeplearning4j_tpu.runtime.warm_image \\
        --model myproj.models:build_classifier \\
        --example-shape 1,64 --output /artifacts/classifier \\
        --name classifier

where ``build_classifier()`` returns a model (or ``(model, example)``).
"""
from __future__ import annotations

import argparse
import importlib
import json
import logging
import os
import sys
import time
from typing import Optional, Sequence

from ..common.environment import SystemProperties, environment
from . import compile_cache

log = logging.getLogger(__name__)


def _load_factory(spec: str):
    """``pkg.module:factory`` -> the callable."""
    mod_name, sep, attr = spec.partition(":")
    if not sep or not attr:
        raise ValueError(
            f"--model must look like pkg.module:factory, got {spec!r}")
    mod = importlib.import_module(mod_name)
    try:
        return getattr(mod, attr)
    except AttributeError:
        raise ValueError(f"{mod_name} has no attribute {attr!r}") from None


def bake(model, example=None, *, output: str, name: str = "model",
         batch_sizes: Optional[Sequence[int]] = None,
         max_batch: Optional[int] = None,
         generative: bool = False) -> dict:
    """Warm ``model``'s executable ladder into ``output`` (remote-store
    layout) and return a bake summary. Programmatic core of the CLI —
    safe to call from build scripts and tests. The process compile-cache
    conf is redirected at ``output`` for the duration and restored
    after."""
    env = environment()
    saved = {p: env.property_override(p)
             for p in (SystemProperties.CACHE_DIR,
                       SystemProperties.REMOTE_CACHE,
                       SystemProperties.CACHE_TIER)}
    output = os.path.abspath(output)
    os.makedirs(output, exist_ok=True)
    engine = None
    t0 = time.perf_counter()
    try:
        # tier=remote: entries land content-addressed under
        # <output>/objects — the exact layout DL4J_TPU_REMOTE_CACHE
        # consumers read. base_dir still points at output so the jax
        # backstop (accelerators) bakes into <output>/xla alongside.
        env.set_cache_dir(output)
        env.set_remote_cache(output)
        env.set_cache_tier("remote")
        compile_cache.reset_cache()
        if compile_cache.cache() is None:
            raise RuntimeError(f"output dir {output} is not writable as "
                               "a compile cache")
        if generative:
            from .generation import DecodeEngine
            engine = DecodeEngine(model, model_name=name)
            buckets = engine.warmup()
        else:
            from .inference import InferenceEngine
            engine = InferenceEngine(model, max_batch=max_batch)
            if example is None:
                raise ValueError("predict models need an example "
                                 "(--example-shape) to fix input shapes")
            buckets = engine.warmup(example, batch_sizes=batch_sizes)
            manifest_dir = os.path.join(output, "manifests")
            os.makedirs(manifest_dir, exist_ok=True)
            engine.save_manifest(os.path.join(
                manifest_dir, f"{name}.warmup.json"))
        inv = compile_cache.inventory()
        return {"name": name, "output": output,
                "generative": bool(generative),
                "buckets": list(buckets),
                "entries": inv.get("entry_count", 0),
                "payload_bytes": inv.get("total_payload_bytes", 0),
                "stats": inv.get("stats", {}),
                "bake_seconds": round(time.perf_counter() - t0, 3)}
    finally:
        if engine is not None:
            try:
                engine.close(timeout_s=10.0)
            except Exception:
                log.debug("engine close after bake failed", exc_info=True)
        for prop, value in saved.items():
            if value is None:
                env.clear_property(prop)
            else:
                env.set_property(prop, value)
        compile_cache.reset_cache()


def _build_example(shape_spec: Optional[str], dtype: str):
    if not shape_spec:
        return None
    import jax.numpy as jnp
    shape = tuple(int(d) for d in shape_spec.split(",") if d.strip())
    return jnp.zeros(shape, dtype=dtype)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.runtime.warm_image",
        description="Pre-bake a model's executable ladder into a "
                    "relocatable artifact directory (remote-store "
                    "layout; point DL4J_TPU_REMOTE_CACHE at it).")
    p.add_argument("--model", required=True,
                   help="factory as pkg.module:callable; called with no "
                        "args, returns the model or (model, example)")
    p.add_argument("--output", required=True,
                   help="artifact directory to bake into")
    p.add_argument("--name", default="model",
                   help="model name for the warmup manifest "
                        "(default: model)")
    p.add_argument("--example-shape", default=None,
                   help="example input shape for predict models, e.g. "
                        "1,64 (batch dim irrelevant; feature shape "
                        "fixes the trace)")
    p.add_argument("--dtype", default="float32",
                   help="example dtype (default: float32)")
    p.add_argument("--batch-sizes", default=None,
                   help="comma-separated batch sizes to warm (default: "
                        "the engine's whole bucket ladder)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="bucket-ladder cap (default: "
                        "DL4J_TPU_INFERENCE_MAX_BATCH)")
    p.add_argument("--generative", action="store_true",
                   help="bake a DecodeEngine ladder (prefill x batch + "
                        "decode step) instead of a predict ladder")
    args = p.parse_args(argv)

    factory = _load_factory(args.model)
    produced = factory()
    if isinstance(produced, tuple) and len(produced) == 2:
        model, example = produced
    else:
        model, example = produced, None
    if example is None:
        example = _build_example(args.example_shape, args.dtype)
    batch_sizes = None
    if args.batch_sizes:
        batch_sizes = [int(b) for b in args.batch_sizes.split(",")
                       if b.strip()]
    summary = bake(model, example, output=args.output, name=args.name,
                   batch_sizes=batch_sizes, max_batch=args.max_batch,
                   generative=args.generative)
    json.dump(summary, sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0 if summary["entries"] > 0 or summary["generative"] else 1


if __name__ == "__main__":  # pragma: no cover - exercised via main()
    sys.exit(main())
