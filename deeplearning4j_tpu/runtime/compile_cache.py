"""Ahead-of-time compilation pipeline: persistent executable cache.

The north-star deployment restarts constantly (autoscaling, rollouts,
preemption), and before this module every restart paid a full re-trace +
XLA re-compile for every inference bucket, train step, and SameDiff graph.
Production serving systems treat compiled executables as cacheable
artifacts (ORCA's amortized engine builds; JAX's persistent compilation
cache); here the same idea is wired through ``counted_jit``, the single
choke point every jitted entry in this codebase dispatches through.

Three layers, safest-first:

1. **On-disk executable store** (``DL4J_TPU_CACHE_DIR``, on by default at
   ``~/.cache/deeplearning4j_tpu``): for *serving-shaped* entries (no
   donation, no explicit sharding kwargs, plain array args — including
   mesh-sharded arrays committed via ``NamedSharding``) the first call
   per input signature runs ``jit(...).lower(...)`` and consults the
   store. A hit deserializes the XLA executable (``PjRtClient.
   deserialize_executable``) and skips XLA compilation entirely; a miss
   compiles via ``lowered.compile()`` and serializes the result back,
   for multi-device programs together with the mesh + in/out
   PartitionSpecs needed to place inputs and reassemble sharded outputs
   into global arrays on reload. The cache key is a sha256 over
   everything that feeds a trace: the lowered StableHLO module (which
   captures shapes, dtypes, batch bucket, donation/sharding attributes,
   and every conf knob that changes the traced program), the jit kwargs,
   the device assignment + input shardings of the concrete call,
   jax/jaxlib versions, backend platform + device kind + device count,
   and the trace-relevant ``DL4J_TPU_*`` flags.
2. **jax persistent-compilation-cache backstop**: when the store is
   enabled on an accelerator backend, ``jax_compilation_cache_dir`` is
   pointed at ``<dir>/xla`` so every compile this process runs —
   including donated train steps and mesh-sharded programs our own store
   refuses to wrap — still loads from disk on restart instead of
   re-running XLA. Gated by ``DL4J_TPU_XLA_CACHE`` (auto|on|off;
   "auto" keeps it off on the CPU backend, where deserialized donated
   executables proved unstable under churn and the store already covers
   the serving path).
3. **Fallback, never crash**: corrupt/truncated/version-mismatched
   entries are deleted and recompiled with a one-time warning; any error
   while lowering, loading, serializing, or calling an AOT entry falls
   back to the live ``jax.jit`` dispatch that predates this module.

Observability: ``dl4j_compiles_total`` is labeled
``cache=hit|miss|bypass``; the ``dl4j_compile_seconds`` histogram carries
the reasoned form — ``hit``, ``miss``, or ``bypass:<reason>`` (e.g.
``bypass:donation`` for the donated-KV decode steps that remain
store-ineligible by design, ``bypass:disabled`` when the store is off).
Disable everything with ``DL4J_TPU_CACHE_DIR=""``.

**Donated-KV-cache decode steps are store-ineligible by design.** The
generative fast path (``runtime.generation.DecodeEngine``) donates its
preallocated KV cache into every prefill/decode step so the cache updates
in place; a raw stored executable bypasses jax's donation bookkeeping, so
``_ineligible_reason`` refuses these entries and they dispatch through
the live jit. They are NOT silently missing from telemetry:
``counted_jit`` still records one compile event per signature with
``cache=bypass`` on ``dl4j_compiles_total`` and ``cache=bypass:donation``
on the ``dl4j_compile_seconds`` histogram (asserted in
tests/test_generation.py). On accelerator backends the
``jax_compilation_cache_dir`` backstop at ``<dir>/xla`` still shortens
their restart compiles; on CPU the backstop stays off (see
``_backstop_wanted``) and decode steps recompile on restart — bounded at
one prefill per prompt bucket plus one decode executable.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common import faults
from ..common.environment import environment
from ..common.locks import ordered_lock

log = logging.getLogger(__name__)

#: bump to invalidate every existing on-disk entry (layout change)
FORMAT_VERSION = 2

_PAYLOAD_EXT = ".bin"
_META_EXT = ".json"


# ---------------------------------------------------------------------------
# environment fingerprint + cache key
# ---------------------------------------------------------------------------

def env_fingerprint() -> str:
    """JSON of everything outside the traced program that can change what
    an executable computes or how it was compiled: versions, topology, and
    the DL4J_TPU_* flags that feed traces. Part of every cache key."""
    import jax
    import jaxlib

    env = environment()
    dev = jax.devices()[0]
    return json.dumps({
        "format": FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "num_devices": jax.device_count(),
        "dtype": env.default_float_dtype(),
        "matmul_precision": env.matmul_precision(),
        "remat": env.training_remat(),
        "grad_accum": env.training_grad_accum(),
        "zero1": env.training_zero1(),
        "bucketing": env.inference_bucketing(),
        "flash_min_seq": env.flash_min_seq(),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }, sort_keys=True)


def _jit_kwargs_repr(jit_kwargs: Dict[str, Any]) -> str:
    """Stable repr of the jit kwargs for key composition. Donation and
    shardings must key entries apart even when they do not change the
    lowered text (e.g. donation XLA judged unusable)."""
    return repr(sorted((k, repr(v)) for k, v in jit_kwargs.items()))


def _placement_fingerprint(args) -> str:
    """Device assignment + input shardings of the call's args. The
    StableHLO text carries the *logical* sharding attributes, but not the
    physical device assignment — two processes with the same program on
    different device orderings (or one sharded vs one replicated over a
    different mesh) must not share a raw executable."""
    if args is None:
        return ""
    import jax
    from jax.sharding import NamedSharding

    parts = []
    try:
        for leaf in jax.tree_util.tree_leaves(args):
            sh = getattr(leaf, "sharding", None)
            if sh is None:
                parts.append("host")
            elif isinstance(sh, NamedSharding):
                mesh = sh.mesh
                parts.append("named:%s:%s:%s:%s" % (
                    ",".join(mesh.axis_names),
                    "x".join(str(s) for s in mesh.devices.shape),
                    ",".join(str(d.id) for d in mesh.devices.flat),
                    sh.spec))
            else:
                ids = sorted(d.id for d in getattr(sh, "device_set", ()))
                parts.append("%s:%s" % (type(sh).__name__, ids))
    except Exception:
        parts.append("unknown")
    return ";".join(parts)


def cache_key(lowered, jit_kwargs: Optional[Dict[str, Any]] = None,
              args=None) -> str:
    """sha256 hex key for a ``jax.stages.Lowered``: the StableHLO text
    captures shapes/dtypes/buckets/mesh attributes and every conf knob
    that alters the traced program; the fingerprint adds versions,
    topology, and env flags; the placement fingerprint adds the device
    assignment + input shardings of the concrete call."""
    h = hashlib.sha256()
    h.update(env_fingerprint().encode())
    h.update(b"\x00")
    h.update(_jit_kwargs_repr(jit_kwargs or {}).encode())
    h.update(b"\x00")
    h.update(_placement_fingerprint(args).encode())
    h.update(b"\x00")
    h.update(lowered.as_text().encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# pluggable raw artifact stores
# ---------------------------------------------------------------------------

class CorruptEntryError(Exception):
    """A stored entry failed validation (format/size/digest) and was
    deleted by the store before raising. The cache layer turns this into
    a one-time warning + a miss — never an exception to the caller."""

    def __init__(self, why: str):
        super().__init__(why)
        self.why = why


_TMP_COUNTER = itertools.count()


def _tmp_suffix() -> str:
    """Unique-per-writer tmp suffix: two replicas (or two threads of one
    replica) pushing the same key must never collide on the tmp file —
    each writes its own and the last ``os.replace`` wins atomically."""
    return ".tmp%d-%d-%d" % (os.getpid(), threading.get_ident(),
                             next(_TMP_COUNTER))


def _stamp_meta(payload: bytes, meta: dict) -> dict:
    """Copy of ``meta`` stamped with the integrity fields every store
    validates on read."""
    meta = dict(meta)
    meta["format"] = FORMAT_VERSION
    meta["payload_bytes"] = len(payload)
    meta["payload_sha"] = hashlib.sha256(payload).hexdigest()
    return meta


def _validate_entry(payload: bytes, meta: dict):
    """Raise ValueError when (payload, meta) fail the integrity check."""
    if meta.get("format") != FORMAT_VERSION:
        raise ValueError(f"format {meta.get('format')} != {FORMAT_VERSION}")
    if len(payload) != meta.get("payload_bytes"):
        raise ValueError("payload truncated")
    if hashlib.sha256(payload).hexdigest() != meta.get("payload_sha"):
        raise ValueError("payload checksum mismatch")


class _FilesystemStore:
    """Shared machinery of the filesystem-rooted stores: an entry is
    ``<key>.bin`` + ``<key>.json`` under ``_entry_dir(key)``, written via
    a unique tmp file + ``os.replace`` (atomic on POSIX, so concurrent
    writers of the same key cannot interleave partial content) and
    digest-verified on every read (a failed check deletes the entry and
    raises :class:`CorruptEntryError`)."""

    tier = "local"

    def _entry_dir(self, key: str, create: bool = False) -> str:
        raise NotImplementedError

    def _paths(self, key: str, create: bool = False) -> Tuple[str, str]:
        d = self._entry_dir(key, create=create)
        return (os.path.join(d, key + _PAYLOAD_EXT),
                os.path.join(d, key + _META_EXT))

    def contains(self, key: str) -> bool:
        return os.path.exists(self._paths(key)[1])

    def get(self, key: str) -> Optional[Tuple[bytes, dict]]:
        payload_p, meta_p = self._paths(key)
        if not os.path.exists(meta_p):
            return None
        try:
            with open(meta_p, "r") as f:
                meta = json.load(f)
            with open(payload_p, "rb") as f:
                payload = f.read()
            _validate_entry(payload, meta)
        except Exception as e:
            self.delete(key)
            raise CorruptEntryError(f"{type(e).__name__}: {e}") from e
        self.touch(key)
        return payload, meta

    def put(self, key: str, payload: bytes, meta: dict) -> bool:
        """``meta`` must already be stamped (``_stamp_meta``)."""
        try:
            payload_p, meta_p = self._paths(key, create=True)
            for path, data, mode in ((payload_p, payload, "wb"),
                                     (meta_p, json.dumps(meta), "w")):
                tmp = path + _tmp_suffix()
                with open(tmp, mode) as f:
                    f.write(data)
                os.replace(tmp, path)
        except OSError as e:
            log.warning("artifact store write failed (%s); continuing "
                        "uncached", e)
            return False
        return True

    def delete(self, key: str):
        for p in self._paths(key):
            try:
                os.remove(p)
            except OSError:
                pass

    def touch(self, key: str):
        """LRU recency hint; overridden to a no-op where mtime churn is
        unwanted (the shared remote)."""
        now = time.time()
        try:
            os.utime(self._paths(key)[0], (now, now))
        except OSError:
            pass

    def entry_meta(self, key: str) -> Optional[dict]:
        try:
            with open(self._paths(key)[1], "r") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def last_used(self, key: str) -> Optional[float]:
        try:
            return os.stat(self._paths(key)[0]).st_mtime
        except OSError:
            return None

    def _iter_dirs(self):
        raise NotImplementedError

    def keys(self) -> List[str]:
        out = []
        for d in self._iter_dirs():
            try:
                names = os.listdir(d)
            except OSError:
                continue
            out.extend(n[:-len(_META_EXT)] for n in names
                       if n.endswith(_META_EXT))
        return out

    def stat(self) -> Dict[str, int]:
        """{"entries", "bytes"} of the tier, by payload files."""
        entries = 0
        total = 0
        for d in self._iter_dirs():
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                if n.endswith(_META_EXT):
                    entries += 1
                elif n.endswith(_PAYLOAD_EXT):
                    try:
                        total += os.stat(os.path.join(d, n)).st_size
                    except OSError:
                        pass
        return {"entries": entries, "bytes": total}

    def clear(self):
        for d in self._iter_dirs():
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                try:
                    os.remove(os.path.join(d, n))
                except OSError:
                    pass
        return self

    def tiers(self) -> List["_FilesystemStore"]:
        return [self]

    def enforce_cap(self, max_bytes: int) -> int:
        """Evict LRU entries beyond ``max_bytes``; returns evicted count.
        Only the local tier caps — see the overrides."""
        return 0


class LocalDirStore(_FilesystemStore):
    """Today's per-machine layout: flat ``<base_dir>/aot/<key>.bin|.json``
    with mtime-LRU eviction. The default store — behavior-identical to
    the pre-ArtifactStore cache when no remote is configured."""

    tier = "local"

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        self.aot_dir = os.path.join(base_dir, "aot")
        os.makedirs(self.aot_dir, exist_ok=True)

    def _entry_dir(self, key: str, create: bool = False) -> str:
        return self.aot_dir

    def _iter_dirs(self):
        yield self.aot_dir

    def enforce_cap(self, max_bytes: int) -> int:
        if max_bytes <= 0:
            return 0
        evicted = 0
        try:
            entries = []
            total = 0
            for name in os.listdir(self.aot_dir):
                if not name.endswith(_PAYLOAD_EXT):
                    continue
                p = os.path.join(self.aot_dir, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                total += st.st_size
                entries.append((st.st_mtime, st.st_size,
                                name[:-len(_PAYLOAD_EXT)]))
            if total <= max_bytes:
                return 0
            entries.sort()  # oldest first
            for _, size, key in entries:
                if total <= max_bytes:
                    break
                self.delete(key)
                total -= size
                evicted += 1
        except OSError:
            pass  # capping is best-effort; never fail the compile path
        return evicted

    def describe(self) -> dict:
        return {"tier": self.tier, "backend": "local-dir",
                "path": self.aot_dir}


class RemoteStore(_FilesystemStore):
    """Content-addressed shared store the whole fleet reads and writes:
    sha256-keyed objects under ``<root>/objects/<key[:2]>/`` (the cache
    key is already a sha256; the two-hex fan-out keeps any one directory
    small at fleet scale). Writes are unique-tmp + ``os.replace`` and
    reads digest-verify, so N replicas pushing the same key concurrently
    converge on one valid entry and a torn write can never be served.

    This filesystem-rooted implementation is both the test double and a
    real deployment path (``DL4J_TPU_REMOTE_CACHE`` pointed at an NFS /
    FUSE-mounted bucket). An HTTP/object-store client is the documented
    extension point: subclass and override ``get``/``put``/``delete``/
    ``contains``/``keys``/``stat`` (and ``manifest_*``) with your
    transport — everything above the store (keying, validation fallback,
    tiering, pull metrics) is transport-agnostic. No LRU here: recency
    touches and byte caps are per-machine policies (``LocalDirStore``);
    a shared store is pruned by whoever owns the bucket."""

    tier = "remote"

    def __init__(self, root: str):
        self.root = root
        self.objects_dir = os.path.join(root, "objects")
        os.makedirs(self.objects_dir, exist_ok=True)

    def _entry_dir(self, key: str, create: bool = False) -> str:
        d = os.path.join(self.objects_dir, key[:2] or "_")
        if create:
            os.makedirs(d, exist_ok=True)
        return d

    def _iter_dirs(self):
        try:
            shards = sorted(os.listdir(self.objects_dir))
        except OSError:
            shards = []
        for s in shards:
            yield os.path.join(self.objects_dir, s)

    def touch(self, key: str):
        pass  # shared mtimes stay put: every replica would churn them

    def manifest_dir(self, create: bool = False) -> str:
        """Where pushed warmup manifests live (``<root>/manifests``) —
        the pull-on-boot counterpart of ``serving_manifest_dir``."""
        d = os.path.join(self.root, "manifests")
        if create:
            os.makedirs(d, exist_ok=True)
        return d

    def describe(self) -> dict:
        return {"tier": self.tier, "backend": "remote-fs",
                "path": self.objects_dir}


class TieredStore:
    """Read local-then-remote, write-populate both.

    A local miss falls through to the shared remote; a remote hit is
    written back into the local dir so the next restart never leaves the
    machine. A *corrupt* local entry is deleted and transparently
    refetched from the remote (``on_corrupt`` is still told, so the
    cache's corruption stats see it); a corrupt remote entry is deleted
    for the whole fleet and reported as a miss. Remote fetch latency
    lands on ``dl4j_cache_pull_seconds{outcome=hit|miss|error}``."""

    tier = "tiered"

    def __init__(self, local: LocalDirStore, remote: RemoteStore):
        self.local = local
        self.remote = remote
        #: set by the owning cache to route corruption into its
        #: warn-once + stats path
        self.on_corrupt: Optional[Callable[[str, str], None]] = None

    def _corrupt(self, key: str, why: str):
        if self.on_corrupt is not None:
            self.on_corrupt(key, why)
        else:
            log.warning("compile cache entry %s.. dropped (%s)",
                        key[:12], why)

    def contains(self, key: str) -> bool:
        return self.local.contains(key) or self.remote.contains(key)

    def get(self, key: str) -> Optional[Tuple[bytes, dict]]:
        local_why = None
        try:
            entry = self.local.get(key)
            if entry is not None:
                return entry
        except CorruptEntryError as e:
            local_why = e.why  # deleted; try to refetch from the remote
        t0 = time.perf_counter()
        try:
            entry = self.remote.get(key)
        except CorruptEntryError as e:
            observe_pull("error", time.perf_counter() - t0)
            self._corrupt(key, f"remote entry: {e.why}")
            return None
        if entry is None:
            observe_pull("miss", time.perf_counter() - t0)
            if local_why is not None:
                # nothing to refetch: surface the local corruption
                raise CorruptEntryError(local_why)
            return None
        observe_pull("hit", time.perf_counter() - t0)
        if local_why is not None:
            self._corrupt(key, f"{local_why}; refetched from remote store")
        self.local.put(key, entry[0], entry[1])
        return entry

    def put(self, key: str, payload: bytes, meta: dict) -> bool:
        local_ok = self.local.put(key, payload, meta)
        remote_ok = self.remote.put(key, payload, meta)
        return local_ok or remote_ok

    def delete(self, key: str):
        self.local.delete(key)
        self.remote.delete(key)

    def keys(self) -> List[str]:
        """Local-tier keys (what the inventory lists as resident)."""
        return self.local.keys()

    def entry_meta(self, key: str) -> Optional[dict]:
        return self.local.entry_meta(key) or self.remote.entry_meta(key)

    def last_used(self, key: str) -> Optional[float]:
        return self.local.last_used(key)

    def stat(self) -> Dict[str, int]:
        return self.local.stat()

    def clear(self):
        """Clears the *local* tier only: the shared remote outlives any
        one replica (use ``RemoteStore.clear()`` deliberately)."""
        self.local.clear()
        return self

    def tiers(self) -> List[Any]:
        return [self.local, self.remote]

    def enforce_cap(self, max_bytes: int) -> int:
        return self.local.enforce_cap(max_bytes)

    def describe(self) -> dict:
        return {"tier": self.tier, "backend": "tiered"}


def observe_pull(outcome: str, seconds: float):
    """Record one remote-store fetch on
    ``dl4j_cache_pull_seconds{outcome}`` (hit = object downloaded, miss =
    not in the remote, error = corrupt/unreadable remote entry) — the
    boot-time pull latency the fleet cold-start gate bounds."""
    try:
        from ..common.metrics import COMPILE_SECONDS_BUCKETS, registry
        registry().histogram(
            "dl4j_cache_pull_seconds",
            "Remote artifact-store fetch latency by outcome "
            "(hit|miss|error)", labels=("outcome",),
            buckets=COMPILE_SECONDS_BUCKETS).labels(
                outcome=outcome).observe(seconds)
    except Exception:
        pass  # observability must never break the load path


# ---------------------------------------------------------------------------
# the executable cache (policy layer over an ArtifactStore)
# ---------------------------------------------------------------------------

class AOTCompileCache:
    """Executable cache: validation stats, corruption warnings, and LRU
    policy over a pluggable raw store.

    Default store is :class:`LocalDirStore` — entry = ``<key>.bin``
    (serialized XLA executable) + ``<key>.json`` (integrity + reload
    metadata) under ``<dir>/aot``, LRU by file mtime, capped at
    ``max_bytes`` (``DL4J_TPU_CACHE_MAX_BYTES``). With
    ``DL4J_TPU_REMOTE_CACHE`` set the store is a :class:`TieredStore`
    (local + content-addressed shared remote). Every read validates
    format version, payload size, and payload sha256; anything off is
    deleted and reported as a miss — a corrupt cache can cost a compile,
    never an exception."""

    def __init__(self, base_dir: str, max_bytes: int, store=None):
        self.base_dir = base_dir
        self.store = store if store is not None else LocalDirStore(base_dir)
        local = next((t for t in self.store.tiers() if t.tier == "local"),
                     None)
        #: the local tier's flat entry dir (tests poke files here); None
        #: for a remote-only store
        self.aot_dir = local.aot_dir if local is not None else None
        self.max_bytes = int(max_bytes)
        self._lock = ordered_lock("cache.store")
        self._warned_keys: set = set()
        self.stats = {"hits": 0, "misses": 0, "puts": 0, "corrupt": 0,
                      "evictions": 0, "put_errors": 0}
        if isinstance(self.store, TieredStore):
            self.store.on_corrupt = self._warn_once
        self._refresh_store_gauges()

    def _drop(self, key: str):
        self.store.delete(key)

    def _warn_once(self, key: str, why: str):
        with self._lock:
            self.stats["corrupt"] += 1
            if key in self._warned_keys:
                return
            self._warned_keys.add(key)
        log.warning("compile cache entry %s.. dropped (%s); recompiling",
                    key[:12], why)

    def _refresh_store_gauges(self):
        """Per-tier size gauges, refreshed on every store mutation."""
        try:
            from ..common.metrics import registry
            reg = registry()
            g_bytes = reg.gauge(
                "dl4j_cache_store_bytes",
                "Payload bytes resident per artifact-store tier",
                labels=("tier",))
            g_entries = reg.gauge(
                "dl4j_cache_store_entries",
                "Executable entries resident per artifact-store tier",
                labels=("tier",))
            for t in self.store.tiers():
                st = t.stat()
                g_bytes.labels(tier=t.tier).set(st["bytes"])
                g_entries.labels(tier=t.tier).set(st["entries"])
        except Exception:
            pass  # observability must never break the compile path

    # -- read --------------------------------------------------------------
    def get(self, key: str) -> Optional[Tuple[bytes, dict]]:
        """(payload, meta) for a valid entry, else None. Corrupt entries
        are deleted with a one-time warning (a tiered store transparently
        refetches a locally corrupt entry from the remote first)."""
        entry = None
        mutated = False
        try:
            entry = self.store.get(key)
            if entry is not None and faults.active():
                # injected read fault: exercises the corrupt-entry
                # recovery path (drop + warn + recompile) on demand
                faults.check("cache.load", key=key)
        except CorruptEntryError as e:
            self._warn_once(key, e.why)
            entry = None
            mutated = True
        except Exception as e:
            self.store.delete(key)
            self._warn_once(key, f"{type(e).__name__}: {e}")
            entry = None
            mutated = True
        if mutated:
            self._refresh_store_gauges()
        if entry is None:
            with self._lock:
                self.stats["misses"] += 1
            return None
        with self._lock:
            self.stats["hits"] += 1
        return entry

    # -- write -------------------------------------------------------------
    def put(self, key: str, payload: bytes, meta: dict) -> bool:
        """Atomic write (unique tmp + rename), then LRU cap
        enforcement on the local tier."""
        meta = _stamp_meta(payload, meta)
        if not self.store.put(key, payload, meta):
            with self._lock:
                self.stats["put_errors"] += 1
            return False
        with self._lock:
            self.stats["puts"] += 1
        evicted = self.store.enforce_cap(self.max_bytes)
        if evicted:
            with self._lock:
                self.stats["evictions"] += evicted
        self._refresh_store_gauges()
        return True

    # -- maintenance -------------------------------------------------------
    def clear(self):
        self.store.clear()
        self._refresh_store_gauges()
        return self

    def entry_count(self) -> int:
        try:
            return len(self.store.keys())
        except OSError:
            return 0


# ---------------------------------------------------------------------------
# singleton resolution (env-driven, re-resolved when the dir changes)
# ---------------------------------------------------------------------------

_CACHE: Optional[AOTCompileCache] = None
_CACHE_CONF_USED: Optional[Tuple] = None
_CACHE_LOCK = ordered_lock("cache.global")
_BACKSTOP_DIR: Optional[str] = None


def _store_conf() -> Tuple[Optional[str], Optional[str], str]:
    """(cache_dir, remote_cache, cache_tier) — the env triple the
    singleton is keyed on."""
    env = environment()
    return (env.cache_dir(), env.remote_cache(), env.cache_tier())


def _build_store(cache_dir: str, remote: Optional[str], tier: str):
    """Store for the resolved conf: no remote (or tier=local) keeps
    today's LocalDirStore; tier=remote serves straight off the shared
    store; auto/tiered with a remote configured reads local-then-remote
    and write-populates both."""
    if tier == "local" or not remote:
        return LocalDirStore(cache_dir)
    if tier == "remote":
        return RemoteStore(remote)
    return TieredStore(LocalDirStore(cache_dir), RemoteStore(remote))


def cache() -> Optional[AOTCompileCache]:
    """The process-wide store, or None when caching is disabled
    (``DL4J_TPU_CACHE_DIR=""``). Re-resolves if the configured dir,
    remote root, or tier changed since the last call (tests,
    ``Environment.set_cache_dir``/``set_remote_cache``)."""
    global _CACHE, _CACHE_CONF_USED
    conf = _store_conf()
    if conf == _CACHE_CONF_USED:
        return _CACHE
    with _CACHE_LOCK:
        if conf != _CACHE_CONF_USED:
            d, remote, tier = conf
            if d:
                try:
                    _CACHE = AOTCompileCache(
                        d, environment().cache_max_bytes(),
                        store=_build_store(d, remote, tier))
                except OSError as e:
                    log.warning("compile cache dir %s unusable (%s); "
                                "caching disabled", d, e)
                    _CACHE = None
            else:
                _CACHE = None
            _CACHE_CONF_USED = conf
        if _CACHE is not None and _backstop_wanted():
            _configure_backstop(_CACHE.base_dir)
        else:
            _disable_backstop()
    return _CACHE


def reset_cache():
    """Drop the singleton and immediately re-resolve the store conf
    (DL4J_TPU_CACHE_DIR / _REMOTE_CACHE / _CACHE_TIER), re-pointing (or
    disabling) the jax backstop so no compile keeps writing into a stale
    — possibly deleted — directory."""
    global _CACHE, _CACHE_CONF_USED
    with _CACHE_LOCK:
        _CACHE = None
        _CACHE_CONF_USED = None
    cache()


def _backstop_wanted() -> bool:
    """Whether to wire ``jax_compilation_cache_dir`` at ``<dir>/xla``
    (``DL4J_TPU_XLA_CACHE``): "on"/"off" force it; "auto" (default)
    enables it only on accelerator backends. On the CPU backend the raw
    executable store already covers serving-shaped entries, and the
    programs only the backstop would cover (donated train steps) proved
    unstable when XLA:CPU deserializes them under churn — reproducible
    nondeterministic SIGABRTs / corrupted updates mid-train-step across
    full-suite runs, gone with the backstop off — so auto keeps CPU on
    the store alone."""
    mode = environment().xla_cache()
    if mode == "on":
        return True
    if mode == "off":
        return False
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def _configure_backstop(base_dir: str):
    """Point jax's persistent compilation cache at ``<dir>/xla`` so every
    compile — including the donated/sharded programs the store cannot wrap
    raw — is disk-backed across restarts. Backends without executable
    serialization simply no-op inside jax; this must never raise."""
    global _BACKSTOP_DIR
    xla_dir = os.path.join(base_dir, "xla")
    if _BACKSTOP_DIR == xla_dir:
        return
    try:
        import jax
        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # jax latches its cache object at the first compile of the
        # process; (re)pointing the config only takes effect after an
        # explicit reset
        try:
            from jax._src import compilation_cache as _jcc
            _jcc.reset_cache()
        except Exception:
            pass
        _BACKSTOP_DIR = xla_dir
    except Exception as e:  # unsupported jax version/backend: store-only
        log.debug("persistent-compilation-cache backstop unavailable: %s", e)


def _disable_backstop():
    """Unset the jax compilation-cache dir (store disabled, or its old
    directory is going away)."""
    global _BACKSTOP_DIR
    if _BACKSTOP_DIR is None:
        return
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            from jax._src import compilation_cache as _jcc
            _jcc.reset_cache()
        except Exception:
            pass
        _BACKSTOP_DIR = None
    except Exception:
        pass


# ---------------------------------------------------------------------------
# serving warmup-manifest handoff
# ---------------------------------------------------------------------------

def serving_manifest_dir(create: bool = True) -> Optional[str]:
    """Directory where the serving registry persists per-model warmup
    manifests so the NEXT replica (or the incoming version of a hot swap)
    replays the shapes live traffic exercised before taking traffic.

    ``DL4J_TPU_SERVING_MANIFEST_DIR`` overrides; the default rides the
    executable cache at ``<DL4J_TPU_CACHE_DIR>/manifests`` — the same
    volume a deployment already ships between replicas for AOT
    executables. Returns None when both are disabled (manifests then live
    only in process memory: hot-swap handoff still works, restart replay
    does not)."""
    d = environment().serving_manifest_dir()
    if not d:
        base = environment().cache_dir()
        if not base:
            return None
        d = os.path.join(base, "manifests")
    if create:
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            log.warning("serving manifest dir %s unusable (%s); manifests "
                        "stay in-memory", d, e)
            return None
    return d


# ---------------------------------------------------------------------------
# fleet handoff: push-on-drain / pull-on-boot over the shared store
# ---------------------------------------------------------------------------

def _tiered() -> Optional[TieredStore]:
    cc = cache()
    if cc is not None and isinstance(cc.store, TieredStore):
        return cc.store
    return None


def _copy_manifests(src: Optional[str], dst: Optional[str]) -> int:
    """Atomic-copy every ``*.warmup.json`` from src into dst; returns the
    count copied."""
    if not src or not dst or not os.path.isdir(src):
        return 0
    try:
        os.makedirs(dst, exist_ok=True)
        names = [n for n in os.listdir(src) if n.endswith(".warmup.json")]
    except OSError:
        return 0
    copied = 0
    for name in names:
        try:
            tmp = os.path.join(dst, name + _tmp_suffix())
            shutil.copyfile(os.path.join(src, name), tmp)
            os.replace(tmp, os.path.join(dst, name))
            copied += 1
        except OSError as e:
            log.warning("manifest copy %s failed (%s)", name, e)
    return copied


def push_to_remote() -> Dict[str, int]:
    """Publish this replica's warm state to the shared store: every local
    executable the remote doesn't have yet, plus the serving warmup
    manifests. Called by ``GracefulLifecycle.drain`` so a draining
    replica's compiles outlive it; safe under concurrent pushers (unique
    tmp + atomic rename per object). No-op without a tiered store."""
    store = _tiered()
    if store is None:
        return {"executables": 0, "manifests": 0}
    pushed = 0
    for key in store.local.keys():
        if store.remote.contains(key):
            continue
        try:
            entry = store.local.get(key)
        except CorruptEntryError:
            continue  # deleted by the read; nothing to publish
        if entry is not None and store.remote.put(key, entry[0], entry[1]):
            pushed += 1
    manifests = _copy_manifests(serving_manifest_dir(create=False),
                                store.remote.manifest_dir(create=True))
    cc = cache()
    if cc is not None:
        cc._refresh_store_gauges()
    if pushed or manifests:
        log.info("pushed %d executables, %d manifests to remote store",
                 pushed, manifests)
    return {"executables": pushed, "manifests": manifests}


def pull_manifests() -> int:
    """Copy the shared store's warmup manifests into the local serving
    manifest dir (overwriting), so ``registry.deploy`` replays the fleet's
    observed shapes instead of starting blind. No-op without a tiered
    store."""
    store = _tiered()
    if store is None:
        return 0
    return _copy_manifests(store.remote.manifest_dir(create=False),
                           serving_manifest_dir(create=True))


def pull_from_remote(keys: Optional[List[str]] = None) -> Dict[str, int]:
    """Boot-time warm restore: download manifests plus every remote
    executable not already local (or just ``keys``) into the local tier.
    Run this *before* ``/readyz`` flips — a replica advertised ready with
    a cold store would compile under live traffic, the exact spike this
    store exists to prevent. Each fetch lands on
    ``dl4j_cache_pull_seconds``. No-op without a tiered store."""
    store = _tiered()
    if store is None:
        return {"executables": 0, "manifests": 0}
    manifests = pull_manifests()
    pulled = 0
    for key in (keys if keys is not None else store.remote.keys()):
        if store.local.contains(key):
            continue
        try:
            if store.get(key) is not None:  # tiered get write-populates
                pulled += 1
        except CorruptEntryError:
            pass  # deleted from the fleet store; next compile republishes
    cc = cache()
    if cc is not None:
        cc._refresh_store_gauges()
    if pulled or manifests:
        log.info("pulled %d executables, %d manifests from remote store",
                 pulled, manifests)
    return {"executables": pulled, "manifests": manifests}


# ---------------------------------------------------------------------------
# AOT entry construction (the counted_jit integration point)
# ---------------------------------------------------------------------------

def _ineligible_reason(args, jit_kwargs: Dict[str, Any]) -> Optional[str]:
    """Why a call may NOT be wrapped as a raw executable (None = may).

    Raw executables bypass jax's arg handling, so refuse anything with
    donation (buffer invalidation — the DecodeEngine's donated-KV steps),
    explicit sharding kwargs / static args (closure semantics), or
    non-array leaves beyond plain python scalars (extended dtypes such as
    PRNG keys lower to internal layouts). Multi-device args ARE eligible:
    the key folds in the device assignment + shardings
    (``_placement_fingerprint``) and ``_load_executor`` reassembles
    sharded outputs into global arrays."""
    import jax

    for k in ("donate_argnums", "donate_argnames"):
        if jit_kwargs.get(k):
            return "donation"
    for k in ("static_argnums", "static_argnames"):
        if jit_kwargs.get(k):
            return "static-args"
    for k in ("in_shardings", "out_shardings"):
        if jit_kwargs.get(k):
            # explicit sharding kwargs ride the live jit (they only appear
            # on training paths, usually next to donation anyway); the
            # serving path shards via committed args, which we do wrap
            return "shardings-kwarg"
    try:
        for leaf in jax.tree_util.tree_leaves(args):
            if isinstance(leaf, (bool, int, float)):
                continue
            dt = getattr(leaf, "dtype", None)
            if dt is None or not hasattr(leaf, "shape"):
                return "non-array"
            if jax.dtypes.issubdtype(dt, jax.dtypes.extended):
                return "extended-dtype"
    except Exception:
        return "args-error"
    return None


def _eligible(args, jit_kwargs: Dict[str, Any]) -> bool:
    return _ineligible_reason(args, jit_kwargs) is None


def cost_analysis(compiled) -> Optional[dict]:
    """XLA cost/memory analysis of a ``jax.stages.Compiled`` as a small
    JSON-able dict — flops, bytes accessed, and the compiled buffer
    sizes. Best-effort: None when the backend exposes neither (the
    inventory then shows the entry without cost columns)."""
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for key, name in (("flops", "flops"),
                              ("bytes accessed", "bytes_accessed")):
                if key in ca:
                    out[name] = float(ca[key])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("temp_size_in_bytes", "temp_bytes"),
                           ("generated_code_size_in_bytes", "code_bytes")):
            v = getattr(ma, attr, None)
            if v is not None:
                out[name] = int(v)
    except Exception:
        pass
    return out or None


def _spec_encode(spec) -> list:
    """PartitionSpec -> JSON list (None | axis name | [axis names])."""
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append([str(n) for n in e])
        else:
            out.append(str(e))
    return out


def _spec_decode(enc):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in enc])


def _sharding_meta(compiled) -> Optional[dict]:
    """mesh + flat in/out PartitionSpecs for a multi-device program (the
    reload recipe ``_load_executor`` uses to place inputs and reassemble
    outputs into global arrays). None for single-device programs. Raises
    on sharding flavors we cannot round-trip (e.g. GSPMDSharding without
    a named mesh) — the caller then treats the entry as bypass."""
    import jax
    from jax.sharding import NamedSharding

    in_leaves = jax.tree_util.tree_leaves(compiled.input_shardings[0])
    out_leaves = jax.tree_util.tree_leaves(compiled.output_shardings)
    if all(len(getattr(s, "device_set", ())) <= 1
           for s in in_leaves + out_leaves):
        return None
    mesh = None

    def desc(s):
        nonlocal mesh
        if not isinstance(s, NamedSharding):
            raise ValueError(
                f"cannot round-trip {type(s).__name__} shardings")
        if mesh is None:
            mesh = s.mesh
        elif s.mesh != mesh:
            raise ValueError("multiple meshes in one program")
        return _spec_encode(s.spec)

    return {"in_specs": [desc(s) for s in in_leaves],
            "out_specs": [desc(s) for s in out_leaves],
            "mesh": {"axes": list(mesh.axis_names),
                     "shape": [int(x) for x in mesh.devices.shape],
                     "device_ids": [int(d.id)
                                    for d in mesh.devices.flat]}}


def _serialize(compiled) -> Tuple[bytes, dict]:
    """(payload, meta) for a ``jax.stages.Compiled``. Raises when the
    backend does not support executable serialization, or when a
    multi-device program's shardings cannot be round-tripped (caller
    treats the entry as bypass; the jax backstop still covers it)."""
    import jax

    exe = compiled.runtime_executable()
    backend = jax.devices()[0].client
    payload = backend.serialize_executable(exe)
    kept = getattr(compiled._executable, "_kept_var_idx", None)
    if kept is None:
        raise ValueError("executable exposes no kept_var_idx")
    meta = {"kept_var_idx": sorted(int(i) for i in kept),
            "created": time.time()}
    sharded = _sharding_meta(compiled)
    if sharded:
        meta.update(sharded)
    cost = cost_analysis(compiled)
    if cost:
        meta["cost"] = cost
    return payload, meta


def _load_executor(payload: bytes, meta: dict, lowered) -> Optional[Callable]:
    """Rebuild a callable from a stored executable: deserialize, then per
    call flatten args in jit order, keep only the argument positions the
    compiled program kept, execute, and unflatten with the lowering's
    output treedef.

    Single-device programs take shard [0] of each result (there is only
    one). Multi-device programs carry their mesh + in/out PartitionSpecs
    in ``meta`` (``_sharding_meta``): inputs are committed to the stored
    input shardings and every result's shards are reassembled into a
    global array via ``jax.make_array_from_single_device_arrays`` (shards
    map by device, so executable device order is irrelevant). Non-donating
    programs only (enforced by ``_ineligible_reason`` before anything is
    stored)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    try:
        if faults.active():
            # injected deserialize fault: the caller must fall back to a
            # live recompile, never surface the failure to a request
            faults.check("cache.deserialize")
        backend = jax.devices()[0].client
        exe = backend.deserialize_executable(payload)
        kept = meta["kept_var_idx"]
        out_tree = lowered.out_tree
        in_sh = out_sh = out_avals = None
        mesh_meta = meta.get("mesh")
        if mesh_meta:
            by_id = {d.id: d for d in jax.devices()}
            devs = np.asarray(
                [by_id[i] for i in mesh_meta["device_ids"]],
                dtype=object).reshape(mesh_meta["shape"])
            mesh = Mesh(devs, tuple(mesh_meta["axes"]))
            in_sh = [NamedSharding(mesh, _spec_decode(s))
                     for s in meta["in_specs"]]
            out_sh = [NamedSharding(mesh, _spec_decode(s))
                      for s in meta["out_specs"]]
            out_avals = jax.tree_util.tree_leaves(lowered.out_info)
            if len(out_avals) != len(out_sh):
                raise ValueError("out_specs/out_info arity mismatch")
    except Exception as e:
        log.warning("compile cache deserialize failed (%s: %s); "
                    "recompiling", type(e).__name__, e)
        return None

    def call(*args):
        flat = jax.tree_util.tree_leaves(args)
        if in_sh is None:
            bufs = [flat[i] if isinstance(flat[i], jax.Array)
                    else jnp.asarray(flat[i]) for i in kept]
        else:
            bufs = [jax.device_put(flat[i], in_sh[i]) for i in kept]
        results = exe.execute_sharded(
            bufs).disassemble_into_single_device_arrays()
        if out_sh is None:
            return jax.tree_util.tree_unflatten(out_tree,
                                                [r[0] for r in results])
        outs = [jax.make_array_from_single_device_arrays(
                    tuple(av.shape), s, r)
                for av, s, r in zip(out_avals, out_sh, results)]
        return jax.tree_util.tree_unflatten(out_tree, outs)

    return call


def aot_entry(jfn, tag: str, args, jit_kwargs: Dict[str, Any]
              ) -> Tuple[Callable, str]:
    """Resolve the callable for one new input signature of ``jfn``.

    Returns ``(callable, label)`` with label in:

    - ``"hit"``    — executable loaded from the store, XLA never ran;
    - ``"miss"``   — lowered + compiled AOT, serialized into the store;
    - ``"bypass:<reason>"`` — caching disabled, entry ineligible for raw
      serialization (e.g. ``bypass:donation`` for the DecodeEngine's
      donated-KV steps), or a step failed: the live ``jax.jit`` dispatch
      is returned unchanged (the jax persistent-cache backstop still
      shortens its compile when enabled). ``dl4j_compiles_total`` records
      the base label; the reasoned form lands on ``dl4j_compile_seconds``.
    """
    cc = cache()
    if cc is None:
        return jfn, "bypass:disabled"
    why = _ineligible_reason(args, jit_kwargs)
    if why is not None:
        return jfn, "bypass:" + why
    try:
        lowered = jfn.lower(*args)
        key = cache_key(lowered, jit_kwargs, args)
    except Exception as e:
        log.debug("AOT lowering failed for %s (%s); live jit", tag, e)
        return jfn, "bypass:lower-error"
    entry = cc.get(key)
    if entry is not None:
        call = _load_executor(entry[0], entry[1], lowered)
        if call is not None:
            return call, "hit"
        cc._drop(key)  # deserialization failure: stale artifact
    try:
        compiled = lowered.compile()
    except Exception as e:
        log.debug("AOT compile failed for %s (%s); live jit", tag, e)
        return jfn, "bypass:compile-error"
    try:
        payload, meta = _serialize(compiled)
        meta["tag_kind"] = tag.split(":")[0]
        stored = cc.put(key, payload, meta)
    except Exception as e:
        log.debug("executable serialization unavailable for %s (%s); "
                  "backstop only", tag, e)
        return compiled, "bypass:serialize"
    return compiled, ("miss" if stored else "bypass:store-error")


def warm(jfn, args, jit_kwargs: Optional[Dict[str, Any]] = None,
         tag: str = "warm") -> str:
    """Pre-bake one entry without executing it: lower + compile + store
    (and populate the jax backstop) so a later process — or this one —
    starts warm. Unlike ``aot_entry``, ineligible entries (donated train
    steps, sharded programs) are still AOT-compiled here so the backstop
    gets their executable on disk — nothing runs, so donation never
    invalidates a live buffer. Returns the cache label. Used by
    ``FitFastPathMixin.warm_compile`` and CI cache-baking."""
    cc = cache()
    if cc is None:
        return "bypass"
    jit_kwargs = jit_kwargs or {}
    if _eligible(args, jit_kwargs):
        _, label = aot_entry(jfn, tag, args, jit_kwargs)
        return label.partition(":")[0]
    try:
        jfn.lower(*args).compile()
    except Exception as e:
        log.debug("warm compile failed for %s (%s: %s)", tag,
                  type(e).__name__, e)
    return "bypass"


# ---------------------------------------------------------------------------
# executable inventory (the /debug/compile_cache endpoint)
# ---------------------------------------------------------------------------

def inventory() -> dict:
    """The executable store as a JSON-able listing: per entry the cache
    key, tag kind, payload size, creation/last-use times, and the XLA
    cost analysis captured at compile time (flops, bytes accessed,
    buffer sizes); plus per-tier backend/entry-count/byte totals under
    ``"tiers"``. Entries (from the primary tier) sort most-recently-used
    first."""
    cc = cache()
    if cc is None:
        return {"enabled": False, "entries": [], "stats": {},
                "tiers": []}
    entries = []
    for key in cc.store.keys():
        meta = cc.store.entry_meta(key)
        if meta is None:
            continue
        entry = {"key": key, "tag_kind": meta.get("tag_kind"),
                 "payload_bytes": meta.get("payload_bytes"),
                 "created": meta.get("created"),
                 "last_used": cc.store.last_used(key)}
        if meta.get("cost"):
            entry["cost"] = meta["cost"]
        entries.append(entry)
    entries.sort(key=lambda e: e.get("last_used") or 0, reverse=True)
    tiers = []
    for t in cc.store.tiers():
        st = t.stat()
        tiers.append({**t.describe(), "entry_count": st["entries"],
                      "payload_bytes": st["bytes"]})
    with cc._lock:
        stats = dict(cc.stats)
    return {"enabled": True, "dir": cc.base_dir,
            "max_bytes": cc.max_bytes, "entry_count": len(entries),
            "total_payload_bytes": sum(e.get("payload_bytes") or 0
                                       for e in entries),
            "stats": stats, "tiers": tiers, "entries": entries}


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def observe_compile(kind: str, cache_label: str, seconds: float):
    """Record one executable materialization (build + first dispatch) in
    ``dl4j_compile_seconds{kind,cache}``."""
    try:
        from ..common.metrics import COMPILE_SECONDS_BUCKETS, registry
        registry().histogram(
            "dl4j_compile_seconds",
            "Wall time to materialize + first-run an executable, by cache "
            "outcome", labels=("kind", "cache"),
            buckets=COMPILE_SECONDS_BUCKETS).labels(
                kind=kind, cache=cache_label).observe(seconds)
    except Exception:
        pass  # observability must never break the dispatch path
