"""Generative serving fast path: KV-cached decode with continuous batching.

The serving stack through PR 6 pads whole requests through a bucket ladder
and answers them one-shot — it cannot serve autoregressive traffic. This
module is the Orca (OSDI '22) per-iteration scheduling playbook plus the
vLLM/PagedAttention (SOSP '23) preallocated-KV-cache design, sized down to
a slot-per-sequence ring cache:

- **prefill/decode split** — a request's prompt runs through ONE
  fixed-shape jitted ``prefill`` (prompt padded up a bucket ladder, one
  executable per bucket) that fills its slot of a preallocated KV cache
  ``[slots, layers, max_ctx, heads, head_dim]`` and samples the first
  token; every later token costs ONE jitted ``decode`` step shared by all
  active slots (a single executable for the whole steady state).
- **continuous batching** — requests join and leave the running decode
  batch *per token*: the loop admits pending requests into free slots
  between decode steps, so a short generation admitted after a long one
  finishes first instead of waiting behind it (no head-of-line blocking),
  and a finished slot is recycled immediately.
- **sampling** — greedy (temperature 0), temperature, and top-k, all
  per-slot arrays inside the jitted step so mixed sampling configs share
  one executable; per-request ``max_tokens`` and EOS stop host-side.

Both steps route through ``counted_jit`` with the cache donated, so the
compile counter observes exactly (len(prompt buckets) + 1) executables
after warmup and steady-state decode performs **zero recompiles** — the
acceptance invariant of the ``generative_decode`` bench. Donated-cache
entries are store-ineligible by design (``runtime.compile_cache``): they
record ``cache=bypass`` on the compile-seconds histogram and rely on the
XLA backstop cache on accelerator backends.

Observability: ``dl4j_decode_requests_total``, ``dl4j_decode_tokens_total``,
``dl4j_decode_steps_total``, ``dl4j_decode_active_slots``,
``dl4j_decode_queue_depth``, ``dl4j_decode_ttft_seconds`` (exemplared with
trace ids). Each request's trace gains a ``generation/prefill`` span
(queue wait + prompt dispatch, TTFT) and a ``generation/decode`` span
(first token → finish), so ``/debug/requests`` reconstructs a
generation's timeline end to end.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import faults
from ..common.environment import environment
from ..common.locks import (ordered_condition, ordered_lock,
                            ordered_rlock)
from ..common.metrics import exponential_buckets, registry
from ..common.tracing import current_context, record_disposition, tracer
from .inference import (EngineClosedError, bucket_for, bucket_ladder,
                        counted_jit)

log = logging.getLogger(__name__)


def is_generative_model(model) -> bool:
    """Duck-typed generative-model protocol (``models.causal_lm.CausalLM``):
    ``init_kv_cache`` / ``prefill`` / ``decode`` plus a params pytree."""
    return all(callable(getattr(model, m, None))
               for m in ("init_kv_cache", "prefill", "decode")) \
        and hasattr(model, "params")


# ---------------------------------------------------------------------------
# sampling (runs inside the jitted steps: per-slot arrays, one executable)
# ---------------------------------------------------------------------------

def sample_tokens(logits, temperature, top_k, key):
    """Next-token sampling over ``logits`` [S, V] (f32).

    ``temperature`` [S]: <= 0 means greedy argmax for that slot.
    ``top_k`` [S]: <= 0 disables the top-k filter for that slot.
    Sampling uses the Gumbel-max trick so greedy/temperature/top-k all
    stay one fused program with fixed shapes.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    thr = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= thr, scaled, -jnp.inf)
    sampled = jnp.argmax(masked + jax.random.gumbel(key, logits.shape),
                         axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

class _GenRequest:
    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "eos",
                 "on_token", "future", "ctx", "deadline", "t_submit",
                 "t_first", "tokens", "slot")

    def __init__(self, prompt, max_tokens, temperature, top_k, eos,
                 on_token, deadline, ctx):
        self.prompt = prompt              # np.int32 [T]
        self.max_tokens = max_tokens
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos = eos                    # int or None
        self.on_token = on_token
        self.future: Future = Future()
        self.ctx = ctx                    # submitter's TraceContext
        self.deadline = deadline          # monotonic instant or None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.tokens: List[int] = []
        self.slot: Optional[int] = None

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline


class DecodeEngine:
    """Continuous-batching autoregressive decode engine over one model.

    - ``generate(prompt, ...) -> Future`` resolving to a result dict
      (``tokens``, ``finish_reason``, ``ttft_s``, token counts); an
      optional ``on_token`` callback streams tokens as they are sampled.
    - ``warmup()`` pre-compiles one prefill executable per prompt bucket
      plus the single decode-step executable.
    - ``drain()/close()/start()`` mirror ``InferenceEngine`` lifecycle so
      the serving registry hot-swaps/parks generative versions the same
      way it does predict engines.

    ``slots`` bounds concurrent sequences (``DL4J_TPU_DECODE_SLOTS``);
    ``max_ctx`` bounds prompt+generation length per sequence
    (``DL4J_TPU_DECODE_MAX_CTX``, capped by the model's position table).
    """

    def __init__(self, model, *, slots: Optional[int] = None,
                 max_ctx: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_token: Optional[int] = None, seed: int = 0):
        if not is_generative_model(model):
            raise TypeError(
                f"cannot decode a {type(model).__name__}: expected the "
                "generative-model protocol (init_kv_cache/prefill/decode)")
        env = environment()
        self.model = model
        self.slots = int(slots if slots is not None else env.decode_slots())
        max_ctx = int(max_ctx if max_ctx is not None
                      else env.decode_max_ctx())
        pos_cap = getattr(getattr(model, "config", None),
                          "max_position_embeddings", None)
        if pos_cap:
            max_ctx = min(max_ctx, int(pos_cap))
        self.max_ctx = max_ctx
        # prompt-length bucket ladder: one prefill executable per rung
        self.ladder = bucket_ladder(self.max_ctx, prompt_buckets)
        self.eos_token = eos_token
        self._seed = int(seed)
        self._params = model.params
        self._cache = model.init_kv_cache(self.slots, self.max_ctx)
        self._step = 0
        # per-slot host state (the loop thread owns it)
        S = self.slots
        self._tokens = np.zeros(S, np.int32)
        self._lengths = np.zeros(S, np.int32)
        self._temps = np.zeros(S, np.float32)
        self._topks = np.zeros(S, np.int32)
        self._slot_req: List[Optional[_GenRequest]] = [None] * S
        self._active_n = 0
        # dispatch serialization: warmup and the loop both step the cache
        self._dispatch_lock = ordered_rlock("decode.dispatch")
        self._warmed: set = set()
        # scheduler state
        self._cv = ordered_condition("decode.scheduler")
        self._pending: List[_GenRequest] = []
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._draining = False
        self._closed = False
        # resilience: supervised-loop state + watchdog-readable dispatch
        # timestamp (serving/resilience.py polls these from outside)
        self._worker_dead = False
        self._dispatch_started_at: Optional[float] = None
        # registry-compat surface (manifest machinery is predict-only)
        self.max_batch = self.slots
        self.manifest_path = None
        self._stats_lock = ordered_lock("decode.stats")
        self._stats = {"requests": 0, "tokens": 0, "decode_steps": 0,
                       "prefills": 0, "expired": 0}
        self._build_steps()
        reg = registry()
        self._reg = reg
        self._m_requests = reg.counter(
            "dl4j_decode_requests_total",
            "Generation requests accepted by DecodeEngine.generate()")
        self._m_tokens = reg.counter(
            "dl4j_decode_tokens_total",
            "Tokens sampled across prefill + decode steps")
        self._m_steps = reg.counter(
            "dl4j_decode_steps_total",
            "Batched single-token decode dispatches")
        self._m_active = reg.gauge(
            "dl4j_decode_active_slots",
            "Sequences currently occupying a decode slot")
        self._m_queue = reg.gauge(
            "dl4j_decode_queue_depth",
            "Generation requests waiting for a free slot")
        self._m_ttft = reg.histogram(
            "dl4j_decode_ttft_seconds",
            "Time from generate() to the first sampled token",
            buckets=exponential_buckets(1e-3, 2.0, 18))
        self._m_expired = reg.counter(
            "dl4j_decode_expired_total",
            "Generation requests whose deadline expired before a slot")
        self._m_restarts = reg.counter(
            "dl4j_engine_restarts_total",
            "Supervised engine worker-thread restarts after a crash",
            labels=("engine",)).labels(engine="decode")
        self._m_slot_leaks = reg.counter(
            "dl4j_decode_slot_leaks_total",
            "KV-cache slots found leaked (occupied without a live rider) "
            "and reclaimed by the per-iteration accounting check")
        self._m_cancelled = reg.counter(
            "dl4j_decode_cancelled_total",
            "Riders whose future was cancelled mid-decode; their slot is "
            "freed immediately")

    # -- jitted steps ------------------------------------------------------
    def _build_steps(self):
        model = self.model

        def prefill_fn(params, cache, ids, slot, length, temp, top_k,
                       seed, step):
            cache, logits = model.prefill(params, cache, ids, slot, length)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            tok = sample_tokens(logits[None], temp[None], top_k[None],
                                key)[0]
            return cache, tok

        def decode_fn(params, cache, tokens, lengths, active, temps,
                      top_ks, seed, step):
            cache, logits = model.decode(params, cache, tokens, lengths)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            nxt = sample_tokens(logits, temps, top_ks, key)
            return cache, jnp.where(active, nxt, tokens)

        # the KV cache is donated: each step consumes the previous buffers
        # in place (on backends that honor donation) — these entries are
        # deliberately ineligible for the raw executable store and show up
        # as cache=bypass on dl4j_compile_seconds (see compile_cache docs)
        # a quantized twin (quant/transforms.quantize_model) carries
        # _precision — suffix the tag so its executables never collide with
        # the full-precision model's in the persistent store (the first tag
        # segment stays "prefill"/"decode": it is the kind metric label)
        prec = getattr(model, "_precision", None)
        suffix = f":{prec}" if prec else ""
        self._prefill = counted_jit(prefill_fn, "prefill" + suffix,
                                    donate_argnums=(1,))
        self._decode = counted_jit(decode_fn, "decode" + suffix,
                                   donate_argnums=(1,))

    def _run_prefill(self, ids, slot, length, temperature, top_k):
        if faults.active():
            faults.check("decode.prefill", slot=slot, length=length)
        with self._dispatch_lock:
            self._dispatch_started_at = time.monotonic()
            try:
                cache, tok = self._prefill(
                    self._params, self._cache, jnp.asarray(ids),
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(length, jnp.int32),
                    jnp.asarray(temperature, jnp.float32),
                    jnp.asarray(top_k, jnp.int32),
                    jnp.asarray(self._seed, jnp.int32),
                    jnp.asarray(self._step, jnp.int32))
                self._cache = cache
                self._step += 1
            finally:
                self._dispatch_started_at = None
        return int(tok)

    def _run_decode(self, active):
        if faults.active():
            faults.check("decode.step", active=int(np.sum(active)))
        with self._dispatch_lock:
            self._dispatch_started_at = time.monotonic()
            try:
                cache, nxt = self._decode(
                    self._params, self._cache, jnp.asarray(self._tokens),
                    jnp.asarray(self._lengths), jnp.asarray(active),
                    jnp.asarray(self._temps), jnp.asarray(self._topks),
                    jnp.asarray(self._seed, jnp.int32),
                    jnp.asarray(self._step, jnp.int32))
                self._cache = cache
                self._step += 1
            finally:
                self._dispatch_started_at = None
        return np.asarray(nxt)

    # -- warmup ------------------------------------------------------------
    def warmup(self, example=None,
               batch_sizes: Optional[Sequence[int]] = None,
               **_ignored) -> List[int]:
        """Compile the ladder before traffic: one prefill executable per
        prompt bucket + the single decode-step executable. Idempotent.
        (``example``/``batch_sizes`` are accepted for registry-warmup
        signature compatibility and ignored: the shapes are fixed by the
        engine's own slots/max_ctx/ladder configuration.)"""
        with self._cv:
            if self._active_n > 0:
                raise RuntimeError(
                    "warmup() while sequences are active would overwrite "
                    "live KV rows; warm before taking traffic")
        warmed = []
        for b in self.ladder:
            key = ("prefill", b)
            if key not in self._warmed:
                ids = np.zeros((1, b), np.int32)
                self._run_prefill(ids, slot=0, length=1, temperature=0.0,
                                  top_k=0)
                self._warmed.add(key)
            warmed.append(b)
        if "decode" not in self._warmed:
            self._run_decode(np.zeros(self.slots, bool))
            self._warmed.add("decode")
        return warmed

    # -- request intake ----------------------------------------------------
    def generate(self, prompt, *, max_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_token="default", on_token: Optional[Callable] = None,
                 timeout_s: Optional[float] = None) -> Future:
        """Enqueue one generation request; returns a Future resolving to
        ``{"tokens", "finish_reason", "ttft_s", "prompt_tokens",
        "completion_tokens"}``.

        ``timeout_s`` bounds the wait for a decode *slot* (admission into
        the running batch), not the generation itself; an expired request
        fails with ``TimeoutError`` before any model work. ``on_token``
        is called from the decode loop with each sampled token id
        (streaming). ``eos_token="default"`` uses the engine's configured
        EOS; ``None`` disables the stop."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("prompt must contain at least one token")
        if ids.size >= self.max_ctx:
            raise ValueError(
                f"prompt length {ids.size} leaves no room to generate "
                f"within max_ctx {self.max_ctx}")
        cap = self.max_ctx - int(ids.size)
        if max_tokens is None:
            max_tokens = min(environment().decode_max_tokens(), cap)
        max_tokens = max(1, min(int(max_tokens), cap))
        eos = self.eos_token if eos_token == "default" else eos_token
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        req = _GenRequest(ids, max_tokens, temperature, top_k, eos,
                          on_token, deadline, current_context())
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                raise EngineClosedError(
                    "DecodeEngine is "
                    + ("closed" if self._closed else
                       "draining" if self._draining else
                       "dead (worker restart budget exhausted)")
                    + "; it no longer accepts requests")
            self._pending.append(req)
            depth = len(self._pending)
            self._cv.notify_all()
        with self._stats_lock:
            self._stats["requests"] += 1
        self._m_requests.inc()
        self._m_queue.set(depth)
        self._ensure_thread()
        return req.future

    def generate_sync(self, prompt, **kw) -> Dict[str, Any]:
        return self.generate(prompt, **kw).result()

    # -- the continuous-batching loop --------------------------------------
    def _ensure_thread(self):
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                return
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._loop_main, name="dl4j-tpu-decode-loop",
                    daemon=True)
                self._thread.start()

    @property
    def worker_dead(self) -> bool:
        """True once the supervised decode loop exhausted its restart
        budget (the watchdog reports this engine unhealthy)."""
        return self._worker_dead

    def _loop_main(self):
        """Supervised decode loop: a crash that escapes the per-iteration
        handler (scheduler-state corruption, not a dispatch fault) is
        counted and the loop restarts with exponential backoff + jitter
        instead of silently killing generation for every later request.
        A crash burst past ``DL4J_TPU_ENGINE_MAX_RESTARTS`` declares the
        worker dead and fails everything queued."""
        policy = faults.RetryPolicy(
            max_restarts=environment().engine_max_restarts(),
            base_s=0.01, max_s=2.0, seed=0)
        while True:
            try:
                self._loop()
                return  # normal stop
            except Exception:
                log.exception("decode loop crashed; restarting")
                policy.note_failure()
                self._m_restarts.inc()
                if policy.exhausted():
                    self._worker_died()
                    return
                time.sleep(policy.backoff.next_delay())

    def _worker_died(self):
        with self._cv:
            self._worker_dead = True
            pending, self._pending = self._pending, []
            if self._thread is threading.current_thread():
                self._thread = None
            self._cv.notify_all()
        log.error("decode loop exceeded its restart budget; engine "
                  "refuses new work (worker_dead)")
        exc = EngineClosedError(
            "DecodeEngine worker thread permanently failed "
            "(restart budget exhausted)")
        for req in pending:
            if not req.future.done():
                req.future.set_exception(exc)
        self._fail_dispatch_riders(exc)

    def _loop(self):
        while True:
            # deliberate thread-crash site: raises OUTSIDE the
            # per-iteration handler so only the supervisor catches it
            if faults.active():
                faults.check("decode.loop")
            with self._cv:
                while (not self._pending and self._active_n == 0
                       and not self._stopping):
                    self._cv.wait()
                if (self._stopping and not self._pending
                        and self._active_n == 0):
                    if self._thread is threading.current_thread():
                        self._thread = None
                    return
            try:
                self._admit_pending()
                if self._active_n > 0:
                    self._decode_once()
            except Exception as e:  # a dispatch fault must not strand
                # futures — but it fails only THIS dispatch's riders
                # (the active slots); queued requests stay queued and
                # are admitted fresh on the next iteration
                log.exception("decode dispatch failed; failing its "
                              "riders only")
                self._fail_dispatch_riders(e)
            self._reconcile_slots()

    def _fail_dispatch_riders(self, exc: Exception):
        """Fail + release only the sequences that rode the failed
        dispatch (every active slot); pending requests survive."""
        for slot, req in enumerate(list(self._slot_req)):
            if req is not None:
                if not req.future.done():
                    req.future.set_exception(exc)
                if req.ctx is not None:
                    record_disposition(req.ctx.trace_id, "engine_restart")
                self._release_slot(slot)

    def _reconcile_slots(self):
        """Slot-lifecycle assertion: every occupied slot must hold a
        rider whose future is still undelivered or just-finished — a
        cancelled/leaked rider is reclaimed here and counted, so a KV
        slot can never stay occupied forever (the regression the
        ``dl4j_decode_slot_leaks_total`` counter exists to catch)."""
        leaked = []
        with self._cv:
            occupied = sum(1 for r in self._slot_req if r is not None)
            if occupied != self._active_n:
                leaked.append(("accounting", occupied - self._active_n))
                self._active_n = occupied
        for slot, req in enumerate(list(self._slot_req)):
            if req is not None and req.future.cancelled():
                self._m_cancelled.inc()
                self._release_slot(slot)
        if leaked:
            self._m_slot_leaks.inc(abs(leaked[0][1]))
            log.warning("decode slot accounting drifted by %d; repaired",
                        leaked[0][1])

    def _admit_pending(self):
        """Fill free slots from the queue (the per-iteration join half of
        continuous batching: this runs between every decode step)."""
        while True:
            with self._cv:
                free = next((i for i, r in enumerate(self._slot_req)
                             if r is None), None)
                if free is None or not self._pending:
                    self._m_queue.set(len(self._pending))
                    return
                req = self._pending.pop(0)
            if req.expired():
                self._expire(req)
                continue
            try:
                self._start_request(req, free)
            except Exception as e:
                if not req.future.done():
                    req.future.set_exception(e)

    def _expire(self, req: _GenRequest):
        if not req.future.done():
            req.future.set_exception(TimeoutError(
                "generation deadline expired before a decode slot freed"))
        with self._stats_lock:
            self._stats["expired"] += 1
        self._m_expired.inc()
        if req.ctx is not None and self._reg.enabled:
            tracer().record("generation/queue_expired", req.t_submit,
                            time.perf_counter(), context=req.ctx,
                            prompt_tokens=int(req.prompt.size),
                            error="TimeoutError")

    def _start_request(self, req: _GenRequest, slot: int):
        """Prefill the request's prompt into ``slot`` and sample its first
        token (this is the TTFT-defining dispatch)."""
        T = int(req.prompt.size)
        bucket = bucket_for(T, self.ladder)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :T] = req.prompt
        t0 = time.perf_counter()
        tok = self._run_prefill(ids, slot=slot, length=T,
                                temperature=req.temperature,
                                top_k=req.top_k)
        req.t_first = time.perf_counter()
        with self._stats_lock:
            self._stats["prefills"] += 1
        if self._reg.enabled:
            self._m_ttft.observe(
                req.t_first - req.t_submit,
                exemplar=req.ctx.trace_id if req.ctx else None)
            if req.ctx is not None:
                tracer().record(
                    "generation/prefill", t0, req.t_first, context=req.ctx,
                    slot=slot, prompt_tokens=T, bucket=bucket,
                    queue_s=round(t0 - req.t_submit, 6))
        req.slot = slot
        with self._cv:
            self._slot_req[slot] = req
            self._active_n += 1
        self._m_active.set(self._active_n)
        self._tokens[slot] = tok
        self._lengths[slot] = T
        self._temps[slot] = req.temperature
        self._topks[slot] = req.top_k
        self._emit_token(req, tok)
        self._check_stop(req, slot, tok)

    def _decode_once(self):
        active = np.array([r is not None for r in self._slot_req])
        nxt = self._run_decode(active)
        with self._stats_lock:
            self._stats["decode_steps"] += 1
        self._m_steps.inc()
        for slot, req in enumerate(list(self._slot_req)):
            if req is None:
                continue
            self._lengths[slot] += 1
            tok = int(nxt[slot])
            self._tokens[slot] = tok
            self._emit_token(req, tok)
            self._check_stop(req, slot, tok)

    def _emit_token(self, req: _GenRequest, tok: int):
        req.tokens.append(tok)
        with self._stats_lock:
            self._stats["tokens"] += 1
        self._m_tokens.inc()
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:
                log.exception("on_token callback raised; token dropped "
                              "from the stream")

    def _check_stop(self, req: _GenRequest, slot: int, tok: int):
        reason = None
        if req.eos is not None and tok == req.eos:
            reason = "eos"
        elif len(req.tokens) >= req.max_tokens:
            reason = "length"
        elif int(self._lengths[slot]) >= self.max_ctx:
            reason = "length"   # context full: no cache row left to write
        if reason is not None:
            self._finish(req, slot, reason)

    def _finish(self, req: _GenRequest, slot: int, reason: str):
        t_done = time.perf_counter()
        if req.ctx is not None and self._reg.enabled:
            tracer().record("generation/decode", req.t_first or t_done,
                            t_done, context=req.ctx, slot=slot,
                            tokens=len(req.tokens), finish_reason=reason)
        self._release_slot(slot)
        ttft = ((req.t_first - req.t_submit)
                if req.t_first is not None else None)
        gen_s = t_done - (req.t_first or req.t_submit)
        if not req.future.done():
            req.future.set_result({
                "tokens": list(req.tokens),
                "finish_reason": reason,
                "prompt_tokens": int(req.prompt.size),
                "completion_tokens": len(req.tokens),
                "ttft_s": round(ttft, 6) if ttft is not None else None,
                "tokens_per_sec": round(len(req.tokens) / gen_s, 3)
                if gen_s > 0 else None,
            })

    def _release_slot(self, slot: int):
        with self._cv:
            if self._slot_req[slot] is not None:
                self._slot_req[slot] = None
                self._active_n -= 1
            # stale KV rows stay in the cache but lengths=0 masks them out
            # of every future attention (poison-value test)
            self._lengths[slot] = 0
            self._tokens[slot] = 0
            self._cv.notify_all()
        self._m_active.set(self._active_n)

    # -- lifecycle (registry-compatible) -----------------------------------
    @property
    def draining(self) -> bool:
        return self._draining and not self._closed

    @property
    def closed(self) -> bool:
        return self._closed

    def start(self):
        with self._cv:
            if self._closed:
                raise EngineClosedError(
                    "DecodeEngine is closed; it cannot be restarted")
            self._draining = False
        self._ensure_thread()
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admitting, finish queued + in-flight generations, stop the
        loop. Reversible via ``start()`` (the registry parks retired
        generative versions warm, same as predict engines)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            self._draining = True
            self._stopping = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._cv:
            leftovers, self._pending = self._pending, []
            drained = (self._active_n == 0
                       and (t is None or not t.is_alive()))
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(EngineClosedError(
                    "DecodeEngine drained before this request was "
                    "scheduled"))
        return drained

    def close(self, timeout_s: float = 30.0) -> bool:
        self._closed = True
        return self.drain(timeout_s)

    def stop(self):
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=30)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- introspection -----------------------------------------------------
    def observed_entries(self) -> List[dict]:
        """Manifest handoff compatibility: generative warmup is fully
        determined by (slots, max_ctx, ladder), so there is nothing to
        replay from observed traffic."""
        return []

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            s = dict(self._stats)
        with self._cv:
            s["active_slots"] = self._active_n
            s["queued"] = len(self._pending)
        s["slots"] = self.slots
        s["max_ctx"] = self.max_ctx
        s["prompt_buckets"] = list(self.ladder)
        return s
