"""Generative serving fast path: paged KV cache, batched prefill,
continuous batching, and speculative decoding.

The serving stack through PR 6 pads whole requests through a bucket ladder
and answers them one-shot — it cannot serve autoregressive traffic. This
module is the Orca (OSDI '22) per-iteration scheduling playbook plus the
vLLM/PagedAttention (SOSP '23) block-granular KV cache, plus Leviathan et
al. (2023) draft-model speculative decoding:

- **paged KV cache** — the cache is a block pool
  ``[num_blocks, layers, block_size, heads, head_dim]`` plus a per-slot
  block table, so a sequence only holds ``ceil(len/block_size)`` blocks
  instead of reserving ``max_ctx`` rows up front, and long/short requests
  share one memory budget. Admission is gated on free *blocks* (not just
  free slots), blocks are appended on demand as a sequence grows, and the
  block-table gather happens inside the jitted step so the executable set
  stays fixed. Block 0 is a scratch block: padding and inactive-slot
  writes land there and are masked out of every attention read. When the
  pool runs dry mid-decode the engine preempts the most recently admitted
  sequence (LIFO), returns its blocks, and requeues it at the head of the
  queue with its generated prefix — recompute-style preemption that keeps
  greedy output token-identical.
- **prefix-aware KV reuse** (RadixAttention, SGLang) — blocks are
  content-addressed by token prefix: the refcounted allocator plus a
  radix tree keyed on block-aligned token bytes let an admitted prompt
  attach the longest cached block run (refcount++) and prefill only the
  uncached tail (``paged_prefill``'s ``start_pos`` entry — same
  executables, zero steady-state recompiles). Completed/preempted
  requests *release* refs instead of freeing; their committed full blocks
  stay cached, so a shared system prompt is prefilled once per fleet
  replica and a multi-turn session's next turn re-attaches its whole
  history. Cold cached leaves are reclaimed LRU as the primary reclaim
  path (LIFO preemption stays the backstop); block-aligned sharing means
  a shared block is never written by an attacher — the copy-on-write
  fork is simply a fresh block at the divergence point. Greedy output is
  token-identical to cold prefill by construction. Gate with
  ``prefix_cache=`` / ``deploy(decode_prefix_cache=)`` /
  ``DL4J_TPU_PREFIX_CACHE``.
- **prefill/decode split with batched prefill** — queued prompts that pad
  to the same prompt bucket are coalesced into ONE fixed-shape jitted
  ``prefill`` dispatch (prompt padded up the bucket ladder, group padded
  up a batch ladder — the ``InferenceEngine`` micro-batcher pattern), so
  a burst of prompts costs one dispatch instead of one per prompt; every
  later token costs ONE jitted ``decode`` step shared by all active slots.
- **continuous batching** — requests join and leave the running decode
  batch *per token*: the loop admits pending requests into free slots
  between decode steps, so a short generation admitted after a long one
  finishes first instead of waiting behind it (no head-of-line blocking),
  and a finished slot is recycled immediately (its blocks return to the
  pool).
- **speculative decoding** — with a small draft model configured
  (``draft_model`` + ``spec_k``/``DL4J_TPU_SPEC_DRAFT_K``), each
  all-greedy decode iteration runs ONE jitted ``spec`` step: the draft
  proposes k tokens autoregressively, the target scores all k+1 positions
  in one cache-aware verify pass, and the accepted prefix (longest match
  against the target's own greedy choices, plus one free target token) is
  committed. Output is token-identical to non-speculative greedy by
  construction; sampling riders and near-context-full sequences fall back
  to the plain decode step.
- **sampling** — greedy (temperature 0), temperature, and top-k, all
  per-slot arrays inside the jitted step so mixed sampling configs share
  one executable; per-request ``max_tokens`` and EOS stop host-side.

All steps route through ``counted_jit`` with the cache(s) donated, so the
compile counter observes exactly ``len(prompt buckets) *
len(batch ladder) + 1 (+1 with speculation)`` executables after warmup
and steady-state decode performs **zero recompiles** — the acceptance
invariant of the ``generative_decode`` bench. Donated-cache entries are
store-ineligible by design (``runtime.compile_cache``): they record
``cache=bypass:donation`` on the compile-seconds histogram and rely on
the XLA backstop cache on accelerator backends.

Observability: ``dl4j_decode_requests_total``, ``dl4j_decode_tokens_total``,
``dl4j_decode_steps_total``, ``dl4j_decode_active_slots``,
``dl4j_decode_queue_depth``, ``dl4j_kv_blocks_free{model}``,
``dl4j_decode_preempted_total``, ``dl4j_spec_proposed_tokens_total`` /
``dl4j_spec_accepted_tokens_total``,
``dl4j_kv_prefix_{hits,misses,evictions}_total``,
``dl4j_kv_prefix_blocks{model}``, ``dl4j_decode_ttft_seconds{model}``
(exemplared with trace ids), ``dl4j_decode_itl_seconds{model}``
(inter-token latency), and the goodput split
``dl4j_tokens_total{model,slo=ok|violated}`` — a token is "good" when
its request's TTFT met the per-model latency objective
(``DL4J_TPU_SLO_LATENCY_MS``; with no objective set every token is ok). Each request's trace gains a
``generation/prefill`` span (queue wait + prompt dispatch, TTFT) and a
``generation/decode`` span (first token → finish), and its result
carries a ``phases`` dict (``queue_s``/``prefill_s``/``decode_s``) so
``/debug/requests`` reconstructs — and attributes — a generation's
timeline end to end; ``/debug/decode`` dumps the live slot map and
block tables.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import faults
from ..common.environment import environment
from ..common.locks import (ordered_condition, ordered_lock,
                            ordered_rlock)
from ..common.metrics import exponential_buckets, registry
from ..common.tracing import current_context, record_disposition, tracer
from .inference import (EngineClosedError, bucket_for, bucket_ladder,
                        counted_jit)

log = logging.getLogger(__name__)


def is_generative_model(model) -> bool:
    """Duck-typed generative-model protocol (``models.causal_lm.CausalLM``):
    the paged-cache trio ``init_paged_kv_cache`` / ``paged_prefill`` /
    ``paged_decode`` (what ``DecodeEngine`` actually serves from), the
    legacy slab trio ``init_kv_cache`` / ``prefill`` / ``decode``, plus a
    params pytree."""
    return all(callable(getattr(model, m, None))
               for m in ("init_kv_cache", "prefill", "decode",
                         "init_paged_kv_cache", "paged_prefill",
                         "paged_decode")) \
        and hasattr(model, "params")


def _cdiv(a: int, b: int) -> int:
    return -(-int(a) // int(b))


# ---------------------------------------------------------------------------
# sampling (runs inside the jitted steps: per-slot arrays, one executable)
# ---------------------------------------------------------------------------

def sample_tokens(logits, temperature, top_k, key):
    """Next-token sampling over ``logits`` [S, V] (f32).

    ``temperature`` [S]: <= 0 means greedy argmax for that slot.
    ``top_k`` [S]: <= 0 disables the top-k filter for that slot.
    Sampling uses the Gumbel-max trick so greedy/temperature/top-k all
    stay one fused program with fixed shapes.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    thr = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= thr, scaled, -jnp.inf)
    sampled = jnp.argmax(masked + jax.random.gumbel(key, logits.shape),
                         axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

class _GenRequest:
    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "eos",
                 "on_token", "future", "ctx", "deadline", "t_submit",
                 "t_first", "t_prefill0", "t_last", "tokens", "slot",
                 "prefix", "admit_seq", "reuse_nodes", "start")

    def __init__(self, prompt, max_tokens, temperature, top_k, eos,
                 on_token, deadline, ctx):
        self.prompt = prompt              # np.int32 [T]
        self.max_tokens = max_tokens
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos = eos                    # int or None
        self.on_token = on_token
        self.future: Future = Future()
        self.ctx = ctx                    # submitter's TraceContext
        self.deadline = deadline          # monotonic instant or None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        # phase boundaries for per-request latency decomposition:
        # queue = [t_submit, t_prefill0), prefill = [t_prefill0,
        # t_first), decode = [t_first, finish). t_last is the previous
        # token's emit instant (the inter-token-latency basis).
        self.t_prefill0: Optional[float] = None
        self.t_last: Optional[float] = None
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        # the rows a prefill must (re)compute: the prompt, extended with
        # every generated token when the request is preempted/requeued
        self.prefix = prompt              # np.int32 [>=T]
        self.admit_seq = -1               # LIFO preemption order
        # prefix-cache attachment planned at admission: the radix nodes
        # whose blocks this request shares, covering rows [0, start)
        self.reuse_nodes: List = []
        self.start = 0

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline


class _BlockAllocator:
    """Refcounted free-list allocator over KV-pool block ids ``1..total``
    (block 0 is the scratch block and is never handed out). A block is
    freed only when its refcount reaches zero: a slot's block table holds
    one ref per appearance, and the radix prefix cache holds one per
    cached node — so a completed request *releases* shared blocks instead
    of freeing them. Callers hold the engine's scheduler lock around
    every operation."""

    def __init__(self, total: int):
        self.total = int(total)
        self._free = list(range(self.total, 0, -1))  # pop() yields 1 first
        self._refs: Dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._refs)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def ref(self, block: int) -> int:
        return self._refs.get(int(block), 0)

    def incref(self, ids) -> None:
        """Add one ref per id (attaching a cached block to another owner).
        Unknown ids are ignored — only live blocks can be shared."""
        for b in ids:
            b = int(b)
            if b in self._refs:
                self._refs[b] += 1

    def decref(self, ids) -> int:
        """Drop one ref per id; a block reaching zero returns to the
        pool. Unknown ids and id 0 are ignored (the reconcile pass
        repairs, it must never corrupt). Returns how many blocks were
        actually freed."""
        n = 0
        for b in ids:
            b = int(b)
            r = self._refs.get(b)
            if r is None:
                continue
            if r <= 1:
                del self._refs[b]
                self._free.append(b)
                n += 1
            else:
                self._refs[b] = r - 1
        return n

    # the historical name: releasing a plain (refcount-1) allocation is
    # exactly a decref
    free = decref

    def refcounts(self) -> Dict[int, int]:
        return dict(self._refs)

    def reset_to(self, expected) -> None:
        """Rebuild so exactly ``expected`` is outstanding
        (block-accounting repair): a ``{block: refcount}`` mapping, or a
        bare iterable of ids meaning refcount 1 each."""
        if not isinstance(expected, dict):
            expected = {int(b): 1 for b in expected}
        self._refs = {int(b): int(r) for b, r in expected.items()
                      if 0 < int(b) <= self.total and int(r) > 0}
        self._free = [b for b in range(self.total, 0, -1)
                      if b not in self._refs]


class _RadixNode:
    """One cached block: ``key`` is the block's exact token bytes,
    ``block`` the pool block id holding those rows' KV. ``refs`` counts
    the slots currently attached through this node (0 = evictable once
    it is a leaf); ``digest`` is the chained prefix hash shown by
    ``/debug/decode``."""
    __slots__ = ("key", "digest", "block", "parent", "children", "refs",
                 "last_used")

    def __init__(self, key: bytes, digest: str, block: int, parent):
        self.key = key
        self.digest = digest
        self.block = int(block)
        self.parent = parent
        self.children: Dict[bytes, "_RadixNode"] = {}
        self.refs = 0
        self.last_used = 0


class _RadixCache:
    """Radix tree over block-aligned token prefixes (RadixAttention,
    SGLang): depth ``d`` holds a sequence's ``d``-th full KV block, keyed
    by that block's exact token bytes — content-addressing by value, so
    two requests sharing a system prompt resolve to the same nodes and
    hash collisions are impossible (the sha1 ``digest`` chain is debug
    display only). The tree holds one allocator ref per cached block;
    attached slots add theirs on top. All mutations happen under the
    engine's scheduler lock."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self.root = _RadixNode(b"", "", 0, None)
        self._nodes: set = set()
        self._clock = 0
        self.evictions = 0          # lifetime LRU evictions

    @property
    def size(self) -> int:
        return len(self._nodes)

    def nodes(self) -> List[_RadixNode]:
        return list(self._nodes)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, tokens) -> List[_RadixNode]:
        """Longest run of cached full blocks prefixing ``tokens`` (walked
        from the root); bumps the run's LRU stamps."""
        out: List[_RadixNode] = []
        node = self.root
        bs = self.block_size
        n = int(len(tokens))
        i = 0
        while i + bs <= n:
            child = node.children.get(tokens[i:i + bs].tobytes())
            if child is None:
                break
            out.append(child)
            node = child
            i += bs
        t = self._tick()
        for nd in out:
            nd.last_used = t
        return out

    def insert(self, tokens, blocks) -> List[_RadixNode]:
        """Record ``blocks[j]`` as the cached KV for token rows
        ``[j*bs, (j+1)*bs)``. Existing nodes win — a duplicate block
        (two identical prompts prefilled cold in one group) stays owned
        by its slot and is freed on release. Returns the newly created
        nodes; the caller takes the tree's allocator ref on each."""
        import hashlib

        node = self.root
        bs = self.block_size
        created: List[_RadixNode] = []
        t = self._tick()
        for j, block in enumerate(blocks):
            if (j + 1) * bs > len(tokens):
                break
            key = tokens[j * bs:(j + 1) * bs].tobytes()
            child = node.children.get(key)
            if child is None:
                digest = hashlib.sha1(
                    node.digest.encode() + key).hexdigest()[:12]
                child = _RadixNode(key, digest, int(block), node)
                node.children[key] = child
                self._nodes.add(child)
                created.append(child)
            child.last_used = t
            node = child
        return created

    def lru_leaf(self) -> Optional[_RadixNode]:
        """Least-recently-used unattached leaf (the next LRU eviction
        victim), or None when nothing is evictable."""
        best = None
        for nd in self._nodes:
            if nd.children or nd.refs > 0:
                continue
            if best is None or nd.last_used < best.last_used:
                best = nd
        return best

    def remove(self, node: _RadixNode) -> None:
        node.parent.children.pop(node.key, None)
        self._nodes.discard(node)

    def reclaimable_count(self, exclude=(), ref_fn=None) -> int:
        """Blocks reclaimable by cascading leaf eviction: nodes whose
        entire subtree is unattached (and not in ``exclude`` — admission
        excludes the nodes a forming prefill group is about to attach).
        ``ref_fn(block)`` is the allocator refcount: a node whose block
        is still owned elsewhere (an active slot inserted it) can be
        *removed* but frees nothing, so it is not counted."""
        ex = set(exclude)

        def walk(nd):
            n, all_ok = 0, True
            for ch in nd.children.values():
                cn, ok = walk(ch)
                n += cn
                all_ok = all_ok and ok
            if all_ok and nd.refs == 0 and nd not in ex:
                frees = ref_fn is None or ref_fn(nd.block) <= 1
                return (n + 1 if frees else n), True
            return n, False

        return sum(walk(ch)[0] for ch in self.root.children.values())


def _shard_kv_pool(mesh, cache_tree):
    """Commit a paged KV pool over the mesh: the heads dim (axis 3 of the
    ``[blocks, layers, block_size, heads, head_dim]`` pool) shards over
    the ``model`` axis when divisible, everything else replicates —
    attention is head-parallel, so each device owns its heads' KV bytes
    end to end."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..common.mesh import MODEL

    size = int(mesh.shape[MODEL]) if MODEL in mesh.axis_names else 1

    def place(leaf):
        if (size > 1 and getattr(leaf, "ndim", 0) == 5
                and leaf.shape[3] % size == 0):
            spec = P(None, None, None, MODEL, None)
        else:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, cache_tree)


class DecodeEngine:
    """Continuous-batching autoregressive decode engine over one model,
    serving from a paged (block-granular) KV cache.

    - ``generate(prompt, ...) -> Future`` resolving to a result dict
      (``tokens``, ``finish_reason``, ``ttft_s``, token counts); an
      optional ``on_token`` callback streams tokens as they are sampled.
    - ``warmup()`` pre-compiles one prefill executable per (prompt bucket,
      batch rung) pair plus the decode-step executable (plus the
      speculative step when a draft model is configured).
    - ``drain()/close()/start()`` mirror ``InferenceEngine`` lifecycle so
      the serving registry hot-swaps/parks generative versions the same
      way it does predict engines.

    ``slots`` bounds concurrent sequences (``DL4J_TPU_DECODE_SLOTS``);
    ``max_ctx`` bounds prompt+generation length per sequence
    (``DL4J_TPU_DECODE_MAX_CTX``, capped by the model's position table);
    ``kv_block_size`` (``DL4J_TPU_KV_BLOCK_SIZE``) sets the block
    granularity — clamped to ``max_ctx``, so setting it >= max_ctx
    reproduces the legacy slab layout; ``kv_blocks`` sizes the pool
    (default: slab-equivalent, ``slots * ceil(max_ctx/block_size)``);
    ``prefill_batch`` caps how many same-bucket prompts share one prefill
    dispatch; ``draft_model`` + ``spec_k`` (``DL4J_TPU_SPEC_DRAFT_K``)
    enable greedy speculative decoding; ``prefix_cache``
    (``DL4J_TPU_PREFIX_CACHE``, default on) enables content-addressed
    KV-block reuse across requests and turns.
    """

    def __init__(self, model, *, slots: Optional[int] = None,
                 max_ctx: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_token: Optional[int] = None, seed: int = 0,
                 kv_block_size: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 prefill_batch: Optional[int] = None,
                 draft_model=None, spec_k: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 model_name: str = "default",
                 mesh=None, param_spec=None):
        if not is_generative_model(model):
            raise TypeError(
                f"cannot decode a {type(model).__name__}: expected the "
                "generative-model protocol (init_paged_kv_cache/"
                "paged_prefill/paged_decode)")
        env = environment()
        self.model = model
        self.model_name = str(model_name)
        self.slots = int(slots if slots is not None else env.decode_slots())
        max_ctx = int(max_ctx if max_ctx is not None
                      else env.decode_max_ctx())
        pos_cap = getattr(getattr(model, "config", None),
                          "max_position_embeddings", None)
        if pos_cap:
            max_ctx = min(max_ctx, int(pos_cap))
        self.max_ctx = max_ctx
        # prompt-length bucket ladder: one prefill executable per rung.
        # The top rung always covers max_ctx: a preempted rider re-enters
        # the queue with prompt+generated as its prefix, which can exceed
        # the largest explicit bucket (but never max_ctx), and must still
        # be admittable.
        self.ladder = bucket_ladder(self.max_ctx, prompt_buckets)
        if self.ladder[-1] < self.max_ctx:
            self.ladder = self.ladder + (self.max_ctx,)
        # paged-cache geometry: block size clamps to the context window
        # (block_size == max_ctx -> one block per sequence == slab layout)
        bs = int(kv_block_size if kv_block_size is not None
                 else env.kv_block_size())
        self.block_size = max(1, min(bs, self.max_ctx))
        self.max_blocks = _cdiv(self.max_ctx, self.block_size)  # per slot
        pool = int(kv_blocks if kv_blocks is not None
                   else self.slots * self.max_blocks)
        self.kv_blocks = max(1, pool)
        # batched prefill: group same-bucket prompts up a batch ladder
        pb = int(prefill_batch if prefill_batch is not None
                 else min(4, self.slots))
        self.prefill_batch = max(1, min(pb, self.slots))
        self.batch_ladder = bucket_ladder(self.prefill_batch)
        # speculative decoding: draft proposes spec_k tokens per step
        k = int(spec_k if spec_k is not None else env.spec_draft_k())
        self.spec_k = max(0, k)
        self.draft = draft_model
        if self.draft is not None and not is_generative_model(self.draft):
            raise TypeError(
                f"draft_model {type(self.draft).__name__} does not "
                "implement the generative-model protocol")
        self._spec_enabled = self.draft is not None and self.spec_k >= 1
        self.eos_token = eos_token
        self._seed = int(seed)
        self._params = model.params
        # +1: block 0 is the scratch block for padding/inactive writes
        self._cache = model.init_paged_kv_cache(self.kv_blocks + 1,
                                                self.block_size)
        self._dparams = self.draft.params if self._spec_enabled else None
        self._dcache = (self.draft.init_paged_kv_cache(
            self.kv_blocks + 1, self.block_size)
            if self._spec_enabled else None)
        # tensor-parallel decode: params shard over the model axis and the
        # paged KV pool shards over its heads dim (replicated fallback when
        # heads do not divide); jit propagates the committed shardings into
        # the donated prefill/decode steps. mesh=None: single-device path.
        self.mesh = mesh
        self.param_spec = param_spec
        if mesh is not None:
            from ..common.mesh import shard_params, validate_mesh
            validate_mesh(mesh)
            self._params = shard_params(mesh, self._params, param_spec)
            self._cache = _shard_kv_pool(mesh, self._cache)
            if self._spec_enabled:
                self._dparams = shard_params(mesh, self._dparams, param_spec)
                self._dcache = _shard_kv_pool(mesh, self._dcache)
        self._step = 0
        # per-slot host state (the loop thread owns it)
        S = self.slots
        self._tokens = np.zeros(S, np.int32)
        self._lengths = np.zeros(S, np.int32)
        self._temps = np.zeros(S, np.float32)
        self._topks = np.zeros(S, np.int32)
        self._tables = np.zeros((S, self.max_blocks), np.int32)
        self._nblocks = np.zeros(S, np.int32)
        self._alloc = _BlockAllocator(self.kv_blocks)
        # content-addressed prefix reuse over the block pool
        # (DL4J_TPU_PREFIX_CACHE / deploy(decode_prefix_cache=))
        pc = (prefix_cache if prefix_cache is not None
              else env.prefix_cache_enabled())
        self._prefix_cache = bool(pc)
        self._radix = _RadixCache(self.block_size)
        self._slot_nodes: List[List[_RadixNode]] = [[] for _ in range(S)]
        self._slot_req: List[Optional[_GenRequest]] = [None] * S
        self._active_n = 0
        self._admit_counter = 0
        # dispatch serialization: warmup and the loop both step the cache
        self._dispatch_lock = ordered_rlock("decode.dispatch")
        self._warmed: set = set()
        # scheduler state
        self._cv = ordered_condition("decode.scheduler")
        self._pending: List[_GenRequest] = []
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._draining = False
        self._closed = False
        # resilience: supervised-loop state + watchdog-readable dispatch
        # timestamp (serving/resilience.py polls these from outside)
        self._worker_dead = False
        self._dispatch_started_at: Optional[float] = None
        # registry-compat surface (manifest machinery is predict-only)
        self.max_batch = self.slots
        self.manifest_path = None
        self._stats_lock = ordered_lock("decode.stats")
        self._stats = {"requests": 0, "tokens": 0, "decode_steps": 0,
                       "prefills": 0, "prefill_dispatches": 0,
                       "prefill_rows": 0, "expired": 0, "preempted": 0,
                       "spec_steps": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "prefix_hits": 0,
                       "prefix_misses": 0, "prefix_reused_rows": 0}
        self._build_steps()
        reg = registry()
        self._reg = reg
        self._m_requests = reg.counter(
            "dl4j_decode_requests_total",
            "Generation requests accepted by DecodeEngine.generate()")
        self._m_tokens = reg.counter(
            "dl4j_decode_tokens_total",
            "Tokens sampled across prefill + decode steps")
        self._m_steps = reg.counter(
            "dl4j_decode_steps_total",
            "Batched decode dispatches (plain single-token + speculative)")
        self._m_active = reg.gauge(
            "dl4j_decode_active_slots",
            "Sequences currently occupying a decode slot")
        self._m_queue = reg.gauge(
            "dl4j_decode_queue_depth",
            "Generation requests waiting for a free slot")
        self._m_blocks_free = reg.gauge(
            "dl4j_kv_blocks_free",
            "Free KV-cache blocks in the paged decode pool",
            labels=("model",)).labels(model=self.model_name)
        self._m_blocks_free.set(self._alloc.free_count)
        self._m_ttft = reg.histogram(
            "dl4j_decode_ttft_seconds",
            "Time from generate() to the first sampled token",
            labels=("model",),
            buckets=exponential_buckets(1e-3, 2.0, 18)).labels(
                model=self.model_name)
        self._m_itl = reg.histogram(
            "dl4j_decode_itl_seconds",
            "Inter-token latency: gap between consecutive sampled "
            "tokens of one request (the decode-phase tail a reader "
            "actually feels)",
            labels=("model",),
            buckets=exponential_buckets(1e-4, 2.0, 18)).labels(
                model=self.model_name)
        goodput = reg.counter(
            "dl4j_tokens_total",
            "Goodput: tokens emitted, split by whether the owning "
            "request's TTFT met the per-model latency objective "
            "(DL4J_TPU_SLO_LATENCY_MS; no objective -> every token ok)",
            labels=("model", "slo"))
        self._m_tok_ok = goodput.labels(model=self.model_name, slo="ok")
        self._m_tok_violated = goodput.labels(model=self.model_name,
                                              slo="violated")
        self._slo_latency_s = env.slo_latency_s()
        self._m_expired = reg.counter(
            "dl4j_decode_expired_total",
            "Generation requests whose deadline expired before a slot")
        self._m_restarts = reg.counter(
            "dl4j_engine_restarts_total",
            "Supervised engine worker-thread restarts after a crash",
            labels=("engine",)).labels(engine="decode")
        self._m_slot_leaks = reg.counter(
            "dl4j_decode_slot_leaks_total",
            "KV-cache slots found leaked (occupied without a live rider) "
            "and reclaimed by the per-iteration accounting check")
        self._m_block_leaks = reg.counter(
            "dl4j_kv_block_leaks_total",
            "KV-pool blocks whose allocator accounting drifted from the "
            "slot block tables and were repaired by the reconcile pass")
        self._m_cancelled = reg.counter(
            "dl4j_decode_cancelled_total",
            "Riders whose future was cancelled mid-decode; their slot is "
            "freed immediately")
        self._m_preempted = reg.counter(
            "dl4j_decode_preempted_total",
            "Sequences preempted (blocks reclaimed, requeued for "
            "recompute) because the KV block pool ran dry mid-decode")
        self._m_spec_proposed = reg.counter(
            "dl4j_spec_proposed_tokens_total",
            "Draft tokens proposed by speculative decode steps")
        self._m_spec_accepted = reg.counter(
            "dl4j_spec_accepted_tokens_total",
            "Draft tokens accepted (verified equal to the target model's "
            "greedy choice) by speculative decode steps")
        self._m_prefix_hits = reg.counter(
            "dl4j_kv_prefix_hits_total",
            "Admitted prompts that attached at least one cached KV block "
            "from the radix prefix cache (tail-only prefill)")
        self._m_prefix_misses = reg.counter(
            "dl4j_kv_prefix_misses_total",
            "Admitted prompts that found no cached KV prefix and "
            "prefilled cold")
        self._m_prefix_evictions = reg.counter(
            "dl4j_kv_prefix_evictions_total",
            "Cached KV blocks reclaimed from the radix prefix cache "
            "(LRU leaf eviction — the primary reclaim path)")
        self._m_prefix_blocks = reg.gauge(
            "dl4j_kv_prefix_blocks",
            "KV-pool blocks currently held by the radix prefix cache",
            labels=("model",)).labels(model=self.model_name)

    # -- jitted steps ------------------------------------------------------
    def _build_steps(self):
        model = self.model
        draft = self.draft if self._spec_enabled else None
        k = self.spec_k

        def prefill_fn(params, cache, ids, tables, lengths, starts, temps,
                       top_ks, seed, step):
            # starts [B]: rows already committed by attached prefix-cache
            # blocks — the dispatch prefills only the tail (all-zero for
            # a cold prefill; traced, so warm and cold tails share one
            # executable per (bucket, batch) rung)
            cache, logits = model.paged_prefill(params, cache, ids,
                                                tables, lengths, starts)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            toks = sample_tokens(logits, temps, top_ks, key)
            return cache, toks

        def prefill_draft_fn(params, dparams, cache, dcache, ids, tables,
                             lengths, starts, temps, top_ks, seed, step):
            # the draft cache must hold the same committed rows as the
            # target's, so the draft prefills inside the same dispatch
            cache, logits = model.paged_prefill(params, cache, ids,
                                                tables, lengths, starts)
            dcache, _ = draft.paged_prefill(dparams, dcache, ids, tables,
                                            lengths, starts)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            toks = sample_tokens(logits, temps, top_ks, key)
            return cache, dcache, toks

        def decode_fn(params, cache, tables, tokens, lengths, active,
                      temps, top_ks, seed, step):
            cache, logits = model.paged_decode(params, cache, tables,
                                               tokens[:, None], lengths)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            nxt = sample_tokens(logits[:, 0], temps, top_ks, key)
            return cache, jnp.where(active, nxt, tokens)

        def spec_fn(params, dparams, cache, dcache, tables, tokens,
                    lengths, active):
            # greedy-only speculative step (Leviathan et al., 2023):
            # draft proposes k tokens one at a time (k+1 steps — the last
            # is write-only so the draft cache covers every row the
            # target may commit), the target verifies all k+1 positions
            # in ONE cache-aware pass, and the longest drafted prefix
            # matching the target's own greedy choices is committed plus
            # one free target token. Rejected rows are overwritten by the
            # next dispatch's writes before any mask admits them.
            S = tokens.shape[0]
            prev = tokens
            drafted = []
            for j in range(k + 1):
                dcache, dlogits = draft.paged_decode(
                    dparams, dcache, tables, prev[:, None], lengths + j)
                if j < k:
                    prev = jnp.argmax(dlogits[:, 0, :],
                                      axis=-1).astype(jnp.int32)
                    drafted.append(prev)
            d = jnp.stack(drafted, axis=1)                      # [S, k]
            verify_in = jnp.concatenate([tokens[:, None], d], axis=1)
            cache, vlogits = model.paged_decode(params, cache, tables,
                                                verify_in, lengths)
            g = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)  # [S, k+1]
            match = (d == g[:, :k]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [S]
            idx = jnp.arange(k + 1)[None, :]
            g_at = jnp.take_along_axis(g, n_acc[:, None], axis=1)
            pad_d = jnp.concatenate(
                [d, jnp.zeros((S, 1), jnp.int32)], axis=1)
            commit = jnp.where(idx < n_acc[:, None], pad_d,
                               jnp.where(idx == n_acc[:, None], g_at, 0))
            n_commit = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
            return cache, dcache, commit, n_commit

        # the KV cache(s) are donated: each step consumes the previous
        # buffers in place (on backends that honor donation) — these
        # entries are deliberately ineligible for the raw executable store
        # and show up as cache=bypass:donation on dl4j_compile_seconds
        # (see compile_cache docs)
        # a quantized twin (quant/transforms.quantize_model) carries
        # _precision — suffix the tag so its executables never collide with
        # the full-precision model's in the persistent store (the first tag
        # segment stays "prefill"/"decode"/"spec": it is the kind metric
        # label)
        prec = getattr(model, "_precision", None)
        suffix = f":{prec}" if prec else ""
        if self._spec_enabled:
            self._prefill = counted_jit(prefill_draft_fn,
                                        "prefill" + suffix,
                                        donate_argnums=(2, 3))
            self._spec = counted_jit(spec_fn, "spec" + suffix,
                                     donate_argnums=(2, 3))
        else:
            self._prefill = counted_jit(prefill_fn, "prefill" + suffix,
                                        donate_argnums=(1,))
            self._spec = None
        self._decode = counted_jit(decode_fn, "decode" + suffix,
                                   donate_argnums=(1,))

    def _run_prefill(self, ids, tables, lengths, starts, temps, top_ks):
        """One batched prefill dispatch: ``ids`` [B, Tb] padded prompt
        *tails*, ``tables`` [B, MB] the target slots' block tables,
        ``lengths`` [B] real total prompt lengths, ``starts`` [B] rows
        already committed by attached cached blocks (0 = cold). Returns
        the B first sampled tokens."""
        if faults.active():
            faults.check("decode.prefill", batch=ids.shape[0],
                         bucket=ids.shape[1])
        with self._dispatch_lock:
            self._dispatch_started_at = time.monotonic()
            try:
                args = (jnp.asarray(ids), jnp.asarray(tables),
                        jnp.asarray(lengths),
                        jnp.asarray(starts, jnp.int32),
                        jnp.asarray(temps, jnp.float32),
                        jnp.asarray(top_ks, jnp.int32),
                        jnp.asarray(self._seed, jnp.int32),
                        jnp.asarray(self._step, jnp.int32))
                if self._spec_enabled:
                    cache, dcache, toks = self._prefill(
                        self._params, self._dparams, self._cache,
                        self._dcache, *args)
                    self._dcache = dcache
                else:
                    cache, toks = self._prefill(self._params, self._cache,
                                                *args)
                self._cache = cache
                self._step += 1
            finally:
                self._dispatch_started_at = None
        return np.asarray(toks)

    def _run_decode(self, active):
        if faults.active():
            faults.check("decode.step", active=int(np.sum(active)))
        with self._dispatch_lock:
            self._dispatch_started_at = time.monotonic()
            try:
                cache, nxt = self._decode(
                    self._params, self._cache, jnp.asarray(self._tables),
                    jnp.asarray(self._tokens), jnp.asarray(self._lengths),
                    jnp.asarray(active), jnp.asarray(self._temps),
                    jnp.asarray(self._topks),
                    jnp.asarray(self._seed, jnp.int32),
                    jnp.asarray(self._step, jnp.int32))
                self._cache = cache
                self._step += 1
            finally:
                self._dispatch_started_at = None
        return np.asarray(nxt)

    def _run_spec(self, active):
        if faults.active():
            faults.check("decode.step", active=int(np.sum(active)),
                         spec=True)
        with self._dispatch_lock:
            self._dispatch_started_at = time.monotonic()
            try:
                cache, dcache, commit, n_commit = self._spec(
                    self._params, self._dparams, self._cache,
                    self._dcache, jnp.asarray(self._tables),
                    jnp.asarray(self._tokens), jnp.asarray(self._lengths),
                    jnp.asarray(active))
                self._cache = cache
                self._dcache = dcache
                self._step += 1
            finally:
                self._dispatch_started_at = None
        return np.asarray(commit), np.asarray(n_commit)

    # -- warmup ------------------------------------------------------------
    def warmup(self, example=None,
               batch_sizes: Optional[Sequence[int]] = None,
               **_ignored) -> List[int]:
        """Compile the ladder before traffic: one prefill executable per
        (prompt bucket, batch rung) pair + the decode-step executable
        (+ the speculative step when enabled). Idempotent. Warmup rows
        use the scratch block table (all zeros) so no live block is
        touched. (``example``/``batch_sizes`` are accepted for
        registry-warmup signature compatibility and ignored: the shapes
        are fixed by the engine's own configuration.)

        ``runtime.warm_image --generative`` runs exactly this warmup to
        pre-bake the ladder into a shared artifact dir; a fleet joiner
        with ``DL4J_TPU_REMOTE_CACHE`` set then pulls the prefill
        executables instead of compiling them. The donated-KV decode
        step is raw-store-ineligible (see ``compile_cache``): it loads
        from the baked ``xla/`` backstop on accelerators and recompiles
        on CPU — bounded at one executable."""
        with self._cv:
            if self._active_n > 0:
                raise RuntimeError(
                    "warmup() while sequences are active would overwrite "
                    "live KV rows; warm before taking traffic")
        warmed = []
        for b in self.ladder:
            for bb in self.batch_ladder:
                key = ("prefill", bb, b)
                if key not in self._warmed:
                    self._run_prefill(np.zeros((bb, b), np.int32),
                                      np.zeros((bb, self.max_blocks),
                                               np.int32),
                                      np.ones(bb, np.int32),
                                      np.zeros(bb, np.int32),
                                      np.zeros(bb, np.float32),
                                      np.zeros(bb, np.int32))
                    self._warmed.add(key)
            warmed.append(b)
        if "decode" not in self._warmed:
            self._run_decode(np.zeros(self.slots, bool))
            self._warmed.add("decode")
        if self._spec_enabled and "spec" not in self._warmed:
            self._run_spec(np.zeros(self.slots, bool))
            self._warmed.add("spec")
        return warmed

    # -- request intake ----------------------------------------------------
    def generate(self, prompt, *, max_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_token="default", on_token: Optional[Callable] = None,
                 timeout_s: Optional[float] = None) -> Future:
        """Enqueue one generation request; returns a Future resolving to
        ``{"tokens", "finish_reason", "ttft_s", "prompt_tokens",
        "completion_tokens", "tokens_per_sec", "phases"}`` — ``phases``
        decomposes the request's latency into
        ``queue_s``/``prefill_s``/``decode_s``.

        ``timeout_s`` bounds the wait for a decode *slot* (admission into
        the running batch), not the generation itself; an expired request
        fails with ``TimeoutError`` before any model work. ``on_token``
        is called from the decode loop with each sampled token id
        (streaming). ``eos_token="default"`` uses the engine's configured
        EOS; ``None`` disables the stop."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("prompt must contain at least one token")
        if ids.size >= self.max_ctx:
            raise ValueError(
                f"prompt length {ids.size} leaves no room to generate "
                f"within max_ctx {self.max_ctx}")
        cap = self.max_ctx - int(ids.size)
        if max_tokens is None:
            max_tokens = min(environment().decode_max_tokens(), cap)
        max_tokens = max(1, min(int(max_tokens), cap))
        worst = self._blocks_for(int(ids.size) + max_tokens)
        if worst > self._alloc.total:
            raise ValueError(
                f"request may need {worst} KV blocks "
                f"(prompt {ids.size} + max_tokens {max_tokens}, "
                f"block_size {self.block_size}) but the pool holds only "
                f"{self._alloc.total}; raise kv_blocks or lower "
                "max_tokens")
        eos = self.eos_token if eos_token == "default" else eos_token
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        req = _GenRequest(ids, max_tokens, temperature, top_k, eos,
                          on_token, deadline, current_context())
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                raise EngineClosedError(
                    "DecodeEngine is "
                    + ("closed" if self._closed else
                       "draining" if self._draining else
                       "dead (worker restart budget exhausted)")
                    + "; it no longer accepts requests")
            self._pending.append(req)
            depth = len(self._pending)
            self._cv.notify_all()
        with self._stats_lock:
            self._stats["requests"] += 1
        self._m_requests.inc()
        self._m_queue.set(depth)
        self._ensure_thread()
        return req.future

    def generate_sync(self, prompt, **kw) -> Dict[str, Any]:
        return self.generate(prompt, **kw).result()

    # -- the continuous-batching loop --------------------------------------
    def _ensure_thread(self):
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                return
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._loop_main, name="dl4j-tpu-decode-loop",
                    daemon=True)
                self._thread.start()

    @property
    def worker_dead(self) -> bool:
        """True once the supervised decode loop exhausted its restart
        budget (the watchdog reports this engine unhealthy)."""
        return self._worker_dead

    def _loop_main(self):
        """Supervised decode loop: a crash that escapes the per-iteration
        handler (scheduler-state corruption, not a dispatch fault) is
        counted and the loop restarts with exponential backoff + jitter
        instead of silently killing generation for every later request.
        A crash burst past ``DL4J_TPU_ENGINE_MAX_RESTARTS`` declares the
        worker dead and fails everything queued."""
        policy = faults.RetryPolicy(
            max_restarts=environment().engine_max_restarts(),
            base_s=0.01, max_s=2.0, seed=0)
        while True:
            try:
                self._loop()
                return  # normal stop
            except Exception:
                log.exception("decode loop crashed; restarting")
                policy.note_failure()
                self._m_restarts.inc()
                if policy.exhausted():
                    self._worker_died()
                    return
                time.sleep(policy.backoff.next_delay())

    def _worker_died(self):
        with self._cv:
            self._worker_dead = True
            pending, self._pending = self._pending, []
            if self._thread is threading.current_thread():
                self._thread = None
            self._cv.notify_all()
        log.error("decode loop exceeded its restart budget; engine "
                  "refuses new work (worker_dead)")
        exc = EngineClosedError(
            "DecodeEngine worker thread permanently failed "
            "(restart budget exhausted)")
        for req in pending:
            if not req.future.done():
                req.future.set_exception(exc)
        self._fail_dispatch_riders(exc)

    def _loop(self):
        while True:
            # deliberate thread-crash site: raises OUTSIDE the
            # per-iteration handler so only the supervisor catches it
            if faults.active():
                faults.check("decode.loop")
            with self._cv:
                while (not self._pending and self._active_n == 0
                       and not self._stopping):
                    self._cv.wait()
                if (self._stopping and not self._pending
                        and self._active_n == 0):
                    if self._thread is threading.current_thread():
                        self._thread = None
                    return
            try:
                self._admit_pending()
                if self._active_n > 0:
                    self._decode_once()
            except Exception as e:  # a dispatch fault must not strand
                # futures — but it fails only THIS dispatch's riders
                # (the active slots); queued requests stay queued and
                # are admitted fresh on the next iteration
                log.exception("decode dispatch failed; failing its "
                              "riders only")
                self._fail_dispatch_riders(e)
            self._reconcile_slots()

    def _fail_dispatch_riders(self, exc: Exception):
        """Fail + release only the sequences that rode the failed
        dispatch (every active slot); pending requests survive."""
        for slot, req in enumerate(list(self._slot_req)):
            if req is not None:
                if not req.future.done():
                    req.future.set_exception(exc)
                if req.ctx is not None:
                    record_disposition(req.ctx.trace_id, "engine_restart")
                self._release_slot(slot)

    def _reconcile_slots(self):
        """Slot- and block-lifecycle assertion: every occupied slot must
        hold a rider whose future is still undelivered or just-finished,
        and the allocator's outstanding-block set must equal the union of
        the occupied slots' block tables — a cancelled/leaked rider or a
        drifted block is reclaimed here and counted, so a KV slot (or
        pool block) can never stay occupied forever (the regressions the
        ``dl4j_decode_slot_leaks_total`` / ``dl4j_kv_block_leaks_total``
        counters exist to catch)."""
        leaked = []
        with self._cv:
            occupied = sum(1 for r in self._slot_req if r is not None)
            if occupied != self._active_n:
                leaked.append(("accounting", occupied - self._active_n))
                self._active_n = occupied
        for slot, req in enumerate(list(self._slot_req)):
            if req is not None and req.future.cancelled():
                self._m_cancelled.inc()
                self._release_slot(slot)
        if leaked:
            self._m_slot_leaks.inc(abs(leaked[0][1]))
            log.warning("decode slot accounting drifted by %d; repaired",
                        leaked[0][1])
        block_drift = 0
        with self._cv:
            # a free slot must hold zero blocks and zero cache
            # attachments; a crashed/cancelled rider's blocks are
            # *decref'd* (not freed): a block shared with the radix cache
            # or another slot survives with its remaining refs
            for slot, req in enumerate(self._slot_req):
                nb = int(self._nblocks[slot])
                if req is None and (nb > 0 or self._slot_nodes[slot]):
                    block_drift += nb
                    self._alloc.decref(self._tables[slot, :nb])
                    for nd in self._slot_nodes[slot]:
                        nd.refs = max(0, nd.refs - 1)
                    self._slot_nodes[slot] = []
                    self._tables[slot, :] = 0
                    self._nblocks[slot] = 0
            # expected refcounts: one per appearance in an occupied
            # slot's table + one per radix-cache node
            expected: Dict[int, int] = {}
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                for b in self._tables[slot, :int(self._nblocks[slot])]:
                    expected[int(b)] = expected.get(int(b), 0) + 1
            for nd in self._radix.nodes():
                expected[nd.block] = expected.get(nd.block, 0) + 1
            actual = self._alloc.refcounts()
            if expected != actual:
                block_drift += len(
                    {b for b in set(expected) | set(actual)
                     if expected.get(b, 0) != actual.get(b, 0)})
                self._alloc.reset_to(expected)
            # node attachment counts must mirror the slots' lists
            want_refs: Dict[int, int] = {}
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                for nd in self._slot_nodes[slot]:
                    want_refs[id(nd)] = want_refs.get(id(nd), 0) + 1
            for nd in self._radix.nodes():
                want = want_refs.get(id(nd), 0)
                if nd.refs != want:
                    block_drift += 1
                    nd.refs = want
            free = self._alloc.free_count
            cached = self._radix.size
        self._m_blocks_free.set(free)
        self._m_prefix_blocks.set(cached)
        if block_drift:
            self._m_block_leaks.inc(block_drift)
            log.warning("KV block accounting drifted by %d blocks; "
                        "repaired", block_drift)

    # -- block accounting --------------------------------------------------
    def _blocks_for(self, rows: int) -> int:
        """Blocks a sequence needs to hold ``rows`` KV rows (capped at the
        per-slot maximum — a row index can never reach max_ctx)."""
        return _cdiv(min(int(rows), self.max_ctx), self.block_size)

    def _grow_slot(self, slot: int, rows: int) -> bool:
        """Extend ``slot``'s block table to cover ``rows`` rows, evicting
        LRU cached leaves when the free list alone cannot satisfy it;
        returns False when the pool cannot satisfy it at all. Caller
        holds ``_cv``."""
        need = self._blocks_for(rows)
        have = int(self._nblocks[slot])
        if need <= have:
            return True
        n = need - have
        if n > self._alloc.free_count:
            self._evict_for(n)
        got = self._alloc.alloc(n)
        if got is None:
            return False
        self._tables[slot, have:need] = got
        self._nblocks[slot] = need
        return True

    def _evict_for(self, n: int) -> int:
        """LRU-evict unattached radix leaves until ``n`` blocks are free
        (the primary reclaim path — LIFO preemption stays the backstop
        when the cache has nothing left to give). Removing a leaf can
        expose its parent as the next candidate, so whole cold chains
        unwind oldest-first. Caller holds ``_cv``."""
        evicted = 0
        while self._alloc.free_count < n:
            leaf = self._radix.lru_leaf()
            if leaf is None:
                break
            self._radix.remove(leaf)
            self._alloc.decref([leaf.block])
            evicted += 1
        if evicted:
            self._radix.evictions += evicted
            self._m_prefix_evictions.inc(evicted)
            self._m_prefix_blocks.set(self._radix.size)
        return evicted

    def _available_blocks(self, exclude=()) -> int:
        """Blocks obtainable without preempting anyone: the free list
        plus everything LRU eviction could actually free. Caller holds
        ``_cv``."""
        return self._alloc.free_count + self._radix.reclaimable_count(
            exclude, self._alloc.ref)

    def _match_prefix(self, req: _GenRequest):
        """Longest cached full-block run prefixing ``req.prefix``, capped
        so at least one tail token remains to prefill (the logits of the
        request's first generated token must come from a real dispatch).
        Returns ``(nodes, rows)``. Caller holds ``_cv``."""
        if not self._prefix_cache:
            return [], 0
        nodes = self._radix.match(req.prefix)
        max_rows = len(req.prefix) - 1
        while nodes and len(nodes) * self.block_size > max_rows:
            nodes.pop()
        return nodes, len(nodes) * self.block_size

    def _attach_nodes(self, slot: int, req: _GenRequest) -> None:
        """Share the matched cached blocks into ``slot``'s table:
        refcount++ on each block, attachment++ on each node (pinning it
        against eviction). The request then prefills only its tail — the
        shared blocks are never written (tail and decode rows land in
        blocks allocated at the divergence point: the copy-on-write
        fork). Caller holds ``_cv``."""
        k = len(req.reuse_nodes)
        if k == 0:
            return
        blocks = [nd.block for nd in req.reuse_nodes]
        self._tables[slot, :k] = blocks
        self._nblocks[slot] = k
        self._alloc.incref(blocks)
        for nd in req.reuse_nodes:
            nd.refs += 1
        self._slot_nodes[slot] = list(req.reuse_nodes)

    def _cache_slot_prefix(self, slot: int, req: _GenRequest) -> None:
        """Insert the slot's committed full blocks into the radix tree
        (tree takes one allocator ref per newly cached block) so a later
        request — or this rider itself after a preemption — can
        re-attach them instead of re-prefilling. Caller holds ``_cv``."""
        if not self._prefix_cache:
            return
        committed = int(self._lengths[slot])
        full = committed // self.block_size
        if full <= 0:
            return
        seq = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)]
        ).astype(np.int32)[:committed]
        blocks = [int(b) for b in self._tables[slot, :full]]
        for nd in self._radix.insert(seq, blocks):
            self._alloc.incref([nd.block])
        self._m_prefix_blocks.set(self._radix.size)

    def _blocks_deficit(self, horizon: int) -> int:
        """Additional pool blocks the active set needs so every rider can
        write ``horizon`` more rows. Caller holds ``_cv``."""
        deficit = 0
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            need = self._blocks_for(int(self._lengths[slot]) + horizon)
            deficit += max(0, need - int(self._nblocks[slot]))
        return deficit

    def _ensure_blocks(self, horizon: int):
        """Guarantee every active rider owns blocks for its next
        ``horizon`` rows, preempting the most recently admitted rider
        (LIFO recompute: blocks returned, request requeued at the queue
        head with its generated prefix) when the pool runs dry."""
        while True:
            victim = failed = None
            with self._cv:
                if self._blocks_deficit(horizon) <= self._available_blocks():
                    for slot, req in enumerate(self._slot_req):
                        if req is not None:
                            ok = self._grow_slot(
                                slot, int(self._lengths[slot]) + horizon)
                            assert ok, "deficit accounting went stale"
                    self._m_blocks_free.set(self._alloc.free_count)
                    return
                riders = [(req.admit_seq, slot, req)
                          for slot, req in enumerate(self._slot_req)
                          if req is not None]
                if len(riders) <= 1:
                    # nothing left to preempt: the pool genuinely cannot
                    # host this sequence (generate() validation makes
                    # this unreachable; keep the guard for drifted state)
                    failed = (riders[0][1], riders[0][2])
                else:
                    _, vslot, vreq = max(riders)
                    victim = (vslot, vreq)
            if failed is not None:
                slot, req = failed
                if not req.future.done():
                    req.future.set_exception(RuntimeError(
                        "KV block pool exhausted with no rider left "
                        "to preempt; raise kv_blocks"))
                self._release_slot(slot)
                return
            self._preempt(*victim)

    def _preempt(self, slot: int, req: _GenRequest):
        """Recompute-preemption: drop ``req`` from its slot, return its
        blocks, and requeue it at the queue head with prompt + generated
        tokens as the new prefill prefix (greedy output stays
        token-identical: a prefill over the full prefix yields the same
        next-token argmax the decode path would have). The victim's
        committed full blocks are first inserted into the radix cache, so
        on re-admit the regrown prefix re-attaches them (refcount++) and
        the re-prefill covers only the uncached tail — unless pool
        pressure LRU-evicted them meanwhile, in which case it recomputes
        from scratch exactly as before."""
        with self._cv:
            if self._slot_req[slot] is not req:
                return
            req.prefix = np.concatenate(
                [req.prompt,
                 np.asarray(req.tokens, np.int32)]).astype(np.int32)
            self._pending.insert(0, req)
            depth = len(self._pending)
            self._cache_slot_prefix(slot, req)
        self._release_slot(slot)
        req.slot = None
        with self._stats_lock:
            self._stats["preempted"] += 1
        self._m_preempted.inc()
        self._m_queue.set(depth)
        log.info("preempted slot %d (seq len %d) for KV blocks; requeued",
                 slot, len(req.prefix))

    # -- admission ---------------------------------------------------------
    def _admit_pending(self):
        """Fill free slots from the queue (the per-iteration join half of
        continuous batching: this runs between every decode step).
        Each queued prompt first walks the radix prefix cache: the
        longest cached block run is attached (refcount++) and only the
        uncached *tail* is prefilled, so requests are coalesced by TAIL
        bucket — a warm multi-turn prompt and a fresh short prompt can
        share one dispatch. Admission is capped by free slots, available
        blocks (free + LRU-evictable cached), and ``prefill_batch``; the
        queue head is always first in its group, so admission order
        cannot starve."""
        while True:
            expired: List[_GenRequest] = []
            group: List[_GenRequest] = []
            slots_for: List[int] = []
            bucket = None
            with self._cv:
                while self._pending and self._pending[0].expired():
                    expired.append(self._pending.pop(0))
                free_slots = [i for i, r in enumerate(self._slot_req)
                              if r is None]
                self._m_queue.set(len(self._pending))
                if self._pending and free_slots:
                    head = self._pending[0]
                    h_nodes, h_start = self._match_prefix(head)
                    bucket = bucket_for(len(head.prefix) - h_start,
                                        self.ladder)
                    # blocks promised to the group so far; matched nodes
                    # are pinned out of the evictable budget (attachment
                    # below makes the pin real before any eviction runs)
                    pinned: set = set()
                    committed = 0
                    need = (self._blocks_for(len(head.prefix) + 1)
                            - len(h_nodes))
                    if bucket is not None and need <= \
                            self._available_blocks(set(h_nodes)):
                        head.reuse_nodes, head.start = h_nodes, h_start
                        pinned.update(h_nodes)
                        committed += need
                        group.append(head)
                        cap = min(len(free_slots), self.prefill_batch)
                        for req in self._pending[1:]:
                            if len(group) >= cap:
                                break
                            if req.expired():
                                expired.append(req)
                                continue
                            r_nodes, r_start = self._match_prefix(req)
                            if bucket_for(len(req.prefix) - r_start,
                                          self.ladder) != bucket:
                                continue
                            need = (self._blocks_for(len(req.prefix) + 1)
                                    - len(r_nodes))
                            if committed + need > self._available_blocks(
                                    pinned | set(r_nodes)):
                                continue
                            req.reuse_nodes, req.start = r_nodes, r_start
                            pinned.update(r_nodes)
                            committed += need
                            group.append(req)
                        for req in group + expired:
                            if req in self._pending:
                                self._pending.remove(req)
                        slots_for = free_slots[:len(group)]
                        # attach every member's cached run BEFORE any
                        # grow: attachment pins the nodes, so one
                        # member's eviction can never free a block
                        # another member matched
                        for req, slot in zip(group, slots_for):
                            self._attach_nodes(slot, req)
                        for req, slot in zip(group, slots_for):
                            ok = self._grow_slot(slot,
                                                 len(req.prefix) + 1)
                            assert ok, "admission budget went stale"
                        self._m_blocks_free.set(self._alloc.free_count)
                        self._m_queue.set(len(self._pending))
            for req in expired:
                self._expire(req)
            if not group:
                return
            try:
                self._start_group(group, slots_for, bucket)
            except Exception as e:
                for req, slot in zip(group, slots_for):
                    if not req.future.done():
                        req.future.set_exception(e)
                    with self._cv:
                        blks = self._tables[slot,
                                            :int(self._nblocks[slot])]
                        self._alloc.decref(blks)
                        for nd in self._slot_nodes[slot]:
                            nd.refs = max(0, nd.refs - 1)
                        self._slot_nodes[slot] = []
                        self._tables[slot, :] = 0
                        self._nblocks[slot] = 0
                        self._m_blocks_free.set(self._alloc.free_count)
                    if self._slot_req[slot] is req:
                        self._release_slot(slot)
                return

    def _expire(self, req: _GenRequest):
        if not req.future.done():
            req.future.set_exception(TimeoutError(
                "generation deadline expired before a decode slot freed"))
        with self._stats_lock:
            self._stats["expired"] += 1
        self._m_expired.inc()
        if req.ctx is not None and self._reg.enabled:
            tracer().record("generation/queue_expired", req.t_submit,
                            time.perf_counter(), context=req.ctx,
                            prompt_tokens=int(req.prompt.size),
                            error="TimeoutError")

    def _start_group(self, group: List[_GenRequest], slots: List[int],
                     bucket: int):
        """Prefill a same-TAIL-bucket group of prompts in ONE dispatch
        (padded up the batch ladder; padding rows write the scratch
        block) and sample each request's first token (the TTFT-defining
        dispatch). A member with an attached cached prefix ships only its
        uncached tail — ``starts[r]`` rows are already committed in its
        shared blocks."""
        B = len(group)
        bb = bucket_for(B, self.batch_ladder)
        ids = np.zeros((bb, bucket), np.int32)
        tables = np.zeros((bb, self.max_blocks), np.int32)
        lengths = np.ones(bb, np.int32)
        starts = np.zeros(bb, np.int32)
        temps = np.zeros(bb, np.float32)
        topks = np.zeros(bb, np.int32)
        for r, (req, slot) in enumerate(zip(group, slots)):
            p = req.prefix
            s = int(req.start)
            tail = p[s:]
            ids[r, :tail.size] = tail
            tables[r] = self._tables[slot]
            lengths[r] = p.size
            starts[r] = s
            temps[r] = req.temperature
            topks[r] = req.top_k
        t0 = time.perf_counter()
        toks = self._run_prefill(ids, tables, lengths, starts, temps,
                                 topks)
        t_done = time.perf_counter()
        hits = sum(1 for req in group if req.start > 0)
        reused = int(sum(req.start for req in group))
        with self._stats_lock:
            self._stats["prefills"] += B
            self._stats["prefill_dispatches"] += 1
            self._stats["prefill_rows"] += int(
                sum(len(req.prefix) - req.start for req in group))
            if self._prefix_cache:
                self._stats["prefix_hits"] += hits
                self._stats["prefix_misses"] += B - hits
                self._stats["prefix_reused_rows"] += reused
        if self._prefix_cache:
            if hits:
                self._m_prefix_hits.inc(hits)
            if B - hits:
                self._m_prefix_misses.inc(B - hits)
        for r, (req, slot) in enumerate(zip(group, slots)):
            tok = int(toks[r])
            first = req.t_first is None
            if req.t_prefill0 is None:
                # first prefill dispatch closes the queue phase; a
                # preempted rider keeps its original boundary so queue
                # attribution stays honest across requeues
                req.t_prefill0 = t0
            if first:
                req.t_first = t_done
            if self._reg.enabled:
                if first:
                    self._m_ttft.observe(
                        req.t_first - req.t_submit,
                        exemplar=req.ctx.trace_id if req.ctx else None)
                if req.ctx is not None:
                    tracer().record(
                        "generation/prefill", t0, t_done, context=req.ctx,
                        slot=slot, prompt_tokens=int(req.prefix.size),
                        cached_tokens=int(req.start),
                        bucket=bucket, batch=B,
                        queue_s=round(t0 - req.t_submit, 6))
            req.slot = slot
            with self._cv:
                self._admit_counter += 1
                req.admit_seq = self._admit_counter
                self._slot_req[slot] = req
                self._active_n += 1
            self._m_active.set(self._active_n)
            self._tokens[slot] = tok
            self._lengths[slot] = int(req.prefix.size)
            self._temps[slot] = req.temperature
            self._topks[slot] = req.top_k
            with self._cv:
                # publish the just-committed prompt blocks: a storm
                # follower sharing this prompt attaches them while this
                # rider is still decoding (decode writes land strictly
                # past the prefix, never inside a published block)
                self._cache_slot_prefix(slot, req)
            self._emit_token(req, tok)
            self._check_stop(req, slot, tok)

    # -- decode ------------------------------------------------------------
    def _spec_ready(self) -> bool:
        """True when this iteration can take the speculative step: every
        active rider is greedy and has k+1 rows of context headroom, and
        the pool can cover the k+1-row write horizon without preempting
        anyone (speculation is a throughput luxury — it must never evict
        a rider that plain decode could serve)."""
        if not self._spec_enabled:
            return False
        k = self.spec_k
        with self._cv:
            riders = [slot for slot, r in enumerate(self._slot_req)
                      if r is not None]
            if not riders:
                return False
            for slot in riders:
                if self._temps[slot] > 0:
                    return False
                if int(self._lengths[slot]) + k + 1 > self.max_ctx:
                    return False
            return self._blocks_deficit(k + 1) <= self._available_blocks()

    def _decode_once(self):
        spec = self._spec_ready()
        self._ensure_blocks(self.spec_k + 1 if spec else 1)
        active = np.array([r is not None for r in self._slot_req])
        if not active.any():
            return
        if spec:
            self._spec_once(active)
        else:
            nxt = self._run_decode(active)
            with self._stats_lock:
                self._stats["decode_steps"] += 1
            self._m_steps.inc()
            for slot, req in enumerate(list(self._slot_req)):
                if req is None:
                    continue
                self._lengths[slot] += 1
                tok = int(nxt[slot])
                self._tokens[slot] = tok
                self._emit_token(req, tok)
                self._check_stop(req, slot, tok)

    def _spec_once(self, active):
        commit, n_commit = self._run_spec(active)
        k = self.spec_k
        n_active = int(np.sum(active))
        accepted = int(np.sum(np.maximum(n_commit[active] - 1, 0)))
        with self._stats_lock:
            self._stats["decode_steps"] += 1
            self._stats["spec_steps"] += 1
            self._stats["spec_proposed"] += k * n_active
            self._stats["spec_accepted"] += accepted
        self._m_steps.inc()
        self._m_spec_proposed.inc(k * n_active)
        self._m_spec_accepted.inc(accepted)
        for slot, req in enumerate(list(self._slot_req)):
            if req is None:
                continue
            for j in range(int(n_commit[slot])):
                tok = int(commit[slot, j])
                self._lengths[slot] += 1
                self._tokens[slot] = tok
                self._emit_token(req, tok)
                self._check_stop(req, slot, tok)
                if self._slot_req[slot] is not req:
                    break  # finished mid-prefix: drop the rest

    def _emit_token(self, req: _GenRequest, tok: int):
        req.tokens.append(tok)
        with self._stats_lock:
            self._stats["tokens"] += 1
        self._m_tokens.inc()
        if self._reg.enabled:
            now = time.perf_counter()
            if req.t_last is not None:
                self._m_itl.observe(now - req.t_last)
            req.t_last = now
            # goodput: every token of a request whose TTFT met the
            # latency objective counts as slo=ok; a late first token
            # taints the whole request's tokens. No configured
            # objective (slo_latency_s() -> None) means nothing can
            # violate — mirrors SLOTracker.
            obj = self._slo_latency_s
            ttft = (req.t_first - req.t_submit) \
                if req.t_first is not None else None
            (self._m_tok_ok if obj is None
             or (ttft is not None and ttft <= obj)
             else self._m_tok_violated).inc()
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:
                log.exception("on_token callback raised; token dropped "
                              "from the stream")

    def _check_stop(self, req: _GenRequest, slot: int, tok: int):
        reason = None
        if req.eos is not None and tok == req.eos:
            reason = "eos"
        elif len(req.tokens) >= req.max_tokens:
            reason = "length"
        elif int(self._lengths[slot]) >= self.max_ctx:
            reason = "length"   # context full: no cache row left to write
        if reason is not None:
            self._finish(req, slot, reason)

    def _finish(self, req: _GenRequest, slot: int, reason: str):
        t_done = time.perf_counter()
        if req.ctx is not None and self._reg.enabled:
            tracer().record("generation/decode", req.t_first or t_done,
                            t_done, context=req.ctx, slot=slot,
                            tokens=len(req.tokens), finish_reason=reason)
        with self._cv:
            # cache prompt + generated full blocks for the session's next
            # turn (the client re-sends its history: the warm turn
            # attaches these and prefills only the new user tail)
            self._cache_slot_prefix(slot, req)
        self._release_slot(slot)
        ttft = ((req.t_first - req.t_submit)
                if req.t_first is not None else None)
        gen_s = t_done - (req.t_first or req.t_submit)
        phases = {
            "queue_s": round(req.t_prefill0 - req.t_submit, 6)
            if req.t_prefill0 is not None else None,
            "prefill_s": round(req.t_first - req.t_prefill0, 6)
            if req.t_first is not None and req.t_prefill0 is not None
            else None,
            "decode_s": round(t_done - req.t_first, 6)
            if req.t_first is not None else None,
        }
        if not req.future.done():
            req.future.set_result({
                "tokens": list(req.tokens),
                "finish_reason": reason,
                "prompt_tokens": int(req.prompt.size),
                "completion_tokens": len(req.tokens),
                "ttft_s": round(ttft, 6) if ttft is not None else None,
                "tokens_per_sec": round(len(req.tokens) / gen_s, 3)
                if gen_s > 0 else None,
                "phases": phases,
            })

    def _release_slot(self, slot: int):
        with self._cv:
            if self._slot_req[slot] is not None:
                self._slot_req[slot] = None
                self._active_n -= 1
            # the slot RELEASES its blocks (refcount--): a block cached
            # in the radix tree or shared with another slot survives
            # with its remaining refs, the rest return to the pool.
            # Stale KV rows stay in freed blocks but lengths=0 + a
            # zeroed table masks them out of every future attention
            # (poison-value test)
            nb = int(self._nblocks[slot])
            if nb > 0:
                self._alloc.decref(self._tables[slot, :nb])
                self._tables[slot, :] = 0
                self._nblocks[slot] = 0
            for nd in self._slot_nodes[slot]:
                nd.refs = max(0, nd.refs - 1)
            self._slot_nodes[slot] = []
            self._lengths[slot] = 0
            self._tokens[slot] = 0
            free = self._alloc.free_count
            self._cv.notify_all()
        self._m_active.set(self._active_n)
        self._m_blocks_free.set(free)

    # -- lifecycle (registry-compatible) -----------------------------------
    @property
    def draining(self) -> bool:
        return self._draining and not self._closed

    @property
    def closed(self) -> bool:
        return self._closed

    def start(self):
        with self._cv:
            if self._closed:
                raise EngineClosedError(
                    "DecodeEngine is closed; it cannot be restarted")
            self._draining = False
        self._ensure_thread()
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admitting, finish queued + in-flight generations, stop the
        loop. Reversible via ``start()`` (the registry parks retired
        generative versions warm, same as predict engines)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            self._draining = True
            self._stopping = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._cv:
            leftovers, self._pending = self._pending, []
            drained = (self._active_n == 0
                       and (t is None or not t.is_alive()))
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(EngineClosedError(
                    "DecodeEngine drained before this request was "
                    "scheduled"))
        return drained

    def close(self, timeout_s: float = 30.0) -> bool:
        self._closed = True
        return self.drain(timeout_s)

    def stop(self):
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=30)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- introspection -----------------------------------------------------
    def observed_entries(self) -> List[dict]:
        """Manifest handoff compatibility: generative warmup is fully
        determined by (slots, max_ctx, ladder, batch ladder), so there is
        nothing to replay from observed traffic."""
        return []

    def debug_snapshot(self) -> Dict[str, Any]:
        """Live slot map + block tables for ``GET /debug/decode`` and the
        flight recorder: which sequence owns which slot, how many rows it
        committed, and which pool blocks back it — plus the ``kernels``
        section: which attention/dequant path served the last dispatch
        (kernel name, chosen path, fallback reason), straight from
        ``kernels.dispatch_snapshot()``. Dispatch happens at trace time,
        so that section describes the executables this process compiled,
        not per-request routing."""
        with self._cv:
            slots = []
            for slot, req in enumerate(self._slot_req):
                nb = int(self._nblocks[slot])
                entry = {"slot": slot, "active": req is not None,
                         "length": int(self._lengths[slot]),
                         "blocks": [int(b)
                                    for b in self._tables[slot, :nb]]}
                if req is not None:
                    entry.update({
                        "prompt_tokens": int(req.prompt.size),
                        "generated": len(req.tokens),
                        "temperature": req.temperature,
                        "trace_id": req.ctx.trace_id if req.ctx else None,
                    })
                slots.append(entry)
            snap = {
                "model": self.model_name,
                "slots": slots,
                "queue_depth": len(self._pending),
                "pool": {"block_size": self.block_size,
                         "total_blocks": self._alloc.total,
                         "free_blocks": self._alloc.free_count,
                         "max_blocks_per_slot": self.max_blocks,
                         "scratch_block": 0},
                "prefix_cache": {
                    "enabled": self._prefix_cache,
                    "cached_blocks": self._radix.size,
                    "evictions": self._radix.evictions,
                    # most-recently-used first, bounded for the endpoint
                    "nodes": [{"digest": nd.digest, "block": nd.block,
                               "refs": nd.refs,
                               "children": len(nd.children),
                               "last_used": nd.last_used}
                              for nd in sorted(
                                  self._radix.nodes(),
                                  key=lambda n: -n.last_used)[:64]],
                },
                "prefill": {"batch": self.prefill_batch,
                            "buckets": list(self.ladder),
                            "batch_ladder": list(self.batch_ladder)},
                "speculative": {"enabled": self._spec_enabled,
                                "k": self.spec_k},
                "worker_dead": self._worker_dead,
                "draining": self._draining,
                "closed": self._closed,
            }
            try:
                from ..kernels import dispatch_snapshot
                snap["kernels"] = dispatch_snapshot()
            except Exception:
                snap["kernels"] = {}
            if self.mesh is not None:
                from ..common.mesh import mesh_shape, spec_desc
                snap["mesh_shape"] = mesh_shape(self.mesh)
                snap["param_spec"] = spec_desc(self.param_spec)
        with self._stats_lock:
            snap["speculative"]["proposed"] = self._stats["spec_proposed"]
            snap["speculative"]["accepted"] = self._stats["spec_accepted"]
            prop = self._stats["spec_proposed"]
            snap["speculative"]["acceptance_rate"] = (
                round(self._stats["spec_accepted"] / prop, 4)
                if prop else None)
        return snap

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            s = dict(self._stats)
        with self._cv:
            s["active_slots"] = self._active_n
            s["queued"] = len(self._pending)
            s["kv_blocks_free"] = self._alloc.free_count
            s["prefix_cached_blocks"] = self._radix.size
            s["prefix_evictions"] = self._radix.evictions
        s["prefix_cache"] = self._prefix_cache
        s["slots"] = self.slots
        s["max_ctx"] = self.max_ctx
        s["prompt_buckets"] = list(self.ladder)
        s["kv_block_size"] = self.block_size
        s["kv_blocks"] = self.kv_blocks
        s["prefill_batch"] = self.prefill_batch
        s["spec_k"] = self.spec_k if self._spec_enabled else 0
        if s["spec_proposed"]:
            s["spec_acceptance"] = round(
                s["spec_accepted"] / s["spec_proposed"], 4)
        return s
