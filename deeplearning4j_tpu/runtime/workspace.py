"""MemoryWorkspace API shims (reference workspace compatibility surface).

Reference: `org/nd4j/linalg/api/memory/MemoryWorkspace.java:28` (scoped
arena allocator, AutoCloseable), `WorkspaceConfiguration` policies, and the
DL4J `LayerWorkspaceMgr` routing. SURVEY §7: "Workspaces — not needed (XLA
arena + donation); keep API as no-op shims for compatibility."

On TPU, XLA owns device memory: buffers live in HBM arenas planned at
compile time, donation reuses them in place, and there is nothing for a
user-level arena to manage. These shims preserve the reference's scoping
API (code written against `try (MemoryWorkspace ws = ...)` patterns ports
cleanly) while recording usage statistics for observability parity.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

_thread_local = threading.local()


@dataclasses.dataclass
class WorkspaceConfiguration:
    """Reference WorkspaceConfiguration builder fields (accepted, advisory)."""
    initial_size: int = 0
    max_size: int = 0
    overallocation_limit: float = 0.0
    policy_allocation: str = "OVERALLOCATE"   # reference AllocationPolicy
    policy_spill: str = "REALLOCATE"
    policy_learning: str = "FIRST_LOOP"
    policy_mirroring: str = "FULL"

    @staticmethod
    def builder() -> "_WSConfigBuilder":
        return _WSConfigBuilder()


class _WSConfigBuilder:
    def __init__(self):
        self._kw = {}

    def initial_size(self, v):
        self._kw["initial_size"] = v
        return self

    def max_size(self, v):
        self._kw["max_size"] = v
        return self

    def policy_allocation(self, v):
        self._kw["policy_allocation"] = v
        return self

    def policy_learning(self, v):
        self._kw["policy_learning"] = v
        return self

    def build(self) -> WorkspaceConfiguration:
        return WorkspaceConfiguration(**self._kw)


class MemoryWorkspace:
    """Scoped workspace shim: context manager like the reference's
    AutoCloseable. Allocation is a no-op (XLA arena); enter/exit and
    generation counters behave like the reference for code parity."""

    def __init__(self, config: WorkspaceConfiguration = None,
                 workspace_id: str = "WS"):
        self.config = config or WorkspaceConfiguration()
        self.id = workspace_id
        self.generation = 0
        self._open = False

    # reference: notifyScopeEntered / notifyScopeLeft
    def __enter__(self) -> "MemoryWorkspace":
        self._open = True
        stack = _ws_stack()
        stack.append(self)
        return self

    def __exit__(self, *exc):
        self._open = False
        self.generation += 1
        stack = _ws_stack()
        if stack and stack[-1] is self:
            stack.pop()
        return False

    def notify_scope_entered(self):
        return self.__enter__()

    def notify_scope_left(self):
        self.__exit__()

    def is_scope_active(self) -> bool:
        return self._open

    # reference tagOutOfScopeUse / current offset introspection — constants
    # here because XLA owns the actual arena
    def get_current_size(self) -> int:
        return 0

    def get_current_offset(self) -> int:
        return 0


class DummyWorkspace(MemoryWorkspace):
    """Reference DummyWorkspace: the no-workspace workspace."""


def _ws_stack():
    if not hasattr(_thread_local, "stack"):
        _thread_local.stack = []
    return _thread_local.stack


class Nd4jWorkspaceManager:
    """`Nd4j.getWorkspaceManager()` analog — thread-scoped named workspaces."""

    def __init__(self):
        self._spaces: Dict[str, MemoryWorkspace] = {}

    def get_workspace_for_current_thread(
            self, config: WorkspaceConfiguration = None,
            workspace_id: str = "WS") -> MemoryWorkspace:
        key = f"{threading.get_ident()}/{workspace_id}"
        if key not in self._spaces:
            self._spaces[key] = MemoryWorkspace(config, workspace_id)
        return self._spaces[key]

    def get_and_activate_workspace(self, config=None, workspace_id="WS"):
        ws = self.get_workspace_for_current_thread(config, workspace_id)
        return ws.__enter__()

    @staticmethod
    def current_workspace() -> Optional[MemoryWorkspace]:
        stack = _ws_stack()
        return stack[-1] if stack else None

    @staticmethod
    def assert_no_workspaces_open(msg: str = "workspaces still open"):
        """Reference WorkspaceUtils.assertNoWorkspacesOpen."""
        if _ws_stack():
            raise AssertionError(msg)


workspace_manager = Nd4jWorkspaceManager()


class LayerWorkspaceMgr:
    """DL4J `nn/workspace/LayerWorkspaceMgr` shim: per-array-type routing
    (ACTIVATIONS / ACT_GRAD / FF_WORKING_MEM / BP_WORKING_MEM / RNN_*).
    All types route to the XLA arena; `leverage_to` is identity."""

    TYPES = ("ACTIVATIONS", "ACTIVATION_GRAD", "FF_WORKING_MEM",
             "BP_WORKING_MEM", "RNN_FF_LOOP_WORKING_MEM",
             "RNN_BP_LOOP_WORKING_MEM", "INPUT", "FF_CACHE")

    def __init__(self, workspace_mode: str = "ENABLED"):
        self.mode = workspace_mode

    @staticmethod
    def no_workspaces() -> "LayerWorkspaceMgr":
        return LayerWorkspaceMgr("NONE")

    @staticmethod
    def builder() -> "LayerWorkspaceMgr":
        return LayerWorkspaceMgr()

    def build(self) -> "LayerWorkspaceMgr":
        return self

    def with_no_layer_workspaces(self) -> "LayerWorkspaceMgr":
        self.mode = "NONE"
        return self

    def create(self, array_type: str, shape, dtype="float32"):
        import jax.numpy as jnp
        return jnp.zeros(shape, dtype)

    def leverage_to(self, array_type: str, array):
        return array

    def validate_array_location(self, array_type: str, array):
        return True
