"""Shape-bucketed compiled inference engine with dynamic micro-batching.

Reference: `org/deeplearning4j/parallelism/ParallelInference.java` (worker
threads + `batchLimit`/`queueLimit` request coalescing) and the Clipper/
Orca-style adaptive-batching serving literature.

The TPU problem it solves: every executable frontend here jits on exact
input shapes, so a serving stream with mixed batch sizes (1, 3, 7, 17, ...)
spends its time retracing/recompiling in XLA instead of on the MXU. The fix
is the standard serving recipe:

- **bucket ladder** — incoming batches are zero-padded up the batch dim to
  the next bucket (default: powers of two up to ``max_batch``), so at most
  ``ceil(log2(max_batch)) + 1`` executables ever compile; padded rows are
  sliced off the result. Row-independent inference (every layer-API forward
  at ``training=False``) makes the sliced rows value-identical to an
  exact-shape run.
- **warmup** — pre-compiles the bucket set before traffic arrives.
- **dynamic micro-batching** — ``submit()`` returns a Future; a background
  thread coalesces concurrent requests within a ``max_delay_ms`` /
  ``max_batch`` window into ONE padded device dispatch and resolves each
  future with its unpadded slice.

The same bucketing is wired into the direct ``output()``/``predict()``
paths of MultiLayerNetwork / ComputationGraph / SameDiff via
``maybe_pad_tree`` (gated by ``Environment.inference_bucketing``, on by
default); every jitted inference entry routes through ``counted_jit`` so
``Environment.compile_count()`` observes one event per newly compiled
input signature.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..common import faults
from ..common.environment import environment
from ..common.locks import ordered_condition, ordered_lock
from ..common.metrics import linear_buckets, registry
from ..common.tracing import (current_context, record_disposition, span,
                              tracer, use_context)


# ---------------------------------------------------------------------------
# bucket ladder + padding primitives
# ---------------------------------------------------------------------------

def bucket_ladder(max_batch: int,
                  buckets: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
    """The sorted bucket set: explicit `buckets` if given, else powers of
    two up to (and always including) `max_batch`."""
    if buckets:
        out = sorted({int(b) for b in buckets if int(b) > 0})
        if not out:
            raise ValueError("bucket ladder is empty")
        return tuple(out)
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(out)


def bucket_for(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds the ladder."""
    for b in ladder:
        if b >= n:
            return b
    return None


def pad_batch(x, target: int):
    """Zero-pad the leading (batch) dim of `x` up to `target` rows."""
    n = x.shape[0]
    if n == target:
        return x
    widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths)


def _leading_dim(tree) -> Optional[int]:
    """Shared leading dim of every array leaf, or None if leaves disagree /
    any leaf is unbatched (scalar) / there are no leaves."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return None
    n = None
    for leaf in leaves:
        if getattr(leaf, "ndim", 0) < 1:
            return None
        if n is None:
            n = leaf.shape[0]
        elif leaf.shape[0] != n:
            return None
    return n


def maybe_pad_tree(tree, *, training: bool = False, mesh=None):
    """Environment-gated bucket padding for the direct output() paths.

    Returns (padded_tree, (n, bucket)) when bucketing applies, else
    (tree, None): disabled flag, training mode (padded rows would enter
    batch statistics), sharded batches, mismatched/absent leading dims,
    batch already on a bucket, or batch above the ladder (exact-shape
    fallback in all cases).
    """
    env = environment()
    if training or mesh is not None or not env.inference_bucketing():
        return tree, None
    n = _leading_dim(tree)
    if n is None or n == 0:
        return tree, None
    b = bucket_for(n, bucket_ladder(env.inference_max_batch()))
    if b is None or b == n:
        return tree, None
    return jax.tree_util.tree_map(lambda l: pad_batch(l, b), tree), (n, b)


def slice_batch(outputs: Sequence[Any], n: int, bucket: int) -> List[Any]:
    """Drop padded rows: slice every output whose leading dim is the bucket
    (batch-shaped); leave scalars / non-batch outputs untouched."""
    return [o[:n] if getattr(o, "ndim", 0) >= 1 and o.shape[0] == bucket
            else o for o in outputs]


# ---------------------------------------------------------------------------
# compile-counted jit
# ---------------------------------------------------------------------------

def counted_jit(fn: Callable, tag: str, **jit_kwargs) -> Callable:
    """``jax.jit(fn, **jit_kwargs)`` wrapped with recompile observability
    AND the AOT compile cache: each new input signature records one
    compile event and resolves its executable through
    ``runtime.compile_cache.aot_entry`` — a persistent-store hit
    deserializes the executable and skips XLA, a miss compiles via
    ``lower().compile()`` and serializes back, and ineligible entries
    (donation, shardings, caching disabled) dispatch through the live jit
    exactly as before. Used by every jitted inference entry AND the fit
    fast path's train/epoch steps (donate_argnums passes through).

    The signature is computed from ``args[1:]`` — by convention the first
    argument is the parameter pytree, whose shapes only change on
    re-init/distribute (which rebuild the wrapper anyway); skipping it
    keeps the per-call overhead off the hot path. Python-scalar leaves
    (e.g. the iteration counter) hash by type, matching jit's behavior of
    tracing them as abstract values — a changing int must not count as a
    recompile. Array leaves include weak_type so an AOT executable is
    never fed an aval it was not built for; and if a resolved entry still
    fails to accept a call (e.g. the param tree was re-initialized with
    new shapes under an unchanged data signature), the entry permanently
    falls back to the live jit for that signature — cache problems may
    cost a compile, never an exception.
    """
    from . import compile_cache

    jfn = jax.jit(fn, **jit_kwargs)
    entries: Dict[Any, Callable] = {}
    kind = tag.split(":")[0]

    def wrapped(*args):
        data = args[1:]
        sig = (jax.tree_util.tree_structure(data),
               tuple((tuple(l.shape), str(l.dtype),
                      bool(getattr(l, "weak_type", False)))
                     if hasattr(l, "shape") else f"py:{type(l).__name__}"
                     for l in jax.tree_util.tree_leaves(data)))
        call = entries.get(sig)
        if call is None:
            t0 = time.perf_counter()
            call, label = compile_cache.aot_entry(jfn, tag, args, jit_kwargs)
            # dl4j_compiles_total keeps the base label; the reasoned form
            # ("bypass:donation", ...) lands on dl4j_compile_seconds
            environment().record_compile((tag,) + sig,
                                         cache=label.partition(":")[0])
            if call is jfn:
                out = jfn(*args)  # first call compiles via the live jit
            else:
                try:
                    out = call(*args)
                except Exception:
                    entries[sig] = jfn
                    return jfn(*args)
            compile_cache.observe_compile(kind, label,
                                          time.perf_counter() - t0)
            entries[sig] = call
            return out
        if call is jfn:
            return jfn(*args)
        try:
            return call(*args)
        except Exception:
            entries[sig] = jfn
            return jfn(*args)

    wrapped._jit = jfn
    return wrapped


# ---------------------------------------------------------------------------
# frontend adapters
# ---------------------------------------------------------------------------

def _unwrap(x):
    if hasattr(x, "jax"):  # NDArray without importing ndarray (cycle-free)
        return x.jax()
    return jnp.asarray(x)


class _MultiLayerAdapter:
    """MultiLayerNetwork: one input array -> one output NDArray."""

    def __init__(self, model):
        self.model = model

    def inputs_of(self, request) -> List[jax.Array]:
        return [_unwrap(request)]

    def run(self, inputs: List[jax.Array]) -> List[jax.Array]:
        return [self.model._output_jit(False)(self.model._params, inputs[0])]

    def package(self, outputs: List[jax.Array]):
        from ..ndarray.ndarray import NDArray
        return NDArray(outputs[0])

    def shard(self, mesh, spec):
        from ..common.mesh import shard_params
        self.model._params = shard_params(mesh, self.model._params, spec)


class _GraphAdapter:
    """ComputationGraph: array/list/dict request -> list of NDArrays,
    ordered as conf.outputs."""

    def __init__(self, model):
        self.model = model
        self.input_names = list(model.conf.inputs)

    def inputs_of(self, request) -> List[jax.Array]:
        if isinstance(request, dict):
            return [_unwrap(request[n]) for n in self.input_names]
        if not isinstance(request, (list, tuple)):
            request = [request]
        if len(request) != len(self.input_names):
            raise ValueError(f"graph expects {len(self.input_names)} inputs, "
                             f"got {len(request)}")
        return [_unwrap(x) for x in request]

    def run(self, inputs: List[jax.Array]) -> List[jax.Array]:
        ind = {n: x for n, x in zip(self.input_names, inputs)}
        return list(self.model._output_jit(False)(self.model._params, ind))

    def package(self, outputs: List[jax.Array]):
        from ..ndarray.ndarray import NDArray
        return [NDArray(o) for o in outputs]

    def shard(self, mesh, spec):
        from ..common.mesh import shard_params
        self.model._params = shard_params(mesh, self.model._params, spec)


class _SameDiffAdapter:
    """SameDiff: placeholder dict -> {name: NDArray} for `outputs`."""

    def __init__(self, model, outputs: Sequence[Any]):
        if not outputs:
            raise ValueError("wrapping a SameDiff requires outputs=[...] "
                             "(the variable names to serve)")
        self.model = model
        self.out_names = [o.name if hasattr(o, "name") else o for o in outputs]
        self.ph_names: Optional[List[str]] = None

    def inputs_of(self, request) -> List[jax.Array]:
        if not isinstance(request, dict):
            raise TypeError("SameDiff requests must be placeholder dicts")
        if self.ph_names is None:
            self.ph_names = sorted(request)
        if sorted(request) != self.ph_names:
            raise ValueError(f"placeholder keys {sorted(request)} != "
                             f"{self.ph_names} of the first request")
        return [_unwrap(request[n]) for n in self.ph_names]

    def run(self, inputs: List[jax.Array]) -> List[jax.Array]:
        sd = self.model
        ph = {n: x for n, x in zip(self.ph_names, inputs)}
        if any(op.needs_key for op in sd._ops.values()):
            fn = sd.make_function(self.out_names, tuple(self.ph_names),
                                  with_rng=True)
            sd._rng_calls = getattr(sd, "_rng_calls", 0) + 1
            return list(fn(sd._arrays, ph,
                           jax.random.key(sd._rng_seed + sd._rng_calls)))
        fn = sd.make_function(self.out_names, tuple(self.ph_names))
        return list(fn(sd._arrays, ph))

    def package(self, outputs: List[jax.Array]):
        from ..ndarray.ndarray import NDArray
        return {n: NDArray(o) for n, o in zip(self.out_names, outputs)}

    def shard(self, mesh, spec):
        from ..common.mesh import shard_params
        self.model._arrays = shard_params(mesh, self.model._arrays, spec)


def _make_adapter(model, outputs):
    # duck-typed so runtime never imports nn/autodiff at module load
    if hasattr(model, "make_function") and hasattr(model, "_vars"):
        return _SameDiffAdapter(model, outputs or [])
    if hasattr(model, "conf") and hasattr(getattr(model.conf, "outputs", None),
                                          "__iter__") and hasattr(
                                              model, "_order"):
        return _GraphAdapter(model)
    if hasattr(model, "layers") and hasattr(model, "_output_jit"):
        return _MultiLayerAdapter(model)
    raise TypeError(f"cannot serve a {type(model).__name__}; expected "
                    "MultiLayerNetwork, ComputationGraph, or SameDiff")


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class EngineClosedError(RuntimeError):
    """Raised by ``submit()``/``infer()`` once the engine is draining or
    closed: late requests must fail fast with a clear signal the caller
    can act on (the serving registry retries them against the engine that
    replaced this one; everyone else surfaces the error)."""


class PoisonRequestError(RuntimeError):
    """A request that failed its coalesced dispatch AND its one isolated
    re-dispatch: the failure follows the request, not the batch, so it is
    quarantined (HTTP 422 with trace id) instead of re-killing every
    micro-batch it rides in. Carries the underlying dispatch error as
    ``__cause__``-style ``cause``."""

    def __init__(self, message: str, cause: Optional[BaseException] = None):
        super().__init__(message)
        self.cause = cause


class _Request:
    __slots__ = ("inputs", "n", "sig", "future", "deadline", "ctx",
                 "t_submit")

    def __init__(self, inputs, sig, future, deadline=None, ctx=None):
        self.inputs = inputs
        self.n = inputs[0].shape[0]
        self.sig = sig
        self.future = future
        self.deadline = deadline  # monotonic instant, or None
        # the submitter's trace context: the batcher thread emits this
        # request's spans under it (contextvars don't cross threads)
        self.ctx = ctx
        self.t_submit = time.perf_counter()

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline


class InferenceEngine:
    """Serving front-end over any executable frontend.

    - ``infer(request)`` — synchronous bucketed inference (pads to the
      bucket, slices padded rows off; batches above ``max_batch`` are
      chunked so the compile bound still holds).
    - ``warmup(example[, batch_sizes])`` — pre-compile buckets.
    - ``submit(request) -> Future`` — enqueue for the dynamic micro-batcher:
      a background thread coalesces concurrent requests within the
      ``max_delay_ms`` / ``max_batch`` window into one padded dispatch.

    Knob mapping from the reference ParallelInference: ``batchLimit`` ->
    ``max_batch``; ``InferenceMode.BATCHED`` -> ``submit()``; ``queueLimit``
    has no analog (the queue is unbounded, ``max_delay_ms`` bounds latency);
    worker replicas are subsumed by XLA running one executable per bucket.
    """

    def __init__(self, model, *, max_batch: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_delay_ms: float = 2.0,
                 outputs: Optional[Sequence[Any]] = None,
                 manifest_path: Optional[str] = None,
                 mesh=None, param_spec=None):
        self.model = model
        self._adapter = _make_adapter(model, outputs)
        # tensor-parallel serving: params are committed into their sharded
        # layout once at construction (model axis; replicated fallback per
        # leaf) and every dispatch's padded batch is committed over the
        # data axis — jit propagates the shardings and XLA inserts the
        # collectives (SNIPPETS [2] GSPMD idiom). mesh=None is the
        # single-device path, byte-for-byte unchanged.
        self.mesh = mesh
        self.param_spec = param_spec
        self._batch_sharding = None
        self._data_size = 1
        if mesh is not None:
            from ..common.mesh import DATA, data_sharding, validate_mesh
            validate_mesh(mesh, required=(DATA,))
            self._batch_sharding = data_sharding(mesh)
            self._replicated = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec())
            self._data_size = int(mesh.shape[DATA])
            self._adapter.shard(mesh, param_spec)
        self.max_batch = int(max_batch if max_batch is not None
                             else environment().inference_max_batch())
        self.ladder = bucket_ladder(self.max_batch, buckets)
        self.max_batch = self.ladder[-1]
        self.max_delay_ms = float(max_delay_ms)
        # warmup guard + traffic-shape manifest: _warmed holds
        # (bucket, input-sig) keys already compiled by warmup, _warming the
        # in-flight ones (concurrent/repeated warmups wait instead of
        # double-compiling); _observed accumulates the shapes live traffic
        # actually dispatched, auto-persisted when manifest_path is set so
        # a restarted server can replay yesterday's buckets before taking
        # traffic.
        # DL105: tracked locks — names are the class-level ordering
        # identity the runtime lock-order tracker (common.locks) and the
        # static pass both reason about
        self._warm_lock = ordered_lock("inference.warm")
        self._warmed: set = set()
        self._warming: Dict[Any, threading.Event] = {}
        self.manifest_path = manifest_path
        self._observed: Dict[Tuple, set] = {}
        # micro-batcher state
        self._cv = ordered_condition("inference.batcher")
        self._pending: List[_Request] = []
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        # lifecycle: draining refuses new requests but is reversible via
        # start() (the registry parks retired versions this way so a
        # rollback re-admits without recompiling); closed is permanent
        self._draining = False
        self._closed = False
        self._inflight = 0  # synchronous infer() calls currently running
        # resilience: the supervised batcher's restart budget state and
        # the watchdog-readable in-flight dispatch timestamp
        self._worker_dead = False
        self._dispatch_started_at: Optional[float] = None
        # stats
        self._lock = ordered_lock("inference.stats")
        self._stats = {"requests": 0, "dispatches": 0, "rows_real": 0,
                       "rows_padded": 0, "coalesced": 0,
                       "bucket_dispatches": {}}
        # telemetry: registry families created once, per-bucket children
        # cached so the dispatch path pays one dict lookup + observe
        self._reg = registry()
        lat = self._reg.histogram(
            "dl4j_inference_latency_seconds",
            "Per-bucket dispatch latency of the inference engine",
            labels=("bucket",))
        pad = self._reg.histogram(
            "dl4j_inference_padding_ratio",
            "Fraction of dispatched rows that were bucket padding",
            labels=("bucket",), buckets=linear_buckets(0.0, 0.05, 20))
        self._m_latency = {b: lat.labels(bucket=b) for b in self.ladder}
        self._m_padding = {b: pad.labels(bucket=b) for b in self.ladder}
        self._m_requests = self._reg.counter(
            "dl4j_inference_requests_total",
            "Requests accepted by infer()/submit()")
        self._m_queue = self._reg.gauge(
            "dl4j_inference_queue_depth",
            "Requests waiting in the submit() micro-batcher queue")
        self._m_coalesce = self._reg.histogram(
            "dl4j_inference_coalesce_size",
            "Requests coalesced into one micro-batched dispatch",
            buckets=[float(1 << i) for i in range(11)])
        self._m_expired = self._reg.counter(
            "dl4j_inference_deadline_expired_total",
            "submit() requests whose deadline expired before dispatch")
        self._m_restarts = self._reg.counter(
            "dl4j_engine_restarts_total",
            "Supervised engine worker-thread restarts after a crash",
            labels=("engine",)).labels(engine="inference")
        self._m_quarantined = self._reg.counter(
            "dl4j_quarantined_requests_total",
            "Poison requests quarantined after a failed isolated retry")
        self._m_isolated = self._reg.counter(
            "dl4j_inference_isolated_retries_total",
            "Riders of a failed coalesced dispatch re-dispatched "
            "individually, by outcome", labels=("outcome",))

    # -- core dispatch ---------------------------------------------------
    def _dispatch(self, inputs: List[jax.Array], n: int,
                  span_attrs: Optional[Dict[str, Any]] = None
                  ) -> List[jax.Array]:
        """Pad `inputs` (shared leading dim n <= max_batch) to the bucket,
        run, slice the padded rows back off. The dispatch span inherits
        any active trace context; ``span_attrs`` lets the micro-batcher
        stamp the coalesced riders' trace_ids onto it."""
        b = bucket_for(n, self.ladder)
        if faults.active():
            faults.check("engine.dispatch", inputs=inputs, rows=n, bucket=b)
        padded = [pad_batch(x, b) for x in inputs]
        if self._batch_sharding is not None:
            # commit the bucket over the data axis (replicated when the
            # bucket does not divide) so jit sees the sharded aval
            sh = (self._batch_sharding if b % self._data_size == 0
                  else self._replicated)
            padded = [jax.device_put(x, sh) for x in padded]
        self._dispatch_started_at = time.monotonic()  # watchdog-readable
        try:
            if self._reg.enabled:
                ctx = current_context()
                t0 = time.perf_counter()
                with span("inference/dispatch", bucket=b, rows=n,
                          **(span_attrs or {})):
                    outs = self._adapter.run(padded)
                lat = self._m_latency.get(b)
                if lat is not None:
                    # tail observations carry the request's trace_id as an
                    # exemplar, linking the histogram back to /debug/trace
                    lat.observe(time.perf_counter() - t0,
                                exemplar=ctx.trace_id if ctx else None)
                    self._m_padding[b].observe((b - n) / b)
            else:
                outs = self._adapter.run(padded)
        finally:
            self._dispatch_started_at = None
        with self._lock:
            s = self._stats
            s["dispatches"] += 1
            s["rows_real"] += n
            s["rows_padded"] += b - n
            s["bucket_dispatches"][b] = s["bucket_dispatches"].get(b, 0) + 1
        self._record_observed(inputs, b)
        return slice_batch(outs, n, b)

    def _dispatch_chunked(self, inputs: List[jax.Array],
                          n: int) -> List[jax.Array]:
        if n <= self.max_batch:
            return self._dispatch(inputs, n)
        pieces = []
        for lo in range(0, n, self.max_batch):
            hi = min(lo + self.max_batch, n)
            pieces.append(self._dispatch([x[lo:hi] for x in inputs], hi - lo))
        out = []
        for idx, parts in enumerate(zip(*pieces)):
            # outputs that carried the batch dim were per-chunk sliced;
            # concatenate those, keep non-batch outputs from the last chunk
            # (all chunks agree on them only for row-independent nets, which
            # is the contract of this engine)
            sliced = all(getattr(p, "ndim", 0) >= 1
                         and p.shape[0] == min(self.max_batch,
                                               n - i * self.max_batch)
                         for i, p in enumerate(parts))
            out.append(jnp.concatenate(parts, axis=0) if sliced
                       else parts[-1])
        return out

    def infer(self, request):
        """Synchronous bucketed inference for one request."""
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                raise EngineClosedError(
                    "InferenceEngine is "
                    + ("closed" if self._closed else
                       "draining" if self._draining else
                       "dead (worker restart budget exhausted)")
                    + "; it no longer accepts requests")
            self._inflight += 1
        try:
            inputs = self._adapter.inputs_of(request)
            n = _leading_dim(inputs)
            if n is None:
                raise ValueError(
                    "request inputs must share a leading batch dim")
            with self._lock:
                self._stats["requests"] += 1
            self._m_requests.inc()
            return self._adapter.package(self._dispatch_chunked(inputs, n))
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    __call__ = infer

    # -- warmup + manifest -------------------------------------------------
    @staticmethod
    def _input_sig(inputs: Sequence[Any]) -> Tuple:
        """Trailing (feature) shapes + dtypes — what identifies a traffic
        shape independent of its batch bucket."""
        return tuple((tuple(int(d) for d in x.shape[1:]), str(x.dtype))
                     for x in inputs)

    def _record_observed(self, inputs: Sequence[Any], bucket: int):
        """Remember that live traffic exercised (sig, bucket); persist to
        the manifest file when one is configured (new keys only — the hot
        path pays a set lookup per dispatch)."""
        sig = self._input_sig(inputs)
        with self._warm_lock:
            buckets = self._observed.setdefault(sig, set())
            if bucket in buckets:
                return
            buckets.add(bucket)
        if self.manifest_path:
            try:
                self.save_manifest(self.manifest_path)
            except OSError as e:
                logging.getLogger(__name__).warning(
                    "warmup manifest write to %s failed (%s)",
                    self.manifest_path, e)

    def save_manifest(self, path: Optional[str] = None) -> str:
        """Write the observed bucket/shape/dtype keys as JSON (atomic).
        A restarted server hands the file to ``warmup()`` to replay
        yesterday's shapes before taking traffic."""
        path = path or self.manifest_path
        if not path:
            raise ValueError("no manifest path given or configured")
        with self._warm_lock:
            entries = [{"inputs": [{"shape": list(s), "dtype": d}
                                   for s, d in sig],
                        "buckets": sorted(int(b) for b in buckets)}
                       for sig, buckets in sorted(self._observed.items())]
        doc = {"version": 1, "max_batch": self.max_batch,
               "entries": entries}
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path

    @staticmethod
    def load_manifest(path: str) -> List[dict]:
        """Parse a warmup manifest; malformed files return [] with a
        warning (a stale manifest must never block serving startup)."""
        try:
            with open(path, "r") as f:
                doc = json.load(f)
            entries = []
            for e in doc.get("entries", []):
                inputs = [(tuple(int(d) for d in i["shape"]), str(i["dtype"]))
                          for i in e["inputs"]]
                buckets = [int(b) for b in e["buckets"]]
                if buckets:
                    entries.append({"inputs": inputs, "buckets": buckets})
            return entries
        except Exception as e:
            logging.getLogger(__name__).warning(
                "warmup manifest %s unreadable (%s: %s); skipping replay",
                path, type(e).__name__, e)
            return []

    def observed_entries(self) -> List[dict]:
        """The live-traffic manifest in ``load_manifest`` format, without
        touching disk — the in-process handoff a serving registry uses to
        warm an incoming model version with the shapes the outgoing
        version actually served."""
        with self._warm_lock:
            return [{"inputs": [(tuple(int(d) for d in s), str(dt))
                                for s, dt in sig],
                     "buckets": sorted(int(b) for b in buckets)}
                    for sig, buckets in sorted(self._observed.items())]

    def warmup(self, example=None,
               batch_sizes: Optional[Sequence[int]] = None,
               manifest: Optional[str] = None,
               workers: Optional[int] = None,
               entries: Optional[List[dict]] = None) -> List[int]:
        """Pre-compile bucket executables before traffic arrives,
        concurrently (XLA compilation releases the GIL, so the ladder
        compiles on a thread pool — wall clock ~ the slowest bucket, not
        the sum).

        `example` is any valid request (its batch size is irrelevant; only
        the trailing feature shapes/dtypes matter). With `batch_sizes`,
        only the buckets those sizes map to are compiled; default is the
        whole ladder. With ``example=None``, shapes are replayed from
        ``entries`` (``load_manifest``/``observed_entries`` format — the
        hot-swap handoff from a live predecessor engine) or from
        ``manifest`` (or the engine's configured ``manifest_path``) — the
        restart flow. Returns the sorted buckets warmed.

        Idempotent and re-entrant: a (bucket, shape) pair already warmed —
        or being warmed by a concurrent call — is never compiled twice;
        late callers wait for the in-flight compile instead.

        With a shared artifact store configured (``DL4J_TPU_REMOTE_CACHE``,
        or a ``runtime.warm_image`` pre-baked artifact dir), each warmup
        compile resolves through the tiered store first — on a fleet
        joiner or freshly booted CI image the whole ladder typically
        loads as store hits and never reaches XLA.
        """
        jobs: List[Tuple[int, Tuple]] = []  # (bucket, input-sig)
        if example is not None:
            sig = self._input_sig(self._adapter.inputs_of(example))
            if batch_sizes is not None:
                todo = sorted({bucket_for(min(int(s), self.max_batch),
                                          self.ladder)
                               for s in batch_sizes})
            else:
                todo = list(self.ladder)
            jobs = [(b, sig) for b in todo]
        else:
            if entries is None:
                path = manifest or self.manifest_path
                if not path or not os.path.exists(path):
                    return []
                entries = self.load_manifest(path)
            for e in entries:
                sig = tuple((tuple(int(d) for d in s), str(dt))
                            for s, dt in e["inputs"])
                for b in e["buckets"]:
                    b = bucket_for(min(int(b), self.max_batch), self.ladder)
                    jobs.append((b, sig))
            jobs = sorted(set(jobs))
        if not jobs:
            return []

        claimed: List[Tuple[int, Tuple, threading.Event]] = []
        wait_for: List[threading.Event] = []
        with self._warm_lock:
            for b, sig in jobs:
                key = (b, sig)
                if key in self._warmed:
                    continue
                ev = self._warming.get(key)
                if ev is not None:
                    wait_for.append(ev)
                    continue
                ev = threading.Event()
                self._warming[key] = ev
                claimed.append((b, sig, ev))

        def compile_one(b, sig, ev):
            try:
                self._dispatch([jnp.zeros((b,) + shape, dtype)
                                for shape, dtype in sig], b)
                with self._warm_lock:
                    self._warmed.add((b, sig))
            finally:
                ev.set()
                with self._warm_lock:
                    self._warming.pop((b, sig), None)

        if claimed:
            n_workers = workers or environment().warmup_threads() \
                or min(len(claimed), os.cpu_count() or 1, 8)
            if n_workers <= 1 or len(claimed) == 1:
                for b, sig, ev in claimed:
                    compile_one(b, sig, ev)
            else:
                with ThreadPoolExecutor(
                        max_workers=min(int(n_workers), len(claimed)),
                        thread_name_prefix="dl4j-tpu-warmup") as pool:
                    futs = [pool.submit(compile_one, b, sig, ev)
                            for b, sig, ev in claimed]
                    for f in futs:
                        f.result()  # surface the first compile error
        for ev in wait_for:
            ev.wait(timeout=600)
        return sorted({b for b, _ in jobs})

    # -- dynamic micro-batcher -------------------------------------------
    def submit(self, request, timeout_s: Optional[float] = None) -> Future:
        """Enqueue one request; the returned Future resolves to the same
        value infer(request) would produce.

        With ``timeout_s``, the request carries a deadline budget: if it
        is still queued when the budget expires, the micro-batcher
        resolves its Future with ``TimeoutError`` instead of padding it
        into a batch slot nobody is waiting for (deadline propagation —
        expired work is shed before dispatch, not after)."""
        inputs = self._adapter.inputs_of(request)
        n = _leading_dim(inputs)
        if n is None:
            raise ValueError("request inputs must share a leading batch dim")
        if n > self.max_batch:
            raise ValueError(f"submit() batch {n} exceeds max_batch "
                             f"{self.max_batch}; use infer() (it chunks)")
        sig = tuple((x.shape[1:], str(x.dtype)) for x in inputs)
        fut: Future = Future()
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                raise EngineClosedError(
                    "InferenceEngine is "
                    + ("closed" if self._closed else
                       "draining" if self._draining else
                       "dead (worker restart budget exhausted)")
                    + "; it no longer accepts requests")
            self._pending.append(_Request(inputs, sig, fut, deadline,
                                          ctx=current_context()))
            depth = len(self._pending)
            self._cv.notify_all()
        with self._lock:
            self._stats["requests"] += 1
        self._m_requests.inc()
        self._m_queue.set(depth)
        self._ensure_thread()
        return fut

    def _ensure_thread(self):
        with self._cv:
            if self._draining or self._closed or self._worker_dead:
                return  # a drain in progress must never be un-stopped
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._batcher_main,
                    name="dl4j-tpu-inference-batcher", daemon=True)
                self._thread.start()

    @property
    def worker_dead(self) -> bool:
        """True once the supervised batcher exhausted its restart budget
        (the watchdog reports this engine unhealthy; submits fail fast)."""
        return self._worker_dead

    def _batcher_main(self):
        """Supervised batcher: a crash anywhere in the loop fails at most
        the dispatch it was running (``_run_group`` already fails only
        its riders), is counted, and the loop resumes after exponential
        backoff with jitter — one uncaught exception must never silently
        kill the dispatch path for every subsequent request. A crash
        *burst* past ``DL4J_TPU_ENGINE_MAX_RESTARTS`` declares the
        worker dead: queued requests fail fast with ``EngineClosedError``
        and the watchdog flips ``/readyz``."""
        policy = faults.RetryPolicy(
            max_restarts=environment().engine_max_restarts(),
            base_s=0.01, max_s=2.0, seed=0)
        while True:
            try:
                self._batcher_loop()
                return  # normal stop (drain / idle exit)
            except Exception:
                logging.getLogger(__name__).exception(
                    "inference batcher crashed; restarting the loop")
                policy.note_failure()
                self._m_restarts.inc()
                if policy.exhausted():
                    self._worker_died()
                    return
                time.sleep(policy.backoff.next_delay())

    def _worker_died(self):
        """Restart budget exhausted: fail everything queued, refuse new
        work, leave the process alive (the registry / operator decides
        what happens next — rollback, redeploy, or drain)."""
        with self._cv:
            self._worker_dead = True
            leftovers, self._pending = self._pending, []
            if self._thread is threading.current_thread():
                self._thread = None
            self._cv.notify_all()
        logging.getLogger(__name__).error(
            "inference batcher exceeded its restart budget; engine "
            "refuses new work (worker_dead)")
        for r in leftovers:
            if not r.future.done():
                r.future.set_exception(EngineClosedError(
                    "InferenceEngine worker thread permanently failed "
                    "(restart budget exhausted)"))

    def start(self):
        """(Re)open the engine for requests: reverses drain() — a parked
        previous version resumes without recompiling — and starts the
        micro-batcher thread. Raises once close() has run."""
        with self._cv:
            if self._closed:
                raise EngineClosedError(
                    "InferenceEngine is closed; it cannot be restarted")
            self._draining = False
        self._ensure_thread()
        return self

    def stop(self):
        """Drain pending requests, then stop the batcher thread (the
        engine stays open: a later submit() restarts it)."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=30)
        return self

    # -- graceful drain / close ------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining and not self._closed

    @property
    def closed(self) -> bool:
        return self._closed

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admitting, flush every queued request through the
        micro-batcher, wait for in-flight infer() calls, and stop the
        batcher thread. Idempotent; reversible via start() (a rollback
        re-admits a parked version). Late submit()/infer() calls raise
        ``EngineClosedError``. Returns True when fully drained within
        ``timeout_s``."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            self._draining = True
            self._stopping = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        # a submit that raced the drain may have left requests behind a
        # dead batcher: fail them explicitly rather than strand futures
        with self._cv:
            leftovers, self._pending = self._pending, []
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            drained = self._inflight == 0 and (t is None or not t.is_alive())
        for r in leftovers:
            if not r.future.done():
                r.future.set_exception(EngineClosedError(
                    "InferenceEngine drained before this request was "
                    "dispatched"))
        return drained

    def close(self, timeout_s: float = 30.0) -> bool:
        """Permanent drain: like drain(), but the engine can never be
        restarted. Idempotent. Returns True when fully drained."""
        self._closed = True
        return self.drain(timeout_s)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _expire(self, req: _Request) -> bool:
        """Resolve an expired request's Future with TimeoutError; True if
        it was expired (and must not occupy a batch slot)."""
        if not req.expired():
            return False
        if not req.future.done():
            req.future.set_exception(TimeoutError(
                "request deadline expired before dispatch"))
        self._m_expired.inc()
        if req.ctx is not None and self._reg.enabled:
            # the expired wait shows up in the request's trace with error
            # status — a shed request's timeline stays reconstructable
            tracer().record("inference/queue_expired", req.t_submit,
                            time.perf_counter(), context=req.ctx,
                            rows=req.n, error="TimeoutError")
        return True

    def _batcher_loop(self):
        while True:
            # the crash site sits BEFORE any request is popped, so an
            # injected batcher crash loses no queued work — the
            # supervisor restarts the loop and the queue survives
            if faults.active():
                faults.check("engine.batcher")
            with self._cv:
                while not self._pending and not self._stopping:
                    self._cv.wait()
                if not self._pending:  # stopping and drained
                    if self._thread is threading.current_thread():
                        # a submit() racing this exit sees _thread None and
                        # reliably starts a fresh batcher for its request
                        self._thread = None
                    return
                first = self._pending.pop(0)
            if self._expire(first):
                continue
            group, total = [first], first.n
            deadline = time.monotonic() + self.max_delay_ms / 1000.0
            while total < self.max_batch:
                with self._cv:
                    timeout = deadline - time.monotonic()
                    while (not self._pending and timeout > 0
                           and not self._stopping):
                        self._cv.wait(timeout)
                        timeout = deadline - time.monotonic()
                    if not self._pending:
                        break
                    nxt = self._pending[0]
                    if nxt.sig != first.sig or total + nxt.n > self.max_batch:
                        break
                    self._pending.pop(0)
                if self._expire(nxt):
                    continue
                group.append(nxt)
                total += nxt.n
            if self._reg.enabled:
                with self._cv:
                    self._m_queue.set(len(self._pending))
            self._run_group(group, total)

    def _run_group(self, group: List[_Request], total: int):
        self._m_coalesce.observe(len(group))
        # the dispatch span runs under the first traced rider's context
        # and lists every rider's trace_id, so each request's timeline
        # survives coalescing: its own trace keeps an inference/ride
        # span, and the shared dispatch names all trace_ids that rode
        lead_ctx = next((r.ctx for r in group if r.ctx is not None), None)
        attrs: Dict[str, Any] = {}
        if lead_ctx is not None:
            riders = [r.ctx.trace_id for r in group if r.ctx is not None]
            attrs["trace_ids"] = riders
            if len(group) > 1:
                attrs["coalesced"] = len(group)
        t_dispatch = time.perf_counter()
        try:
            if len(group) == 1:
                inputs = group[0].inputs
            else:
                with self._lock:
                    self._stats["coalesced"] += len(group)
                inputs = [jnp.concatenate(parts, axis=0)
                          for parts in zip(*(r.inputs for r in group))]
            if lead_ctx is not None:
                with use_context(lead_ctx):
                    outs = self._dispatch(inputs, total, span_attrs=attrs)
            else:
                outs = self._dispatch(inputs, total, span_attrs=attrs)
            lo = 0
            for r in group:
                hi = lo + r.n
                r.future.set_result(self._adapter.package(
                    [o[lo:hi] if getattr(o, "ndim", 0) >= 1
                     and o.shape[0] == total else o for o in outs]))
                lo = hi
            self._record_rides(group, t_dispatch)
        except Exception as e:
            self._rescue_group(group, e, t_dispatch)

    def _rescue_group(self, group: List[_Request], exc: Exception,
                      t_dispatch: float):
        """Poison isolation: a failed coalesced dispatch re-dispatches
        each rider individually ONCE, so the one request actually
        carrying the fault is quarantined (``PoisonRequestError`` → 4xx
        with trace id) while its innocent riders succeed — instead of
        the poison re-killing every batch it rides in. An
        ``EngineClosedError`` (drain race) is not a model fault and
        fails the group as before so the registry's swap retry fires."""
        if isinstance(exc, EngineClosedError):
            for r in group:
                if not r.future.done():
                    r.future.set_exception(exc)
            self._record_rides(group, t_dispatch,
                               error=type(exc).__name__)
            return
        for r in group:
            if r.future.done():
                continue
            trace_id = r.ctx.trace_id if r.ctx is not None else None
            try:
                outs = self._dispatch(r.inputs, r.n,
                                      span_attrs={"isolated_retry": True})
            except Exception as e2:
                self._m_isolated.labels(outcome="quarantined").inc()
                self._m_quarantined.inc()
                record_disposition(trace_id, "quarantined")
                if r.ctx is not None and self._reg.enabled:
                    tracer().record(
                        "inference/quarantine", t_dispatch,
                        time.perf_counter(), context=r.ctx, rows=r.n,
                        error=type(e2).__name__)
                r.future.set_exception(PoisonRequestError(
                    f"request quarantined: dispatch failed coalesced "
                    f"({type(exc).__name__}: {exc}) and again isolated "
                    f"({type(e2).__name__}: {e2})", cause=e2))
            else:
                self._m_isolated.labels(outcome="ok").inc()
                record_disposition(trace_id, "retried")
                r.future.set_result(self._adapter.package(outs))
        self._record_rides(group, t_dispatch,
                           error=type(exc).__name__)

    def _record_rides(self, group: List[_Request], t_dispatch: float,
                      error: Optional[str] = None):
        """Per-rider micro-batcher spans: each traced request gets an
        ``inference/ride`` span in its OWN trace covering queue wait +
        dispatch, so its timeline reads end-to-end even when another
        request's trace holds the shared dispatch span."""
        if not self._reg.enabled:
            return
        t1 = time.perf_counter()
        for r in group:
            if r.ctx is None:
                continue
            attrs = {"rows": r.n, "coalesced": len(group),
                     "queue_s": round(t_dispatch - r.t_submit, 6)}
            if error is not None:
                attrs["error"] = error
            tracer().record("inference/ride", r.t_submit, t1,
                            context=r.ctx, **attrs)

    # -- observability ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self._stats.items()}
        real, padded = s["rows_real"], s["rows_padded"]
        s["padding_overhead"] = padded / max(real + padded, 1)
        s["compile_count"] = environment().compile_count()
        s["buckets"] = list(self.ladder)
        if self.mesh is not None:
            from ..common.mesh import mesh_shape, spec_desc
            s["mesh_shape"] = mesh_shape(self.mesh)
            s["param_spec"] = spec_desc(self.param_spec)
        return s
