"""Fused softmax-cross-entropy over a large vocab — Pallas TPU kernel.

Reference counterpart: `softmax_cross_entropy_loss_with_logits` +
`sparse_softmax_cross_entropy_loss_with_logits`
(`libnd4j/include/ops/declarable/headers/loss.h`) — the MLM-loss hot path
over the 30k-row vocab. The naive lowering materializes [N, V] softmax in
HBM twice (fwd + bwd). This kernel streams [TN, TV] vocab tiles through
VMEM (a full 30k-vocab row block would blow the 16MB VMEM budget):
fwd accumulates the online-softmax state in VMEM scratch across the
(sequential) vocab grid dimension and emits loss + (max, logsumexp) per
row; bwd regenerates softmax tiles and subtracts the one-hot — nothing
[N, V]-shaped beyond the logits themselves ever hits HBM.

Layout note: per-row stats ride as [N, 1] (lane dim 1) — Mosaic rank-1
blocks are restricted; 2-D trailing-1 blocks lower cleanly.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _fwd_kernel(x_ref, lab_ref, loss_ref, m_ref, l_ref, m_s, l_s, xl_s, *,
                tile_v, n_v_blocks):
    j = pl.program_id(1)
    labels = lab_ref[...]                     # [TN, 1]
    tn = labels.shape[0]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s[...], -1e30)
        l_s[...] = jnp.zeros_like(l_s[...])
        xl_s[...] = jnp.zeros_like(xl_s[...])

    blk = x_ref[...].astype(jnp.float32)      # [TN, TV]
    m_old = m_s[...]                          # [TN, 1]
    m_new = jnp.maximum(m_old, jnp.max(blk, axis=-1, keepdims=True))
    l_new = l_s[...] * jnp.exp(m_old - m_new) + \
        jnp.sum(jnp.exp(blk - m_new), axis=-1, keepdims=True)
    cols = j * tile_v + jax.lax.broadcasted_iota(jnp.int32, (tn, tile_v), 1)
    hit = cols == labels
    xl_s[...] = xl_s[...] + jnp.sum(jnp.where(hit, blk, 0.0), axis=-1,
                                    keepdims=True)
    m_s[...] = m_new
    l_s[...] = l_new

    @pl.when(j == n_v_blocks - 1)
    def _emit():
        loss_ref[...] = jnp.log(l_s[...]) + m_s[...] - xl_s[...]
        m_ref[...] = m_s[...]
        l_ref[...] = l_s[...]


def _bwd_kernel(x_ref, lab_ref, m_ref, l_ref, g_ref, dx_ref, *, tile_v):
    blk = x_ref[...].astype(jnp.float32)      # [TN, TV]
    labels = lab_ref[...]                     # [TN, 1]
    m = m_ref[...]                            # [TN, 1]
    l = l_ref[...]
    g = g_ref[...]
    tn, tv = blk.shape
    jv = pl.program_id(1)
    probs = jnp.exp(blk - m) / l
    cols = jv * tv + jax.lax.broadcasted_iota(jnp.int32, (tn, tv), 1)
    onehot = (cols == labels).astype(jnp.float32)
    dx_ref[...] = ((probs - onehot) * g).astype(dx_ref.dtype)


def _xent_fwd_call(logits, labels2d, tile_n, tile_v):
    from jax.experimental.pallas import tpu as pltpu
    N, V = logits.shape
    tile_n = min(tile_n, N)
    tile_v = min(tile_v, V)
    n_v_blocks = V // tile_v
    kern = functools.partial(_fwd_kernel, tile_v=tile_v,
                             n_v_blocks=n_v_blocks)
    col = pl.BlockSpec((tile_n, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        kern,
        grid=(N // tile_n, n_v_blocks),
        in_specs=[pl.BlockSpec((tile_n, tile_v), lambda i, j: (i, j)), col],
        out_specs=[col, col, col],
        out_shape=[jax.ShapeDtypeStruct((N, 1), jnp.float32)] * 3,
        scratch_shapes=[pltpu.VMEM((tile_n, 1), jnp.float32)] * 3,
        interpret=_interpret(),
    )(logits, labels2d)


def _xent_bwd_call(logits, labels2d, m, l, g, tile_n, tile_v):
    N, V = logits.shape
    tile_n = min(tile_n, N)
    tile_v = min(tile_v, V)
    kern = functools.partial(_bwd_kernel, tile_v=tile_v)
    col = pl.BlockSpec((tile_n, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        kern,
        grid=(N // tile_n, V // tile_v),
        in_specs=[pl.BlockSpec((tile_n, tile_v), lambda i, j: (i, j)),
                  col, col, col, col],
        out_specs=pl.BlockSpec((tile_n, tile_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(logits.shape, logits.dtype),
        interpret=_interpret(),
    )(logits, labels2d, m, l, g)


def _pad_inputs(logits, labels, tile_n, tile_v):
    """Pad N to the row-tile boundary (zero rows/labels, sliced away) and V
    to the vocab-tile boundary (-1e30 columns: exp -> 0, no effect)."""
    N, V = logits.shape
    n_pad = (-N) % tile_n
    v_pad = (-V) % tile_v
    if v_pad:
        logits = jnp.concatenate(
            [logits, jnp.full((N, v_pad), -1e30, logits.dtype)], axis=1)
    if n_pad:
        logits = jnp.concatenate(
            [logits, jnp.zeros((n_pad, logits.shape[1]), logits.dtype)],
            axis=0)
        labels = jnp.concatenate(
            [labels, jnp.zeros((n_pad,), labels.dtype)], axis=0)
    return logits, labels, N


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_softmax_xent(logits, labels, tile_n: int = 128,
                       tile_v: int = 2048):
    """Per-row -log softmax(logits)[label]; logits [N, V], labels [N] int.

    Returns [N] float32 losses. Differentiable wrt logits; the softmax
    matrix is regenerated tile-wise in bwd (never stored). Non-tile-multiple
    N/V are padded internally (padded rows sliced away, padded vocab at
    -1e30 contributes nothing)."""
    lp, labp, N = _pad_inputs(logits, labels, tile_n, tile_v)
    loss, _, _ = _xent_fwd_call(lp, labp[:, None], tile_n, tile_v)
    return loss[:N, 0]


def _f(logits, labels, tile_n, tile_v):
    lp, labp, N = _pad_inputs(logits, labels, tile_n, tile_v)
    lab2 = labp[:, None]
    loss, m, l = _xent_fwd_call(lp, lab2, tile_n, tile_v)
    return loss[:N, 0], (lp, lab2, m, l, logits.shape)


def _b(tile_n, tile_v, res, g):
    lp, lab2, m, l, orig_shape = res
    N, V = orig_shape
    g_pad = jnp.zeros((lp.shape[0],), jnp.float32).at[:N].set(
        g.astype(jnp.float32))
    dx = _xent_bwd_call(lp, lab2, m, l, g_pad[:, None], tile_n, tile_v)
    return dx[:N, :V], None


fused_softmax_xent.defvjp(_f, _b)
