"""Fused softmax-cross-entropy over a large vocab — Pallas TPU kernel.

Reference counterpart: `softmax_cross_entropy_loss_with_logits` +
`sparse_softmax_cross_entropy_loss_with_logits`
(`libnd4j/include/ops/declarable/headers/loss.h`) — the MLM-loss hot path
over the 30k-row vocab. The naive lowering materializes [N, V] softmax in
HBM twice (fwd + bwd). This kernel streams vocab tiles through VMEM:
fwd emits loss + the (max, logsumexp) stats per row; bwd regenerates
softmax tiles and subtracts the one-hot — nothing [N, V]-shaped ever hits
HBM beyond the logits themselves.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _fwd_kernel(x_ref, lab_ref, loss_ref, m_ref, l_ref, *, tile_v, vocab):
    labels = lab_ref[...]                     # [TN]
    tn = labels.shape[0]

    def body(j, carry):
        m, l, xl = carry
        blk = x_ref[:, pl.ds(j * tile_v, tile_v)].astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(blk, axis=-1))
        l_new = l * jnp.exp(m - m_new) + \
            jnp.sum(jnp.exp(blk - m_new[:, None]), axis=-1)
        cols = j * tile_v + jax.lax.broadcasted_iota(jnp.int32,
                                                     (tn, tile_v), 1)
        hit = cols == labels[:, None]
        xl_new = xl + jnp.sum(jnp.where(hit, blk, 0.0), axis=-1)
        return m_new, l_new, xl_new

    m0 = jnp.full((tn,), -1e30, jnp.float32)
    l0 = jnp.zeros((tn,), jnp.float32)
    xl0 = jnp.zeros((tn,), jnp.float32)
    m, l, xl = jax.lax.fori_loop(0, vocab // tile_v, body, (m0, l0, xl0))
    loss_ref[...] = jnp.log(l) + m - xl
    m_ref[...] = m
    l_ref[...] = l


def _bwd_kernel(x_ref, lab_ref, m_ref, l_ref, g_ref, dx_ref, *, tile_v):
    blk = x_ref[...].astype(jnp.float32)      # [TN, TV]
    labels = lab_ref[...]
    m = m_ref[...]
    l = l_ref[...]
    g = g_ref[...]
    tn, tv = blk.shape
    jv = pl.program_id(1)
    probs = jnp.exp(blk - m[:, None]) / l[:, None]
    cols = jv * tv + jax.lax.broadcasted_iota(jnp.int32, (tn, tv), 1)
    onehot = (cols == labels[:, None]).astype(jnp.float32)
    dx_ref[...] = ((probs - onehot) * g[:, None]).astype(dx_ref.dtype)


def _xent_fwd_call(logits, labels, tile_n, tile_v):
    N, V = logits.shape
    tile_n = min(tile_n, N)
    tile_v = min(tile_v, V)
    kern = functools.partial(_fwd_kernel, tile_v=tile_v, vocab=V)
    return pl.pallas_call(
        kern,
        grid=(N // tile_n,),
        in_specs=[pl.BlockSpec((tile_n, V), lambda i: (i, 0)),
                  pl.BlockSpec((tile_n,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((tile_n,), lambda i: (i,)),
                   pl.BlockSpec((tile_n,), lambda i: (i,)),
                   pl.BlockSpec((tile_n,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((N,), jnp.float32),
                   jax.ShapeDtypeStruct((N,), jnp.float32),
                   jax.ShapeDtypeStruct((N,), jnp.float32)],
        interpret=_interpret(),
    )(logits, labels)


def _xent_bwd_call(logits, labels, m, l, g, tile_n, tile_v):
    N, V = logits.shape
    tile_n = min(tile_n, N)
    tile_v = min(tile_v, V)
    kern = functools.partial(_bwd_kernel, tile_v=tile_v)
    return pl.pallas_call(
        kern,
        grid=(N // tile_n, V // tile_v),
        in_specs=[pl.BlockSpec((tile_n, tile_v), lambda i, j: (i, j)),
                  pl.BlockSpec((tile_n,), lambda i, j: (i,)),
                  pl.BlockSpec((tile_n,), lambda i, j: (i,)),
                  pl.BlockSpec((tile_n,), lambda i, j: (i,)),
                  pl.BlockSpec((tile_n,), lambda i, j: (i,))],
        out_specs=pl.BlockSpec((tile_n, tile_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(logits.shape, logits.dtype),
        interpret=_interpret(),
    )(logits, labels, m, l, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_softmax_xent(logits, labels, tile_n: int = 128,
                       tile_v: int = 2048):
    """Per-row -log softmax(logits)[label]; logits [N, V], labels [N] int.

    Returns [N] float32 losses. Differentiable wrt logits; the softmax
    matrix is regenerated tile-wise in bwd (never stored)."""
    loss, _, _ = _xent_fwd_call(logits, labels, tile_n, tile_v)
    return loss


def _f(logits, labels, tile_n, tile_v):
    loss, m, l = _xent_fwd_call(logits, labels, tile_n, tile_v)
    return loss, (logits, labels, m, l)


def _b(tile_n, tile_v, res, g):
    logits, labels, m, l = res
    dx = _xent_bwd_call(logits, labels, m, l, g.astype(jnp.float32),
                        tile_n, tile_v)
    return dx, None


fused_softmax_xent.defvjp(_f, _b)
