"""Flash attention — Pallas TPU kernels (online softmax, O(S) memory, fwd+bwd).

Reference counterpart: the vendor-accelerated attention path
(`libnd4j/include/ops/declarable/platform/cudnn/` attention kernels and
`helpers/AttentionHelper.h`). On TPU the hot path is a Pallas kernel that
keeps only [TQ, TK] score tiles in VMEM, accumulates the online softmax in
f32 scratch, and never materializes the [S, S] probability matrix in HBM —
forward OR backward, so S=2048+ training fits where the XLA path OOMs.

Layout: q/k/v are [BH, S, D] (batch*heads flattened; callers reshape).
All three kernels use a 3-D grid whose innermost dimension is the
*sequential* stream (kv blocks for fwd/dq, q blocks for dkv) so Mosaic
double-buffers the streamed blocks while f32 accumulators persist in VMEM
scratch across the sequential steps:

  fwd : grid (BH, nQ, nK)  scratch m/l/acc     outputs o, lse=m+log(l)
  dq  : grid (BH, nQ, nK)  scratch dq_acc      p recomputed from q,k,lse
  dkv : grid (BH, nK, nQ)  scratch dk/dv_acc   ds = p * (g·vᵀ − delta)

delta = rowsum(o ⊙ do) is precomputed with plain XLA (one elementwise pass).

Sequence lengths that don't divide the tiles are zero-padded to the tile
boundary (padded keys masked off, padded query rows sliced away). A fully
masked row degrades to a uniform softmax — identical to what the XLA
softmax produces for an all-−1e30 row, and the lse identity keeps the
backward consistent with that without special cases.

Tests run interpret mode on CPU; the real chip runs compiled.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _params(n_parallel):
    # CompilerParams (jax >= 0.5) was TPUCompilerParams in 0.4.x
    cp = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cp(dimension_semantics=("parallel",) * n_parallel + ("arbitrary",))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                m_sc, l_sc, acc_sc, *, scale, causal, n_k):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # dots run in the input dtype (bf16 stays on the fast MXU path) with
    # f32 accumulation; softmax stats are always f32
    q, k, v = q_ref[0], k_ref[0], v_ref[0]               # [TQ,D],[TK,D]
    tq, tk = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if mask_ref is not None:
        s = jnp.where(mask_ref[0][:, 0][None, :] != 0, s, _NEG_INF)
    if causal:
        q_pos = iq * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = ik * tk + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

    m_prev = m_sc[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    m_sc[...] = m_new[:, None]
    l_sc[...] = l_sc[...] * alpha[:, None] + jnp.sum(p, axis=-1)[:, None]
    acc_sc[...] = acc_sc[...] * alpha[:, None] + \
        jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(ik == n_k - 1)
    def _done():
        l = l_sc[:, 0]
        o_ref[0] = (acc_sc[...] / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)
        lse_ref[0] = (m_sc[:, 0] + jnp.log(jnp.maximum(l, 1e-30)))[:, None]


def _fwd_kernel_nomask(q_ref, k_ref, v_ref, o_ref, lse_ref,
                       m_sc, l_sc, acc_sc, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                m_sc, l_sc, acc_sc, **kw)


def _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k):
    BH, S, D = q.shape
    n_q, n_k = S // tile_q, S // tile_k
    grid = (BH, n_q, n_k)
    in_specs = [
        pl.BlockSpec((1, tile_q, D), lambda bh, iq, ik: (bh, iq, 0)),
        pl.BlockSpec((1, tile_k, D), lambda bh, iq, ik: (bh, ik, 0)),
        pl.BlockSpec((1, tile_k, D), lambda bh, iq, ik: (bh, ik, 0)),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, tile_k, 1),
                                     lambda bh, iq, ik: (bh, ik, 0)))
        args.append(mask)
    kern = functools.partial(
        _fwd_kernel if mask is not None else _fwd_kernel_nomask,
        scale=scale, causal=causal, n_k=n_k)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, tile_q, D), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, tile_q, 1), lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, D), jnp.float32),
        ],
        compiler_params=_params(2),
        interpret=_interpret(),
    )(*args)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _p_tile(q, k, mask_row, lse, iq, ik, scale, causal):
    """Recompute the [TQ, TK] probability tile from saved lse."""
    tq, tk = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if mask_row is not None:
        s = jnp.where(mask_row[:, 0][None, :] != 0, s, _NEG_INF)
    if causal:
        q_pos = iq * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = ik * tk + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return jnp.exp(s - lse[:, 0][:, None]), s


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, mask_ref,
               dq_ref, dq_sc, *, scale, causal, n_k):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    q, k, v, g = q_ref[0], k_ref[0], v_ref[0], g_ref[0]
    mrow = mask_ref[0] if mask_ref is not None else None
    p, _ = _p_tile(q, k, mrow, lse_ref[0], iq, ik, scale, causal)
    dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [TQ, TK]
    ds = p * (dp - delta_ref[0])
    dq_sc[...] += jnp.dot(ds.astype(k.dtype), k,
                          preferred_element_type=jnp.float32) * scale

    @pl.when(ik == n_k - 1)
    def _done():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _dq_kernel_nomask(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                      dq_ref, dq_sc, **kw):
    _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, None,
               dq_ref, dq_sc, **kw)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, mask_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal, n_q):
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    q, k, v, g = q_ref[0], k_ref[0], v_ref[0], g_ref[0]
    mrow = mask_ref[0] if mask_ref is not None else None
    p, _ = _p_tile(q, k, mrow, lse_ref[0], iq, ik, scale, causal)
    dv_sc[...] += jax.lax.dot_general(
        p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    dk_sc[...] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale

    @pl.when(iq == n_q - 1)
    def _done():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _dkv_kernel_nomask(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_sc, dv_sc, **kw):
    _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, None,
                dk_ref, dv_ref, dk_sc, dv_sc, **kw)


def _flash_bwd(q, k, v, mask, o, lse, g, scale, causal, tile_q, tile_k,
               lse_cot=None):
    BH, S, D = q.shape
    # the bwd kernels hold three [TQ, TK] f32 tiles live (p, dp, ds); cap
    # tiles at 512 so long-seq fwd tiles (2048) don't blow the 16MB VMEM
    if tile_q > 512 and S % 512 == 0:
        tile_q = 512
    if tile_k > 512 and S % 512 == 0:
        tile_k = 512
    n_q, n_k = S // tile_q, S // tile_k
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [BH, S, 1]
    if lse_cot is not None:
        # d lse_j / d s_jk = p_jk, so an lse cotangent enters ds as
        # p * g_lse — algebraically delta' = delta - g_lse with zero
        # kernel changes (ds = p * (dp - delta'))
        delta = delta - lse_cot.astype(jnp.float32)

    def qspec(f):
        return pl.BlockSpec((1, tile_q, D), f)

    def kspec(f):
        return pl.BlockSpec((1, tile_k, D), f)

    # dq: stream kv blocks for each q block
    in_specs = [
        qspec(lambda bh, iq, ik: (bh, iq, 0)),          # q
        kspec(lambda bh, iq, ik: (bh, ik, 0)),          # k
        kspec(lambda bh, iq, ik: (bh, ik, 0)),          # v
        qspec(lambda bh, iq, ik: (bh, iq, 0)),          # g
        pl.BlockSpec((1, tile_q, 1), lambda bh, iq, ik: (bh, iq, 0)),  # lse
        pl.BlockSpec((1, tile_q, 1), lambda bh, iq, ik: (bh, iq, 0)),  # delta
    ]
    args = [q, k, v, g, lse, delta]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, tile_k, 1),
                                     lambda bh, iq, ik: (bh, ik, 0)))
        args.append(mask)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel if mask is not None else
                          _dq_kernel_nomask,
                          scale=scale, causal=causal, n_k=n_k),
        grid=(BH, n_q, n_k),
        in_specs=in_specs,
        out_specs=qspec(lambda bh, iq, ik: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((tile_q, D), jnp.float32)],
        compiler_params=_params(2),
        interpret=_interpret(),
    )(*args)

    # dk/dv: stream q blocks for each kv block
    in_specs = [
        qspec(lambda bh, ik, iq: (bh, iq, 0)),          # q
        kspec(lambda bh, ik, iq: (bh, ik, 0)),          # k
        kspec(lambda bh, ik, iq: (bh, ik, 0)),          # v
        qspec(lambda bh, ik, iq: (bh, iq, 0)),          # g
        pl.BlockSpec((1, tile_q, 1), lambda bh, ik, iq: (bh, iq, 0)),  # lse
        pl.BlockSpec((1, tile_q, 1), lambda bh, ik, iq: (bh, iq, 0)),  # delta
    ]
    args = [q, k, v, g, lse, delta]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, tile_k, 1),
                                     lambda bh, ik, iq: (bh, ik, 0)))
        args.append(mask)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel if mask is not None else
                          _dkv_kernel_nomask,
                          scale=scale, causal=causal, n_q=n_q),
        grid=(BH, n_k, n_q),
        in_specs=in_specs,
        out_specs=[kspec(lambda bh, ik, iq: (bh, ik, 0)),
                   kspec(lambda bh, ik, iq: (bh, ik, 0))],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((tile_k, D), jnp.float32),
                        pltpu.VMEM((tile_k, D), jnp.float32)],
        compiler_params=_params(2),
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing (mask variants split so mask=None stays cheap)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, tile_q, tile_k):
    o, _ = _flash_fwd(q, k, v, None, scale, causal, tile_q, tile_k)
    return o


def _flash_f(q, k, v, scale, causal, tile_q, tile_k):
    o, lse = _flash_fwd(q, k, v, None, scale, causal, tile_q, tile_k)
    return o, (q, k, v, o, lse)


def _flash_b(scale, causal, tile_q, tile_k, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, None, o, lse, g, scale, causal, tile_q, tile_k)


_flash.defvjp(_flash_f, _flash_b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_masked(q, k, v, mask, scale, causal, tile_q, tile_k):
    o, _ = _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k)
    return o


def _flash_masked_f(q, k, v, mask, scale, causal, tile_q, tile_k):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k)
    return o, (q, k, v, mask, o, lse)


def _flash_masked_b(scale, causal, tile_q, tile_k, res, g):
    q, k, v, mask, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, mask, o, lse, g, scale, causal,
                            tile_q, tile_k)
    return dq, dk, dv, None


_flash_masked.defvjp(_flash_masked_f, _flash_masked_b)


# (o, lse)-returning variant: the ring/SP path needs the per-block lse to
# merge block outputs exactly; both outputs are differentiable (the lse
# cotangent rides the delta term, see _flash_bwd).

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_lse_masked(q, k, v, mask, scale, causal, tile_q, tile_k):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k)
    return o, lse


def _flash_lse_masked_f(q, k, v, mask, scale, causal, tile_q, tile_k):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k)
    return (o, lse), (q, k, v, mask, o, lse)


def _flash_lse_masked_b(scale, causal, tile_q, tile_k, res, g):
    q, k, v, mask, o, lse = res
    g_o, g_lse = g
    dq, dk, dv = _flash_bwd(q, k, v, mask, o, lse, g_o, scale, causal,
                            tile_q, tile_k, lse_cot=g_lse)
    return dq, dk, dv, None


_flash_lse_masked.defvjp(_flash_lse_masked_f, _flash_lse_masked_b)


def _fit_tile(want, s_pad):
    """Largest multiple of 128 ≤ want that divides s_pad (s_pad is a
    multiple of 128)."""
    t = min(want, s_pad)
    t -= t % 128
    while s_pad % t:
        t -= 128
    return t


def _prep(q, k, v, mask, scale, tile_q, tile_k):
    """Resolve tiles, zero-pad S to the tile boundary, flatten to the
    kernels' [B*H, S_pad, D] layout. Returns (qf, kf, vf, mf, scale,
    tile_q, tile_k, S, S_pad, B, H, D)."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    if tile_q is None or tile_k is None:
        if S <= 128:
            S_pad = S
            tile_q = tile_k = S
        else:
            S_pad = -(-S // 128) * 128
            tile_q = _fit_tile(tile_q or 2048, S_pad)
            tile_k = _fit_tile(tile_k or 512, S_pad)
    else:
        tile_q = min(tile_q, max(S, 1))
        tile_k = min(tile_k, max(S, 1))
        lcm = tile_q * tile_k // math.gcd(tile_q, tile_k)
        S_pad = -(-S // lcm) * lcm
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        if mask is None:
            mask = jnp.ones((B, S), jnp.int32)
        mask = jnp.pad(mask, [(0, 0), (0, S_pad - S)])
    qf = jnp.moveaxis(q, 2, 1).reshape(B * H, S_pad, D)
    kf = jnp.moveaxis(k, 2, 1).reshape(B * H, S_pad, D)
    vf = jnp.moveaxis(v, 2, 1).reshape(B * H, S_pad, D)
    mf = (jnp.repeat(mask.astype(jnp.int32), H, axis=0)[..., None]
          if mask is not None else None)
    return qf, kf, vf, mf, scale, tile_q, tile_k, S, S_pad, B, H, D


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    scale: float = None, tile_q: int = None,
                    tile_k: int = None):
    """Flash attention over [B, S, H, D] (BTHD, the framework convention).

    mask: optional [B, S] key validity (1 = attend). Differentiable in
    q/k/v; O(S) HBM in both forward and backward (the probability matrix
    only ever exists as [tile_q, tile_k] VMEM tiles).
    Any S is accepted: inputs are zero-padded to the tile boundary (padded
    keys masked off; padded query rows sliced away).

    Default tiles are tuned on v5e at S=2048, D=64 (tq=2048/tk=512:
    fwd 4.7ms vs XLA 8.8/7.1ms f32/bf16; train 5.8-6.1ms vs 13.5/7.5ms);
    they shrink to divisors of the padded length for other shapes."""
    (qf, kf, vf, mf, scale, tile_q, tile_k,
     S, S_pad, B, H, D) = _prep(q, k, v, mask, scale, tile_q, tile_k)
    if mf is not None:
        out = _flash_masked(qf, kf, vf, mf, scale, causal, tile_q, tile_k)
    else:
        out = _flash(qf, kf, vf, scale, causal, tile_q, tile_k)
    out = jnp.moveaxis(out.reshape(B, H, S_pad, D), 1, 2)
    return out[:, :S] if S_pad != S else out


def flash_attention_with_lse(q, k, v, mask=None, causal: bool = False,
                             scale: float = None, tile_q: int = None,
                             tile_k: int = None):
    """flash_attention that also returns the log-sum-exp of the scores.

    Returns (out [B, S, H, D], lse [B, H, S] f32). The lse is what a
    sequence-parallel caller (parallel/ring_attention.py) needs to merge
    per-KV-block outputs into the exact global softmax; it is
    differentiable alongside out (the lse cotangent folds into the
    backward kernels' delta term).
    """
    (qf, kf, vf, mf, scale, tile_q, tile_k,
     S, S_pad, B, H, D) = _prep(q, k, v, mask, scale, tile_q, tile_k)
    out, lse = _flash_lse_masked(qf, kf, vf, mf, scale, causal,
                                 tile_q, tile_k)
    out = jnp.moveaxis(out.reshape(B, H, S_pad, D), 1, 2)
    lse = lse.reshape(B, H, S_pad)
    if S_pad != S:
        out, lse = out[:, :S], lse[:, :, :S]
    return out, lse
