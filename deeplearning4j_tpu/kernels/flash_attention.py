"""Flash attention — Pallas TPU kernel (online-softmax, O(S) memory).

Reference counterpart: the vendor-accelerated attention path
(`libnd4j/include/ops/declarable/platform/cudnn/` attention kernels and
`helpers/AttentionHelper.h`). On TPU the hot path is a Pallas kernel that
keeps the [TQ, TK] score tile in VMEM, accumulates the online softmax in
f32, and never materializes the [S, S] probability matrix in HBM.

Layout: q/k/v are [BH, S, D] (batch*heads flattened into the grid's first
axis; callers reshape). The kernel grid is (BH, S // TILE_Q); each program
streams K/V blocks of TILE_K rows with jax.lax.fori_loop.

Backward: jax.custom_vjp whose bwd recomputes attention with the standard
XLA path — NOTE this materializes the [S, S] score matrix in the backward,
so the O(S) memory benefit applies to the forward/inference path only (a
flash backward kernel is the follow-up for O(S) training memory).

Sequence lengths that don't divide the tiles are zero-padded to the tile
boundary (padded keys masked off, padded query rows sliced away).

Tests run interpret mode on CPU; the real chip runs compiled.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale, tile_k,
                seq_len, causal, q_tile):
    q = q_ref[0].astype(jnp.float32)                      # [TQ, D]
    tq = q.shape[0]
    iq = pl.program_id(1)
    q_start = iq * q_tile

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * tile_k, tile_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * tile_k, tile_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            km = mask_ref[0, pl.ds(j * tile_k, tile_k)]
            s = jnp.where(km[None, :] != 0, s, _NEG_INF)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                       (tq, tile_k), 0)
            k_pos = j * tile_k + jax.lax.broadcasted_iota(jnp.int32,
                                                          (tq, tile_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + \
            jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((tq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((tq,), jnp.float32)
    a0 = jnp.zeros((tq, q.shape[1]), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, seq_len // tile_k, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k):
    BH, S, D = q.shape
    tile_q = min(tile_q, S)
    tile_k = min(tile_k, S)
    grid = (BH, S // tile_q)
    in_specs = [
        pl.BlockSpec((1, tile_q, D), lambda bh, iq: (bh, iq, 0)),
        pl.BlockSpec((1, S, D), lambda bh, iq: (bh, 0, 0)),
        pl.BlockSpec((1, S, D), lambda bh, iq: (bh, 0, 0)),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, S), lambda bh, iq: (bh, 0)))
        args.append(mask)
    kern = functools.partial(
        _fwd_kernel if mask is not None else _fwd_kernel_nomask,
        scale=scale, tile_k=tile_k, seq_len=S, causal=causal, q_tile=tile_q)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, tile_q, D), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*args)


def _fwd_kernel_nomask(q_ref, k_ref, v_ref, o_ref, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, **kw)


def _reference(q, k, v, mask, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask[:, None, :] != 0, s, _NEG_INF)
    if causal:
        S = q.shape[1]
        tri = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(tri[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, has_mask_sentinel, scale, causal, tile_q, tile_k):
    # has_mask_sentinel unused in the no-mask overload; see flash_attention
    return _flash_fwd(q, k, v, None, scale, causal, tile_q, tile_k)


def _flash_f(q, k, v, has_mask_sentinel, scale, causal, tile_q, tile_k):
    out = _flash_fwd(q, k, v, None, scale, causal, tile_q, tile_k)
    return out, (q, k, v)


def _flash_b(has_mask_sentinel, scale, causal, tile_q, tile_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, None, scale,
                                                   causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_f, _flash_b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_masked(q, k, v, mask, scale, causal, tile_q, tile_k):
    return _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k)


def _flash_masked_f(q, k, v, mask, scale, causal, tile_q, tile_k):
    out = _flash_fwd(q, k, v, mask, scale, causal, tile_q, tile_k)
    return out, (q, k, v, mask)


def _flash_masked_b(scale, causal, tile_q, tile_k, res, g):
    q, k, v, mask = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, mask, scale,
                                                   causal), q, k, v)
    return vjp(g) + (None,)


_flash_masked.defvjp(_flash_masked_f, _flash_masked_b)


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    scale: float = None, tile_q: int = 128,
                    tile_k: int = 128):
    """Flash attention over [B, S, H, D] (BTHD, the framework convention).

    mask: optional [B, S] key validity (1 = attend). Differentiable.
    Any S is accepted: inputs are zero-padded to the tile boundary (padded
    keys masked off; padded query rows sliced away)."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    tile_q = min(tile_q, max(S, 1))
    tile_k = min(tile_k, max(S, 1))
    lcm = tile_q * tile_k // math.gcd(tile_q, tile_k)
    S_pad = -(-S // lcm) * lcm
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        if mask is None:
            mask = jnp.ones((B, S), jnp.int32)
        mask = jnp.pad(mask, [(0, 0), (0, S_pad - S)])
    qf = jnp.moveaxis(q, 2, 1).reshape(B * H, S_pad, D)
    kf = jnp.moveaxis(k, 2, 1).reshape(B * H, S_pad, D)
    vf = jnp.moveaxis(v, 2, 1).reshape(B * H, S_pad, D)
    if mask is not None:
        mf = jnp.repeat(mask.astype(jnp.int32), H, axis=0)
        out = _flash_masked(qf, kf, vf, mf, scale, causal, tile_q, tile_k)
    else:
        out = _flash(qf, kf, vf, 0, scale, causal, tile_q, tile_k)
    out = jnp.moveaxis(out.reshape(B, H, S_pad, D), 1, 2)
    return out[:, :S] if S_pad != S else out
