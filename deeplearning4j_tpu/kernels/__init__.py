"""Pallas TPU kernels — the hand-written hot-op layer.

Role parity: the reference's per-op vendor kernels
(`libnd4j/include/ops/declarable/platform/{cudnn,mkldnn}/`) — ops where
letting the compiler lower naively leaves performance on the table. On TPU
that list is short (XLA fuses most of the op library); the kernels here
cover the known gaps for the flagship workloads:

- `flash_attention`: online-softmax attention with a full Pallas backward —
  no [S,S] HBM materialization in either direction. Measured on v5e at
  B=4 S=2048 H=12 D=64: 1.27x XLA forward, 1.64x XLA training step; at
  S=8192 the XLA path cannot compile on one chip while this trains.
- `paged_flash_decode`: the decode-side counterpart — walks the paged KV
  block tables in-kernel (scalar-prefetch) with online-softmax
  accumulation, replacing the `jnp.take` gather read of
  `models.causal_lm.paged_decode` (gated by ``DL4J_TPU_PAGED_KERNEL``).

A fused vocab-tiled softmax-xent kernel lived here through round 3 and was
deleted after honest tuning kept it behind XLA at the BERT headline shape
(N=16384, V=30522, f32; best Pallas config tn=256 tv=2048): 0.93x forward,
0.61x training vs XLA's 35.4ms/35.2ms. XLA's exp/reduce fusion already
saturates this op; a kernel would need to fuse the producing matmul to win,
which belongs to a future logits-never-materialized head design.

The kernels run `interpret=True` on CPU so the unit tests exercise the
exact kernel code path hardware-free.
"""
from typing import Dict, Optional

from .flash_attention import flash_attention, flash_attention_with_lse
from .paged_flash_decode import paged_flash_decode

__all__ = ["flash_attention", "flash_attention_with_lse",
           "paged_flash_decode", "attention_dispatch", "kernel_dispatch",
           "dispatch_snapshot"]

_dispatch_logged = False

#: last trace-time path decision per kernel family — what
#: ``DecodeEngine.debug_snapshot`` (GET /debug/decode, flight recorder)
#: reports as "which path served the most recent compile in this process"
_last_dispatch: Dict[str, Dict[str, Optional[str]]] = {}


def kernel_dispatch(kernel: str, path: str, reason: str = "") -> str:
    """Record one trace-time kernel-vs-fallback decision: ticks
    ``dl4j_kernel_dispatch_total{kernel,path}`` and updates the
    last-dispatch snapshot. ``reason`` says why a fallback won (empty for
    the hand-written kernel path). Returns ``path`` so dispatchers can
    tail-call it."""
    _last_dispatch[kernel] = {"kernel": kernel, "path": path,
                              "reason": reason or None}
    try:
        from ..common.environment import environment
        environment().metrics().counter(
            "dl4j_kernel_dispatch_total",
            "Hand-written-kernel vs fallback path decisions per kernel "
            "family, evaluated at trace time",
            labels=("kernel", "path")).labels(
                kernel=kernel, path=path).inc()
    except Exception:
        pass  # observability must never break a trace
    return path


def dispatch_snapshot() -> Dict[str, Dict[str, Optional[str]]]:
    """Copy of the last dispatch decision per kernel family:
    ``{kernel: {"kernel", "path", "reason"}}``. Process-global (dispatch
    happens at trace time, once per compiled executable)."""
    return {k: dict(v) for k, v in _last_dispatch.items()}


def _paged_path(env, head_dim, block_size):
    """Path for ``paged=True`` dispatch: "paged_flash" (the Pallas
    block-table kernel) or "paged" (the XLA gather fallback), plus the
    fallback reason. Deliberately independent of the query length — see
    attention_dispatch's docstring."""
    if head_dim is None or block_size is None:
        # gather-view callers that never hand over tiling info (e.g.
        # paged_prefill) stay on the gather path by contract
        return "paged", "caller provides no tile info (gather-view path)"
    mode = env.paged_kernel()
    if mode == "off":
        return "paged", "DL4J_TPU_PAGED_KERNEL=off"
    if mode == "on":
        return "paged_flash", ""
    # auto: hardware only, and only when the pool layout tiles natively
    import jax
    if jax.default_backend() == "cpu":
        return "paged", "cpu backend (auto gates the kernel to accelerators)"
    from .paged_flash_decode import tileable
    if not tileable(head_dim, block_size):
        return "paged", (f"untileable pool layout: head_dim={head_dim} "
                         f"block_size={block_size}")
    return "paged_flash", ""


def attention_dispatch(seq_len: int, paged: bool = False, *,
                       head_dim: Optional[int] = None,
                       block_size: Optional[int] = None) -> str:
    """Auto-dispatch for ``flash=True`` attention configs: "flash",
    "xla", "paged", or "paged_flash".

    ``paged=True`` marks the paged-KV decode path
    (``models.causal_lm.paged_decode``): when the caller passes the pool
    tiling (``head_dim``/``block_size``) the Pallas block-table kernel
    ("paged_flash") is eligible per ``DL4J_TPU_PAGED_KERNEL`` — "auto"
    (default) takes it on accelerator backends when
    ``paged_flash_decode.tileable`` holds, "on" forces it (interpret
    mode off-accelerator), "off" pins the XLA gather fallback ("paged").
    The decision deliberately ignores ``seq_len``: on the paged path the
    query length is the *per-slot* token count — 1 for the decode step,
    k+1 for the speculative verify — and both must land on the same path
    or a spec-k engine would flap between executables mid-stream. The
    seq<2 XLA pin below applies only to the non-paged (slab) path, where
    seq_len really is the attention width. Gather-view callers that pass
    no tiling info (``paged_prefill``) always get "paged".

    BENCH_r05 measured the flash BERT variant at 93.7 samples/sec vs 1373
    for plain XLA attention at seq_len=128 — the Pallas kernel's blocking
    only pays past roughly ``DL4J_TPU_FLASH_MIN_SEQ`` (default 1024), so
    below the threshold flash-requesting models silently take the XLA
    path. Evaluated at trace time (shapes are static under jit), so the
    ``dl4j_attn_dispatch_total{path=}`` and
    ``dl4j_kernel_dispatch_total{kernel,path}`` counters tick once per
    compiled executable, and the debug log fires once per process.

    Decode-shaped queries (seq_len < 2 — the KV-cached single-token step
    of ``runtime.generation.DecodeEngine``) take the XLA path
    UNCONDITIONALLY on the non-paged path, whatever
    ``DL4J_TPU_FLASH_MIN_SEQ`` says: a 1-row query can never amortize the
    Pallas kernel's blocking, and the decode executable must stay stable
    across env retunes."""
    global _dispatch_logged
    from ..common.environment import environment

    env = environment()
    reason = ""
    if paged:
        path, reason = _paged_path(env, head_dim, block_size)
    elif int(seq_len) < 2:
        path, reason = "xla", "seq_len<2 decode pin"
    elif int(seq_len) >= env.flash_min_seq():
        path = "flash"
    else:
        path, reason = "xla", "seq_len<DL4J_TPU_FLASH_MIN_SEQ"
    try:
        env.metrics().counter(
            "dl4j_attn_dispatch_total",
            "Attention path decisions for flash=True configs",
            labels=("path",)).labels(path=path).inc()
    except Exception:
        pass  # observability must never break a trace
    kernel_dispatch("paged_decode" if paged else "attention", path, reason)
    if path == "xla" and not _dispatch_logged:
        _dispatch_logged = True
        import logging
        logging.getLogger(__name__).debug(
            "flash=True requested at seq_len=%d < DL4J_TPU_FLASH_MIN_SEQ=%d;"
            " using the XLA attention path (override the threshold via the"
            " env var)", seq_len, env.flash_min_seq())
    return path
