"""Pallas TPU kernels — the hand-written hot-op layer.

Role parity: the reference's per-op vendor kernels
(`libnd4j/include/ops/declarable/platform/{cudnn,mkldnn}/`) — ops where
letting the compiler lower naively leaves performance on the table. On TPU
that list is short (XLA fuses most of the op library); the kernel here
covers the known gap for the flagship workloads:

- `flash_attention`: online-softmax attention with a full Pallas backward —
  no [S,S] HBM materialization in either direction. Measured on v5e at
  B=4 S=2048 H=12 D=64: 1.27x XLA forward, 1.64x XLA training step; at
  S=8192 the XLA path cannot compile on one chip while this trains.

A fused vocab-tiled softmax-xent kernel lived here through round 3 and was
deleted after honest tuning kept it behind XLA at the BERT headline shape
(N=16384, V=30522, f32; best Pallas config tn=256 tv=2048): 0.93x forward,
0.61x training vs XLA's 35.4ms/35.2ms. XLA's exp/reduce fusion already
saturates this op; a kernel would need to fuse the producing matmul to win,
which belongs to a future logits-never-materialized head design.

The kernel runs `interpret=True` on CPU so the unit tests exercise the
exact kernel code path hardware-free.
"""
from .flash_attention import flash_attention, flash_attention_with_lse

__all__ = ["flash_attention", "flash_attention_with_lse",
           "attention_dispatch"]

_dispatch_logged = False


def attention_dispatch(seq_len: int, paged: bool = False) -> str:
    """Auto-dispatch for ``flash=True`` attention configs: "flash",
    "xla", or "paged".

    ``paged=True`` marks the block-table gather-attention path of the
    paged KV cache (``models.causal_lm.paged_decode``): it always
    computes via XLA einsums over the gathered block view — never the
    Pallas flash kernel, whatever the query length — and records its own
    ``dl4j_attn_dispatch_total{path=paged}`` label so the paged and slab
    decode paths are distinguishable in telemetry. Decode shapes
    (seq_len < 2) stay pinned to XLA on the non-paged path exactly as
    before.

    BENCH_r05 measured the flash BERT variant at 93.7 samples/sec vs 1373
    for plain XLA attention at seq_len=128 — the Pallas kernel's blocking
    only pays past roughly ``DL4J_TPU_FLASH_MIN_SEQ`` (default 1024), so
    below the threshold flash-requesting models silently take the XLA
    path. Evaluated at trace time (shapes are static under jit), so the
    ``dl4j_attn_dispatch_total{path=}`` counter ticks once per compiled
    executable, and the debug log fires once per process.

    Decode-shaped queries (seq_len < 2 — the KV-cached single-token step
    of ``runtime.generation.DecodeEngine``) take the XLA path
    UNCONDITIONALLY, whatever ``DL4J_TPU_FLASH_MIN_SEQ`` says: a 1-row
    query can never amortize the Pallas kernel's blocking, and the decode
    executable must stay stable across env retunes."""
    global _dispatch_logged
    from ..common.environment import environment

    env = environment()
    if paged:
        path = "paged"
    elif int(seq_len) < 2:
        path = "xla"
    else:
        path = "flash" if int(seq_len) >= env.flash_min_seq() else "xla"
    try:
        env.metrics().counter(
            "dl4j_attn_dispatch_total",
            "Attention path decisions for flash=True configs",
            labels=("path",)).labels(path=path).inc()
    except Exception:
        pass  # observability must never break a trace
    if path == "xla" and not _dispatch_logged:
        _dispatch_logged = True
        import logging
        logging.getLogger(__name__).debug(
            "flash=True requested at seq_len=%d < DL4J_TPU_FLASH_MIN_SEQ=%d;"
            " using the XLA attention path (override the threshold via the"
            " env var)", seq_len, env.flash_min_seq())
    return path
