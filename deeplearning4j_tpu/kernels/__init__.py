"""Pallas TPU kernels — the hand-written hot-op layer.

Role parity: the reference's per-op vendor kernels
(`libnd4j/include/ops/declarable/platform/{cudnn,mkldnn}/`) — ops where
letting the compiler lower naively leaves performance on the table. On TPU
that list is short (XLA fuses most of the op library); the kernels here
cover the two known gaps for the flagship workloads:

- `flash_attention`: online-softmax attention, no [S,S] HBM materialization
- `fused_softmax_xent`: streaming vocab-tiled MLM loss (30k vocab)

All kernels run `interpret=True` on CPU so the unit tests exercise the
exact kernel code path hardware-free.
"""
from .flash_attention import flash_attention
from .softmax_xent import fused_softmax_xent

__all__ = ["flash_attention", "fused_softmax_xent"]
