"""Paged-flash decode — Pallas TPU kernel over the paged KV block pool.

The XLA fallback in ``models.causal_lm.paged_decode`` reads the cache by
gathering every slot's blocks into a contiguous ``[S, C, H, D]`` view
(``jnp.take`` over the block table) and running dense einsum attention
against it — one full round-trip of the slot's KV through HBM per layer
per step, plus the materialized gather copy. This kernel closes that gap
the way PagedAttention (vLLM, SOSP'23) and Flash-Decoding do: the block
table itself rides into the kernel as a *scalar-prefetch* operand, the
grid walks ``(slot, table_column)``, and each KV block is DMA'd HBM→VMEM
exactly once, straight from its pool position — no gathered copy ever
exists. Scores accumulate through the standard online-softmax recurrence
(f32 m/l/acc VMEM scratch persisting across the sequential block walk),
with per-slot length masking so scratch blocks (table padding points at
block 0) and uncommitted tail rows contribute nothing.

``Q`` is the per-slot query count: 1 for the classic decode step, k+1
for the speculative verify pass — one kernel serves both, and the
dispatch decision (``kernels.attention_dispatch``) deliberately ignores
``Q`` so spec-k configs can never flap between paths mid-stream.

Layouts match the pool exactly (no transposes at the call site):

  q                [S, Q, H, D]   queries at positions lengths[s]+0..Q-1
  k_pages/v_pages  [N, Bs, H, D]  one layer's slice of the block pool
  tables           [S, MB] int32  per-slot block table (0 = scratch)
  lengths          [S]     int32  committed rows per slot

Heads are walked inside the kernel body (H is static and small for the
decode shapes this serves), so one block fetch feeds all heads. Tests
run interpret mode on CPU; the real chip runs compiled.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret, _params

_NEG_INF = -1e30


def tileable(head_dim: int, block_size: int) -> bool:
    """Whether the paged KV layout hits Mosaic's native f32/bf16 tiling
    on hardware: the lane dim of every streamed block is ``head_dim``
    and the key sublane dim is ``block_size``. Shapes that fail this run
    the XLA gather fallback under ``DL4J_TPU_PAGED_KERNEL=auto`` (the
    compiled kernel would pad each tiny block up to a full tile and lose
    to the gather); interpret mode accepts any shape."""
    return int(head_dim) % 128 == 0 and int(block_size) % 8 == 0


def _kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
            m_sc, l_sc, acc_sc, *, scale, n_blocks, heads):
    s, b = pl.program_id(0), pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    n_q, bs = q_ref.shape[1], k_ref.shape[1]
    # logical row each key of this table column occupies in the slot's
    # sequence vs the row each query writes at: key row r is visible to
    # query qi iff r <= lengths[s]+qi — identical to the gather path's
    # key_mask, and it zeroes scratch-block padding (columns past the
    # slot's allocation point at block 0 but their logical rows exceed
    # every query position)
    row = b * bs + jax.lax.broadcasted_iota(jnp.int32, (n_q, bs), 1)
    qpos = lengths_ref[s] + jax.lax.broadcasted_iota(
        jnp.int32, (n_q, bs), 0)
    mask = row <= qpos

    for h in range(heads):
        q, k, v = q_ref[0, :, h, :], k_ref[0, :, h, :], v_ref[0, :, h, :]
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        sc = jnp.where(mask, sc, _NEG_INF)
        m_prev = m_sc[h][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
        p = jnp.exp(sc - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        m_sc[h] = m_new[:, None]
        l_sc[h] = l_sc[h] * alpha[:, None] + jnp.sum(p, axis=-1)[:, None]
        acc_sc[h] = acc_sc[h] * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(b == n_blocks - 1)
    def _done():
        for h in range(heads):
            l = jnp.maximum(l_sc[h][:, 0], 1e-30)
            o_ref[0, :, h, :] = (acc_sc[h] / l[:, None]).astype(o_ref.dtype)


def paged_flash_decode(q, k_pages, v_pages, tables, lengths,
                       scale: float = None, interpret: bool = None):
    """Attention context for the paged decode step, read straight from
    the block pool. Returns ``ctx [S, Q, H, D]`` in ``q.dtype`` — the
    drop-in replacement for the gather path's softmax(QKᵀ)·V (the caller
    keeps its own QKV projections, cache scatter and output projection).

    The K/V pages must already hold the current step's rows: callers
    scatter the fresh K/V through the block table first (exactly as the
    gather path does) and pass the updated pool slice in.
    """
    S, Q, H, D = q.shape
    Bs = k_pages.shape[1]
    MB = tables.shape[1]
    scale = D ** -0.5 if scale is None else scale
    if interpret is None:
        interpret = _interpret()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, MB),
        in_specs=[
            pl.BlockSpec((1, Q, H, D), lambda s, b, t, ln: (s, 0, 0, 0)),
            # the in-kernel block-table walk: the KV index maps read the
            # prefetched table, so each grid step DMAs its pool block
            # directly — the gather copy never exists
            pl.BlockSpec((1, Bs, H, D),
                         lambda s, b, t, ln: (t[s, b], 0, 0, 0)),
            pl.BlockSpec((1, Bs, H, D),
                         lambda s, b, t, ln: (t[s, b], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Q, H, D),
                               lambda s, b, t, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, Q, 1), jnp.float32),
            pltpu.VMEM((H, Q, 1), jnp.float32),
            pltpu.VMEM((H, Q, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, scale=scale, n_blocks=MB, heads=H),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Q, H, D), q.dtype),
        compiler_params=_params(1),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages)
