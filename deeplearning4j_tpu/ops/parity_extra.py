"""Parity-op tail: remaining reference ops not covered by a family module.

Reference: `headers/parity_ops.h` stragglers (Assert, confusion_matrix,
fake_quant*, compare_and_bitpack, create_view, norm, min_max_datatype,
broadcastgradientargs), `headers/convo.h` deconv2d_tf + conv2d_input_bp,
`headers/decoder.h` ctc_beam, `headers/util.h` print_variable,
`headers/BarnesHutTsne.h` (t-SNE kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import op
from .conv_ops import deconv2d


_callback_support = None  # None = unprobed


def _host_callbacks_supported() -> bool:
    """Some PJRT backends (the axon TPU tunnel) reject host send/recv
    callbacks with UNIMPLEMENTED at run time — probe once with a tiny jitted
    callback so Assert only takes the checked path where it can execute."""
    global _callback_support
    if _callback_support is None:
        try:
            @jax.jit
            def _probe(x):
                jax.debug.callback(lambda v: None, x)
                return x

            # ensure_compile_time_eval: Assert is usually first hit while
            # TRACING a user function — without it the probe would be
            # staged into that outer trace instead of executing now
            with jax.ensure_compile_time_eval():
                jax.block_until_ready(_probe(jnp.asarray(0)))
                # callback failures surface out-of-band on some backends —
                # flush outstanding effects before declaring support
                jax.effects_barrier()
            _callback_support = True
        except Exception:
            _callback_support = False
    return _callback_support


@op("Assert", "parity", differentiable=False)
def assert_op(condition, *data, message="assertion failed"):
    """Host-checked assert (reference Assert).

    Eager: raises AssertionError immediately. Under jit the condition is
    routed through a host callback that raises when it is False at runtime,
    so an imported graph keeps its checks when compiled, instead of
    silently dropping them. On backends without host-callback support
    (probed once) the jit path degrades to the old no-op with a warning."""
    try:
        ok = bool(jnp.all(condition))
    except jax.errors.TracerBoolConversionError:
        if not _host_callbacks_supported():
            import logging
            logging.getLogger(__name__).warning(
                "Assert under jit is a no-op: backend does not support "
                "host callbacks")
            return jnp.asarray(True)

        def _host_check(ok_value):
            if not bool(np.all(ok_value)):
                raise AssertionError(message)

        jax.debug.callback(_host_check, jnp.all(condition))
        return jnp.asarray(True)
    if not ok:
        raise AssertionError(message)
    return jnp.asarray(True)


@op("confusion_matrix", "parity", differentiable=False)
def confusion_matrix(labels, predictions, num_classes=None, weights=None):
    n = int(num_classes) if num_classes is not None else \
        int(jnp.maximum(jnp.max(labels), jnp.max(predictions))) + 1
    idx = labels.astype(jnp.int32) * n + predictions.astype(jnp.int32)
    w = weights if weights is not None else jnp.ones_like(idx, jnp.float32)
    cm = jnp.zeros((n * n,), w.dtype).at[idx].add(w)
    return cm.reshape(n, n)


@op("fake_quant_with_min_max_vars", "parity")
def fake_quant_with_min_max_vars(x, min_val, max_val, num_bits=8,
                                 narrow_range=False):
    qmin = 1.0 if narrow_range else 0.0
    qmax = float(2 ** int(num_bits) - 1)
    mn = jnp.asarray(min_val, x.dtype)
    mx = jnp.asarray(max_val, x.dtype)
    scale = (mx - mn) / (qmax - qmin)
    # zero point via inv-scale multiply, not division: XLA lowers x/s to
    # x * (1/s) whose reciprocal rounding can push an exact half-integer
    # (e.g. 127.5 for [-1.5, 1.5]) off the std::round nudge TF computes
    inv_scale = (qmax - qmin) / (mx - mn)
    zero = qmin - mn * inv_scale
    # std::round semantics (half-away-from-zero; zero >= qmin >= 0 after
    # clip), not jnp.round's half-to-even
    zero = jnp.clip(jnp.floor(zero + 0.5), qmin, qmax)
    nudged_min = (qmin - zero) * scale
    nudged_max = (qmax - zero) * scale
    clipped = jnp.clip(x, nudged_min, nudged_max)
    q = jnp.round((clipped - nudged_min) * inv_scale)
    return q * scale + nudged_min


@op("fake_quant_with_min_max_vars_per_channel", "parity")
def fake_quant_per_channel(x, min_val, max_val, num_bits=8,
                           narrow_range=False):
    return fake_quant_with_min_max_vars(x, min_val, max_val, num_bits,
                                        narrow_range)


@op("compare_and_bitpack", "parity", differentiable=False)
def compare_and_bitpack(x, threshold):
    """Pack (x > threshold) bits into uint8, 8 values per byte (TF op)."""
    bits = (x > threshold).astype(jnp.uint8)
    flat = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(flat * weights, axis=-1).astype(jnp.uint8)


@op("create_view", "parity", differentiable=False)
def create_view(x, *index_args, **_):
    """Reference create_view builds a strided view; functionally a slice
    alias (views are emulated at the NDArray layer)."""
    return jnp.asarray(x)


@op("norm", "parity")
def norm(x, mode=0, dims=None, keep_dims=False):
    """Reference norm op: mode 0=fro, 1=max, 2=1-norm, ...; dims optional."""
    axis = tuple(dims) if dims else None
    if mode in (0, "fro", "euclidean"):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis,
                                keepdims=keep_dims))
    if mode in (1, "max", "inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keep_dims)
    return jnp.sum(jnp.abs(x), axis=axis, keepdims=keep_dims)


@op("min_max_datatype", "datatypes", differentiable=False)
def min_max_datatype(dtype, min_or_max=0):
    from ..common.dtype import DataType
    dt = DataType.from_any(dtype).jax
    if jnp.issubdtype(dt, jnp.floating):
        info = jnp.finfo(dt)
    else:
        info = jnp.iinfo(dt)
    return jnp.asarray(info.min if min_or_max == 0 else info.max, dt)


@op("broadcastgradientargs", "parity", differentiable=False)
def broadcast_gradient_args(shape_a, shape_b):
    """Axes each operand was broadcast over (TF BroadcastGradientArgs) —
    the reduction axes for each grad in a broadcast binary op's bp."""
    sa = [int(s) for s in np.asarray(shape_a)]
    sb = [int(s) for s in np.asarray(shape_b)]
    rank = max(len(sa), len(sb))
    pa = [1] * (rank - len(sa)) + sa
    pb = [1] * (rank - len(sb)) + sb
    ra = [i for i in range(rank) if pa[i] == 1 and pb[i] != 1]
    rb = [i for i in range(rank) if pb[i] == 1 and pa[i] != 1]
    return (np.asarray(ra, np.int64), np.asarray(rb, np.int64))


@op("deconv2d_tf", "conv")
def deconv2d_tf(output_shape, weights, grad_out, strides=(1, 1),
                padding="SAME", data_format="NHWC"):
    """TF Conv2DBackpropInput flavor: explicit output shape tensor
    (reference deconv2d_tf)."""
    return deconv2d(grad_out, weights, None, strides=strides,
                    padding=padding, data_format=data_format)


@op("conv2d_input_bp", "conv")
def conv2d_input_bp(input_shape, weights, grad_out, strides=(1, 1),
                    padding="SAME", dilation=(1, 1), data_format="NCHW"):
    """Gradient of conv2d wrt its input (reference conv2d_input_bp)."""
    shape = tuple(int(s) for s in np.asarray(input_shape))

    def fwd(x):
        from .conv_ops import conv2d
        return conv2d(x, weights, None, strides=strides, padding=padding,
                      dilation=dilation, data_format=data_format)

    zeros = jnp.zeros(shape, weights.dtype)
    _, vjp = jax.vjp(fwd, zeros)
    return vjp(grad_out)[0]


@op("ctc_beam", "decoder", differentiable=False)
def ctc_beam(logits, sequence_length=None, beam_width=8, blank_index=0,
             top_paths=1):
    """CTC beam-search decoder (reference headers/decoder.h ctc_beam).

    logits: [B, T, C] (or [T, C]). Host-side numpy beam search — decode is
    not a training-path op. Returns (paths [B, top, T], log_probs
    [B, top])."""
    arr = np.asarray(jax.device_get(logits), np.float32)
    if arr.ndim == 2:
        arr = arr[None]
    B, T, C = arr.shape
    logp = arr - np.logaddexp.reduce(arr, axis=-1, keepdims=True)
    out_paths = np.full((B, top_paths, T), -1, np.int64)
    out_logp = np.full((B, top_paths), -np.inf, np.float32)
    for b in range(B):
        Tb = int(sequence_length[b]) if sequence_length is not None else T
        # beam: prefix tuple -> (p_blank, p_nonblank) in log space
        beams = {(): (0.0, -np.inf)}
        for t in range(Tb):
            new = {}
            for prefix, (pb, pnb) in beams.items():
                for c in range(C):
                    p = logp[b, t, c]
                    if c == blank_index:
                        key = prefix
                        npb, nnb = new.get(key, (-np.inf, -np.inf))
                        new[key] = (np.logaddexp(npb,
                                                 np.logaddexp(pb, pnb) + p),
                                    nnb)
                    else:
                        key = prefix + (c,)
                        npb, nnb = new.get(key, (-np.inf, -np.inf))
                        if prefix and prefix[-1] == c:
                            nnb = np.logaddexp(nnb, pb + p)
                            opb, onb = new.get(prefix, (-np.inf, -np.inf))
                            new[prefix] = (opb, np.logaddexp(onb, pnb + p))
                        else:
                            nnb = np.logaddexp(nnb,
                                               np.logaddexp(pb, pnb) + p)
                        new[key] = (npb, nnb)
            ranked = sorted(new.items(),
                            key=lambda kv: -np.logaddexp(*kv[1]))
            beams = dict(ranked[:beam_width])
        ranked = sorted(beams.items(), key=lambda kv: -np.logaddexp(*kv[1]))
        for k, (prefix, probs) in enumerate(ranked[:top_paths]):
            out_paths[b, k, :len(prefix)] = prefix
            out_logp[b, k] = np.logaddexp(*probs)
    return jnp.asarray(out_paths), jnp.asarray(out_logp)


@op("print_variable", "util", differentiable=False)
def print_variable(x, message=""):
    jax.debug.print(message + "{x}", x=x)
    return x


# -- Barnes-Hut t-SNE kernels (reference BarnesHutTsne.h) -----------------

@op("barnes_symmetrized", "tsne", differentiable=False)
def barnes_symmetrized(row_p, col_p, val_p, n=None):
    """Symmetrize a sparse CSR affinity matrix: P = (P + P^T) / 2.

    Returns dense [n, n] (TPU: dense linear algebra beats host CSR)."""
    rows = np.asarray(row_p).astype(np.int64)
    cols = np.asarray(col_p).astype(np.int64)
    vals = np.asarray(val_p)
    n = int(n) if n is not None else len(rows) - 1
    dense = np.zeros((n, n), vals.dtype)
    for i in range(n):
        for k in range(rows[i], rows[i + 1]):
            dense[i, cols[k]] = vals[k]
    sym = (dense + dense.T) / 2.0
    return jnp.asarray(sym)


@op("barnes_edge_forces", "tsne")
def barnes_edge_forces(p_matrix, y):
    """Attractive edge forces of t-SNE: sum_j p_ij (y_i - y_j) / (1+|d|^2)."""
    diff = y[:, None, :] - y[None, :, :]            # [n, n, d]
    dist = 1.0 + jnp.sum(diff * diff, axis=-1)
    w = p_matrix / dist
    return jnp.einsum("ij,ijd->id", w, diff)


@op("barnes_gains", "tsne", differentiable=False)
def barnes_gains(gains, grad, prev_grad, min_gain=0.01):
    """t-SNE adaptive gain update (reference barnes_gains)."""
    same_sign = (grad * prev_grad) > 0
    new = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    return jnp.maximum(new, min_gain)


@op("cell_contains", "tsne", differentiable=False)
def cell_contains(corner, width, point):
    """Barnes-Hut quadtree membership test."""
    lo = corner - width / 2.0
    hi = corner + width / 2.0
    return jnp.all((point >= lo) & (point <= hi), axis=-1)
