"""Auto-registered backprop (_bp/_grad) ops via jax.vjp.

Reference: every declarable op family ships a hand-written `<op>_bp`
(`libnd4j/include/ops/declarable/headers/*.h`, ~120 ops). On TPU the
backprop rule IS `jax.vjp` of the forward — XLA differentiates and fuses
it; hand-written backward kernels would be strictly worse. These wrappers
exist for op-name parity and for graphs that invoke bp ops explicitly
(imported gradient graphs, OpValidation-style per-op tests).

Convention (matching the reference bp signature): positional args are the
forward inputs followed by the upstream gradient(s); kwargs are forwarded.
Gradients are returned for every floating-point input (zeros_like for
integer inputs, as the reference does).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import OpRegistry, OpDef
from .reference_inventory import all_reference_ops


def _make_bp(fwd_fn, name):
    def bp(*args, **kwargs):
        if len(args) < 2:
            raise ValueError(f"{name}: expected (inputs..., grad)")
        *xs, g = args
        is_diff = [hasattr(x, "dtype") and
                   jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
                   for x in xs]

        def fwd(*diff_args):
            it = iter(diff_args)
            full = [next(it) if d else x for x, d in zip(xs, is_diff)]
            return fwd_fn(*full, **kwargs)

        diff_xs = [x for x, d in zip(xs, is_diff) if d]
        if not diff_xs:
            return tuple(jnp.zeros_like(jnp.asarray(x)) for x in xs)
        out, vjp = jax.vjp(fwd, *diff_xs)
        # cotangent must match the output structure
        cot = jax.tree_util.tree_map(
            lambda o: jnp.broadcast_to(jnp.asarray(g, o.dtype), o.shape), out)
        diff_grads = iter(vjp(cot))
        grads = tuple(next(diff_grads) if d
                      else jnp.zeros_like(jnp.asarray(x))
                      for x, d in zip(xs, is_diff))
        return grads[0] if len(grads) == 1 else grads

    bp.__name__ = name
    return bp


def register_auto_bp():
    """Register `<op>_bp` / `<op>_grad` for every registered differentiable
    base op that the reference inventory lists a bp for."""
    reg = OpRegistry.get()
    for name in all_reference_ops():
        for suffix in ("_bp", "_grad"):
            if not name.endswith(suffix):
                continue
            base = name[: -len(suffix)]
            if reg.has(name) or not reg.has(base):
                continue
            base_def = reg.lookup(base)
            if not base_def.differentiable:
                continue
            reg.register(OpDef(name=name, fn=_make_bp(base_def.fn, name),
                               category="autodiff_bp", differentiable=False))
    # irregular names / bases flagged non-differentiable but with real vjps
    for bp_name, base in (("lstmLayerCellBp", "lstmLayerCell"),
                          ("dynamic_partition_bp", "dynamic_partition")):
        if not reg.has(bp_name) and reg.has(base):
            reg.register(OpDef(name=bp_name,
                               fn=_make_bp(reg.lookup(base).fn, bp_name),
                               category="autodiff_bp", differentiable=False))


register_auto_bp()
