"""Loss ops.

Reference: `libnd4j/include/ops/declarable/headers/loss.h` — 12 loss families,
each with weights broadcasting and a `reduction` mode enum:
0 = NONE, 1 = SUM, 2 = MEAN_BY_WEIGHT (sum/sumWeights), 3 = MEAN_BY_NONZERO_WEIGHT.
Grad variants (`*_loss_grad`) come free via `jax.grad`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op

NONE, SUM, MEAN_BY_WEIGHT, MEAN_BY_NONZERO = 0, 1, 2, 3


def _reduce(per_elem, weights, reduction):
    if weights is None:
        weights = jnp.ones((), per_elem.dtype)
    weighted = per_elem * weights
    if reduction == NONE:
        return weighted
    if reduction == SUM:
        return jnp.sum(weighted)
    if reduction == MEAN_BY_WEIGHT:
        total_w = jnp.sum(jnp.broadcast_to(weights, per_elem.shape))
        return jnp.sum(weighted) / jnp.maximum(total_w, 1e-12)
    # MEAN_BY_NONZERO
    nz = jnp.sum(jnp.broadcast_to(weights, per_elem.shape) != 0)
    return jnp.sum(weighted) / jnp.maximum(nz.astype(weighted.dtype), 1.0)


@op("mean_sqerr_loss", "loss")
def mean_sqerr_loss(predictions, weights=None, labels=None, reduction=MEAN_BY_WEIGHT):
    return _reduce(jnp.square(predictions - labels), weights, reduction)


@op("absolute_difference_loss", "loss")
def absolute_difference_loss(predictions, weights=None, labels=None,
                             reduction=MEAN_BY_WEIGHT):
    return _reduce(jnp.abs(predictions - labels), weights, reduction)


@op("huber_loss", "loss")
def huber_loss(predictions, weights=None, labels=None, delta=1.0,
               reduction=MEAN_BY_WEIGHT):
    err = jnp.abs(predictions - labels)
    quad = jnp.minimum(err, delta)
    per = 0.5 * quad * quad + delta * (err - quad)
    return _reduce(per, weights, reduction)


@op("log_loss", "loss")
def log_loss(predictions, weights=None, labels=None, eps=1e-7,
             reduction=MEAN_BY_WEIGHT):
    per = -(labels * jnp.log(predictions + eps)
            + (1 - labels) * jnp.log(1 - predictions + eps))
    return _reduce(per, weights, reduction)


@op("log_poisson_loss", "loss")
def log_poisson_loss(log_predictions, weights=None, labels=None, full=False,
                     reduction=MEAN_BY_WEIGHT):
    per = jnp.exp(log_predictions) - labels * log_predictions
    if full:
        per = per + labels * jnp.log(jnp.maximum(labels, 1e-12)) - labels \
            + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(labels, 1e-12))
    return _reduce(per, weights, reduction)


@op("hinge_loss", "loss")
def hinge_loss(logits, weights=None, labels=None, reduction=MEAN_BY_WEIGHT):
    signed = 2.0 * labels - 1.0
    return _reduce(jnp.maximum(0.0, 1.0 - signed * logits), weights, reduction)


@op("squared_hinge_loss", "loss")
def squared_hinge_loss(logits, weights=None, labels=None, reduction=MEAN_BY_WEIGHT):
    signed = 2.0 * labels - 1.0
    return _reduce(jnp.square(jnp.maximum(0.0, 1.0 - signed * logits)), weights,
                   reduction)


@op("cosine_distance_loss", "loss")
def cosine_distance_loss(predictions, weights=None, labels=None, axis=-1,
                         reduction=MEAN_BY_WEIGHT):
    per = 1.0 - jnp.sum(predictions * labels, axis=axis, keepdims=True)
    return _reduce(per, weights, reduction)


@op("mean_pairwssqerr_loss", "loss")
def mean_pairwssqerr_loss(predictions, weights=None, labels=None,
                          reduction=MEAN_BY_WEIGHT):
    d = predictions - labels
    n = d.shape[-1]
    sum_sq = jnp.sum(d * d, axis=-1, keepdims=True)
    sq_sum = jnp.square(jnp.sum(d, axis=-1, keepdims=True))
    per = jnp.where(n > 1, 2.0 * (n * sum_sq - sq_sum) / jnp.maximum(n * (n - 1), 1),
                    jnp.zeros_like(sum_sq))
    return _reduce(per, weights, reduction)


@op("sigm_cross_entropy_loss", "loss")
def sigm_cross_entropy_loss(logits, weights=None, labels=None,
                            label_smoothing=0.0, reduction=MEAN_BY_WEIGHT):
    if label_smoothing > 0:
        labels = labels * (1 - label_smoothing) + 0.5 * label_smoothing
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _reduce(per, weights, reduction)


@op("softmax_cross_entropy_loss", "loss")
def softmax_cross_entropy_loss(logits, weights=None, labels=None,
                               label_smoothing=0.0, reduction=MEAN_BY_WEIGHT):
    if label_smoothing > 0:
        n = labels.shape[-1]
        labels = labels * (1 - label_smoothing) + label_smoothing / n
    per = -jnp.sum(labels * jax.nn.log_softmax(logits, axis=-1), axis=-1)
    return _reduce(per, weights, reduction)


@op("softmax_cross_entropy_loss_with_logits", "loss")
def softmax_cross_entropy_loss_with_logits(logits, labels, axis=-1):
    return -jnp.sum(labels * jax.nn.log_softmax(logits, axis=axis), axis=axis)


@op("sparse_softmax_cross_entropy_loss_with_logits", "loss")
def sparse_softmax_cross_entropy_loss_with_logits(labels, logits):
    lsm = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(lsm, labels[..., None].astype(jnp.int32),
                                axis=-1)[..., 0]


@op("weighted_cross_entropy_with_logits", "loss")
def weighted_cross_entropy_with_logits(targets, logits, pos_weight):
    log_weight = 1 + (pos_weight - 1) * targets
    return (1 - targets) * logits + log_weight * (
        jnp.log1p(jnp.exp(-jnp.abs(logits))) + jnp.maximum(-logits, 0))


@op("l2_loss", "loss")
def l2_loss(x):
    return jnp.sum(x * x) / 2


@op("ctc_loss", "loss")
def ctc_loss(labels, logits, label_lengths, logit_lengths, blank_index=0):
    """CTC via optax (log-domain forward algorithm, scan-based — TPU-friendly)."""
    import optax
    B, T, C = logits.shape
    logit_pad = 1.0 - (jnp.arange(T)[None, :] < logit_lengths[:, None]).astype(logits.dtype)
    label_pad = 1.0 - (jnp.arange(labels.shape[1])[None, :] < label_lengths[:, None]).astype(logits.dtype)
    return optax.ctc_loss(logits, logit_pad, labels, label_pad,
                          blank_id=int(blank_index))
