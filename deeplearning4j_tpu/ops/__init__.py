"""Op library: named, registered pure functions over jax.Arrays.

Importing this package registers every op family (the DECLARE_OP macro
auto-registration analog, `libnd4j/include/ops/declarable/OpRegistrator.h`).
"""
from .registry import OpRegistry, exec_op, op  # noqa: F401

from . import (  # noqa: F401  (import for registration side effects)
    bitwise_ops,
    compression,
    controlflow,
    conv_ops,
    linalg_ops,
    loss_ops,
    nn_ops,
    pairwise,
    random_ops,
    recurrent,
    reduce,
    segment_ops,
    shape_ops,
    transforms,
    updater_ops,
)


def registry() -> OpRegistry:
    return OpRegistry.get()
