"""Op library: named, registered pure functions over jax.Arrays.

Importing this package registers every op family (the DECLARE_OP macro
auto-registration analog, `libnd4j/include/ops/declarable/OpRegistrator.h`).
"""
from .registry import OpRegistry, exec_op, op  # noqa: F401

from . import (  # noqa: F401  (import for registration side effects)
    bitwise_ops,
    compression,
    controlflow,
    conv_ops,
    image_ops,
    linalg_ops,
    list_ops,
    loss_ops,
    nlp_ops,
    nn_ops,
    pairwise,
    parity_extra,
    random_ops,
    recurrent,
    reduce,
    segment_ops,
    shape_ops,
    string_ops,
    transforms,
    updater_ops,
)
from . import autobp  # noqa: F401  (last: derives _bp ops from the above)


def registry() -> OpRegistry:
    return OpRegistry.get()
