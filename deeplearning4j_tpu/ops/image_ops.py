"""Image ops (reference `libnd4j/include/ops/declarable/headers/images.h`
and the image portion of parity_ops.h).

Color conversions use the standard matrices; resizes lower to
`jax.image.resize` (XLA-fused gathers/convs — no hand kernels needed on
TPU). Channel convention: trailing axis = channels, like the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op

# -- color space conversions ---------------------------------------------

_YIQ = np.array([[0.299, 0.587, 0.114],
                 [0.595716, -0.274453, -0.321263],
                 [0.211456, -0.522591, 0.311135]], np.float32)
_YUV = np.array([[0.299, 0.587, 0.114],
                 [-0.14714119, -0.28886916, 0.43601035],
                 [0.61497538, -0.51496512, -0.10001026]], np.float32)


@op("rgb_to_yiq", "images")
def rgb_to_yiq(x):
    return jnp.einsum("...c,dc->...d", x, jnp.asarray(_YIQ))


@op("yiq_to_rgb", "images")
def yiq_to_rgb(x):
    return jnp.einsum("...c,dc->...d", x, jnp.asarray(np.linalg.inv(_YIQ)))


@op("rgb_to_yuv", "images")
def rgb_to_yuv(x):
    return jnp.einsum("...c,dc->...d", x, jnp.asarray(_YUV))


@op("yuv_to_rgb", "images")
def yuv_to_rgb(x):
    return jnp.einsum("...c,dc->...d", x, jnp.asarray(np.linalg.inv(_YUV)))


@op("rgb_to_grs", "images")
def rgb_to_grs(x):
    w = jnp.asarray([0.2989, 0.5870, 0.1140], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@op("rgb_to_hsv", "images")
def rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.max(x, axis=-1)
    mn = jnp.min(x, axis=-1)
    diff = mx - mn
    safe = jnp.where(diff == 0, 1.0, diff)
    h = jnp.where(mx == r, (g - b) / safe % 6.0,
                  jnp.where(mx == g, (b - r) / safe + 2.0,
                            (r - g) / safe + 4.0))
    h = jnp.where(diff == 0, 0.0, h) / 6.0
    s = jnp.where(mx == 0, 0.0, diff / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


@op("hsv_to_rgb", "images")
def hsv_to_rgb(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


# -- resize family --------------------------------------------------------

def _resize(x, size, method):
    size = tuple(int(s) for s in size)
    if x.ndim == 4:
        shape = (x.shape[0],) + size + (x.shape[3],)
    elif x.ndim == 3:
        shape = size + (x.shape[2],)
    else:
        raise ValueError("resize expects [B,H,W,C] or [H,W,C]")
    return jax.image.resize(x, shape, method=method)


@op("resize_bilinear", "images")
def resize_bilinear(x, size=None, height=None, width=None, **_):
    return _resize(x, size or (height, width), "linear")


@op("resize_nearest_neighbor", "images")
def resize_nearest_neighbor(x, size=None, height=None, width=None, **_):
    return _resize(x, size or (height, width), "nearest")


@op("resize_bicubic", "images")
def resize_bicubic(x, size=None, height=None, width=None, **_):
    return _resize(x, size or (height, width), "cubic")


@op("resize_area", "images")
def resize_area(x, size=None, height=None, width=None, **_):
    # area = anti-aliased linear downsample (XLA has no direct area kernel)
    size = tuple(int(s) for s in (size or (height, width)))
    if x.ndim == 4:
        shape = (x.shape[0],) + size + (x.shape[3],)
    else:
        shape = size + (x.shape[2],)
    return jax.image.resize(x, shape, method="linear", antialias=True)


_METHODS = {0: "linear", 1: "cubic", 2: "nearest", 3: "linear", 4: "linear",
             "bilinear": "linear", "bicubic": "cubic", "nearest": "nearest",
             "area": "linear", "lanczos3": "lanczos3",
             "lanczos5": "lanczos5", "gaussian": "linear",
             "mitchellcubic": "cubic"}


@op("image_resize", "images", aliases=("resize_images",))
def image_resize(x, size, method="bilinear", **_):
    return _resize(x, size, _METHODS.get(method, "linear"))


@op("crop_and_resize", "images")
def crop_and_resize(image, boxes, box_indices, crop_size, method="bilinear",
                    extrapolation_value=0.0):
    """TF CropAndResize: normalized boxes [y1,x1,y2,x2] per box."""
    ch, cw = int(crop_size[0]), int(crop_size[1])
    H, W = image.shape[1], image.shape[2]
    m = _METHODS.get(method, "linear")

    def one(box, idx):
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        img = image[idx]
        ys = y1 * (H - 1) + jnp.arange(ch) / max(ch - 1, 1) * \
            (y2 - y1) * (H - 1)
        xs = x1 * (W - 1) + jnp.arange(cw) / max(cw - 1, 1) * \
            (x2 - x1) * (W - 1)
        if m == "nearest":
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, W - 1)
            return img[yi][:, xi]
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        tl = img[y0][:, x0]
        tr = img[y0][:, x1i]
        bl = img[y1i][:, x0]
        br = img[y1i][:, x1i]
        return (tl * (1 - wy) * (1 - wx) + tr * (1 - wy) * wx +
                bl * wy * (1 - wx) + br * wy * wx)

    return jax.vmap(one)(boxes, box_indices.astype(jnp.int32))


# -- photometric adjustments ----------------------------------------------

@op("adjust_contrast", "images", aliases=("adjust_contrast_v2",))
def adjust_contrast(x, factor=1.0):
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


@op("adjust_saturation", "images")
def adjust_saturation(x, factor=1.0):
    hsv = rgb_to_hsv(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], axis=-1))


@op("adjust_hue", "images")
def adjust_hue(x, delta=0.0):
    hsv = rgb_to_hsv(x)
    h = (hsv[..., 0] + delta) % 1.0
    return hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


# -- detection helpers ----------------------------------------------------

def _iou(a, b):
    y1 = jnp.maximum(a[0], b[0])
    x1 = jnp.maximum(a[1], b[1])
    y2 = jnp.minimum(a[2], b[2])
    x2 = jnp.minimum(a[3], b[3])
    inter = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)
    area_a = (a[2] - a[0]) * (a[3] - a[1])
    area_b = (b[2] - b[0]) * (b[3] - b[1])
    return inter / jnp.maximum(area_a + area_b - inter, 1e-9)


@op("non_max_suppression", "images", differentiable=False,
    aliases=("non_max_suppression_v3",))
def non_max_suppression(boxes, scores, max_output_size,
                        iou_threshold=0.5, score_threshold=-jnp.inf):
    """Greedy NMS returning selected indices (padded with -1)."""
    n = boxes.shape[0]
    max_out = int(max_output_size)
    order = jnp.argsort(-scores)

    def body(state, _):
        selected, sel_count, suppressed = state
        avail = (~suppressed) & (scores[order] > score_threshold)
        idx_in_order = jnp.argmax(avail)
        any_avail = jnp.any(avail)
        cand = order[idx_in_order]
        do = any_avail & (sel_count < max_out)
        selected = jnp.where(
            do, selected.at[jnp.clip(sel_count, 0, max_out - 1)].set(cand),
            selected)
        sel_count = sel_count + jnp.where(do, 1, 0)
        ious = jax.vmap(lambda b: _iou(boxes[cand], b))(boxes[order])
        suppressed = suppressed | (avail & (ious > iou_threshold)) | \
            (jnp.arange(n) == idx_in_order)
        return (selected, sel_count, suppressed), None

    init = (jnp.full((max_out,), -1, jnp.int32), jnp.int32(0),
            jnp.zeros((n,), bool))
    (selected, _, _), _ = jax.lax.scan(body, init, None, length=min(n, max_out))
    return selected


@op("non_max_suppression_overlaps", "images", differentiable=False)
def non_max_suppression_overlaps(overlaps, scores, max_output_size,
                                 overlap_threshold=0.5,
                                 score_threshold=-jnp.inf):
    """NMS over a precomputed pairwise overlap matrix."""
    n = overlaps.shape[0]
    max_out = int(max_output_size)
    order = jnp.argsort(-scores)

    def body(state, _):
        selected, sel_count, suppressed = state
        avail = (~suppressed) & (scores[order] > score_threshold)
        idx_in_order = jnp.argmax(avail)
        any_avail = jnp.any(avail)
        cand = order[idx_in_order]
        do = any_avail & (sel_count < max_out)
        selected = jnp.where(
            do, selected.at[jnp.clip(sel_count, 0, max_out - 1)].set(cand),
            selected)
        sel_count = sel_count + jnp.where(do, 1, 0)
        suppressed = suppressed | (avail &
                                   (overlaps[cand][order] >
                                    overlap_threshold)) | \
            (jnp.arange(n) == idx_in_order)
        return (selected, sel_count, suppressed), None

    init = (jnp.full((max_out,), -1, jnp.int32), jnp.int32(0),
            jnp.zeros((n,), bool))
    (selected, _, _), _ = jax.lax.scan(body, init, None,
                                       length=min(n, max_out))
    return selected


@op("draw_bounding_boxes", "images", differentiable=False)
def draw_bounding_boxes(images, boxes, colors=None):
    """Draw box outlines (normalized [y1,x1,y2,x2]) onto images [B,H,W,C]."""
    B, H, W, C = images.shape
    if colors is None:
        colors = jnp.ones((1, C), images.dtype)
    colors = jnp.asarray(colors)

    def draw_one(img, img_boxes):
        yy = jnp.arange(H)[:, None]
        xx = jnp.arange(W)[None, :]

        def body(im, bc):
            box, color = bc
            y1 = jnp.round(box[0] * (H - 1)).astype(jnp.int32)
            x1 = jnp.round(box[1] * (W - 1)).astype(jnp.int32)
            y2 = jnp.round(box[2] * (H - 1)).astype(jnp.int32)
            x2 = jnp.round(box[3] * (W - 1)).astype(jnp.int32)
            on_edge = (((yy == y1) | (yy == y2)) & (xx >= x1) & (xx <= x2)) \
                | (((xx == x1) | (xx == x2)) & (yy >= y1) & (yy <= y2))
            return jnp.where(on_edge[..., None], color, im), None

        n_boxes = img_boxes.shape[0]
        cols = jnp.broadcast_to(colors, (n_boxes, C)) \
            if colors.shape[0] != n_boxes else colors
        im, _ = jax.lax.scan(body, img, (img_boxes, cols))
        return im

    return jax.vmap(draw_one)(images, boxes)
