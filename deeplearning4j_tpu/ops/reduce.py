"""Reduction ops.

Reference: reduce family in `libnd4j/include/ops/declarable/headers/parity_ops.h`
(reduce_sum/mean/... at various lines) plus legacy reduce{Float,Same,Bool,Long},
indexreduce, summarystats loop families. XLA reduce + the MXU-friendly layout
replace the reference's TAD-dimension reduce kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


def _axes(dims, keep_dims=False):
    if dims is None or dims == () or dims == []:
        return None
    if isinstance(dims, int):
        return (dims,)
    return tuple(int(d) for d in dims)


def _make_reduce(name, fn, differentiable=True):
    @op(name, "reduce", differentiable=differentiable)
    def _r(x, dims=None, keep_dims=False):
        return fn(x, axis=_axes(dims), keepdims=bool(keep_dims))
    return _r


_make_reduce("reduce_sum", jnp.sum)
_make_reduce("reduce_mean", jnp.mean)
_make_reduce("reduce_max", jnp.max)
_make_reduce("reduce_min", jnp.min)
_make_reduce("reduce_prod", jnp.prod)
_make_reduce("reduce_norm1", lambda x, axis, keepdims: jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims))
_make_reduce("reduce_norm2", lambda x, axis, keepdims: jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims)))
_make_reduce("reduce_sqnorm", lambda x, axis, keepdims: jnp.sum(x * x, axis=axis, keepdims=keepdims))
_make_reduce("reduce_norm_max", lambda x, axis, keepdims: jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims))
_make_reduce("reduce_logsumexp", lambda x, axis, keepdims: jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))
_make_reduce("amax", lambda x, axis, keepdims: jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims))
_make_reduce("amin", lambda x, axis, keepdims: jnp.min(jnp.abs(x), axis=axis, keepdims=keepdims))
_make_reduce("amean", lambda x, axis, keepdims: jnp.mean(jnp.abs(x), axis=axis, keepdims=keepdims))
_make_reduce("reduce_any", lambda x, axis, keepdims: jnp.any(x, axis=axis, keepdims=keepdims), differentiable=False)
_make_reduce("reduce_all", lambda x, axis, keepdims: jnp.all(x, axis=axis, keepdims=keepdims), differentiable=False)
_make_reduce("countNonZero", lambda x, axis, keepdims: jnp.sum((x != 0), axis=axis, keepdims=keepdims), differentiable=False)
_make_reduce("countZero", lambda x, axis, keepdims: jnp.sum((x == 0), axis=axis, keepdims=keepdims), differentiable=False)


@op("reduce_stdev", "reduce")
def reduce_stdev(x, dims=None, keep_dims=False, bias_corrected=True):
    return jnp.std(x, axis=_axes(dims), keepdims=bool(keep_dims),
                   ddof=1 if bias_corrected else 0)


@op("reduce_variance", "reduce")
def reduce_variance(x, dims=None, keep_dims=False, bias_corrected=True):
    return jnp.var(x, axis=_axes(dims), keepdims=bool(keep_dims),
                   ddof=1 if bias_corrected else 0)


@op("reduce_dot", "reduce")
def reduce_dot(x, y, dims=None, keep_dims=False):
    return jnp.sum(x * y, axis=_axes(dims), keepdims=bool(keep_dims))


@op("moments", "reduce")
def moments(x, dims=None, keep_dims=False):
    axes = _axes(dims)
    return (jnp.mean(x, axis=axes, keepdims=bool(keep_dims)),
            jnp.var(x, axis=axes, keepdims=bool(keep_dims)))


@op("normalize_moments", "reduce")
def normalize_moments(count, mean_ss, var_ss, shift=0.0):
    mean = mean_ss / count + shift
    variance = var_ss / count - jnp.square(mean - shift)
    return mean, variance


@op("sufficient_statistics", "reduce")
def sufficient_statistics(x, dims=None, shift=None):
    axes = _axes(dims)
    count = jnp.asarray(
        jnp.prod(jnp.asarray([x.shape[a] for a in (axes or range(x.ndim))])),
        x.dtype)
    xs = x - shift if shift is not None else x
    return count, jnp.sum(xs, axis=axes), jnp.sum(xs * xs, axis=axes)


# -- index reductions ---------------------------------------------------
@op("argmax", "indexreduce", differentiable=False, aliases=("argamax",))
def argmax(x, dims=None, keep_dims=False):
    axis = None if dims is None else (dims if isinstance(dims, int) else dims[0])
    r = jnp.argmax(x, axis=axis)
    if keep_dims and axis is not None:
        r = jnp.expand_dims(r, axis)
    return r


@op("argmin", "indexreduce", differentiable=False, aliases=("argamin",))
def argmin(x, dims=None, keep_dims=False):
    axis = None if dims is None else (dims if isinstance(dims, int) else dims[0])
    r = jnp.argmin(x, axis=axis)
    if keep_dims and axis is not None:
        r = jnp.expand_dims(r, axis)
    return r


@op("top_k", "indexreduce", differentiable=False)
def top_k(x, k, sorted=True):
    return jax.lax.top_k(x, k)


@op("in_top_k", "indexreduce", differentiable=False)
def in_top_k(predictions, targets, k):
    _, idx = jax.lax.top_k(predictions, k)
    return jnp.any(idx == targets[:, None], axis=-1)


@op("nth_element", "indexreduce", differentiable=False)
def nth_element(x, n, reverse=False):
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]


@op("percentile", "reduce", differentiable=False)
def percentile(x, q, dims=None, interpolation="linear"):
    return jnp.percentile(x, q, axis=_axes(dims), method=interpolation)


@op("bincount", "reduce", differentiable=False)
def bincount(x, weights=None, minlength=0, maxlength=None):
    length = minlength if maxlength is None else maxlength
    length = max(int(length), 1)
    return jnp.bincount(x.ravel(), weights=None if weights is None else weights.ravel(),
                        length=length)


@op("histogram", "reduce", differentiable=False)
def histogram(x, bins):
    h, _ = jnp.histogram(x, bins=int(bins))
    return h


@op("histogram_fixed_width", "reduce", differentiable=False)
def histogram_fixed_width(x, value_range, nbins=100):
    # TF semantics: out-of-range values clamp into the edge bins
    # (jnp.histogram would drop them)
    lo, hi = float(value_range[0]), float(value_range[1])
    nbins = int(nbins)
    idx = jnp.floor((x.ravel() - lo) / (hi - lo) * nbins).astype(jnp.int32)
    idx = jnp.clip(idx, 0, nbins - 1)
    return jnp.zeros(nbins, jnp.int32).at[idx].add(1)


# -- reduce3 (pairwise distance reductions) -----------------------------
@op("cosine_similarity", "reduce3")
def cosine_similarity(x, y, dims=None, keep_dims=False):
    axes = _axes(dims)
    num = jnp.sum(x * y, axis=axes, keepdims=bool(keep_dims))
    nx = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=bool(keep_dims)))
    ny = jnp.sqrt(jnp.sum(y * y, axis=axes, keepdims=bool(keep_dims)))
    return num / jnp.maximum(nx * ny, 1e-12)


@op("cosine_distance", "reduce3")
def cosine_distance(x, y, dims=None, keep_dims=False):
    return 1.0 - cosine_similarity(x, y, dims, keep_dims)


@op("euclidean_distance", "reduce3")
def euclidean_distance(x, y, dims=None, keep_dims=False):
    return jnp.sqrt(jnp.sum((x - y) ** 2, axis=_axes(dims), keepdims=bool(keep_dims)))


@op("manhattan_distance", "reduce3")
def manhattan_distance(x, y, dims=None, keep_dims=False):
    return jnp.sum(jnp.abs(x - y), axis=_axes(dims), keepdims=bool(keep_dims))


@op("jaccard_distance", "reduce3")
def jaccard_distance(x, y, dims=None, keep_dims=False):
    axes = _axes(dims)
    mins = jnp.sum(jnp.minimum(x, y), axis=axes, keepdims=bool(keep_dims))
    maxs = jnp.sum(jnp.maximum(x, y), axis=axes, keepdims=bool(keep_dims))
    return 1.0 - mins / jnp.maximum(maxs, 1e-12)


@op("hamming_distance", "reduce3", differentiable=False)
def hamming_distance(x, y, dims=None, keep_dims=False):
    return jnp.sum((x != y), axis=_axes(dims), keepdims=bool(keep_dims))


@op("dot", "reduce3")
def dot(x, y, dims=None, keep_dims=False):
    if dims is None:
        return jnp.sum(x * y)
    return jnp.sum(x * y, axis=_axes(dims), keepdims=bool(keep_dims))


@op("matrix_band_part", "transforms")
def matrix_band_part(x, num_lower, num_upper):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if num_lower >= 0:
        keep &= (i - j) <= num_lower
    if num_upper >= 0:
        keep &= (j - i) <= num_upper
    return jnp.where(keep, x, jnp.zeros_like(x))
