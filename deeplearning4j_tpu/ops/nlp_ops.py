"""NLP training-round ops (reference `headers/nlp.h`: skipgram, cbow).

Reference: `libnd4j/include/ops/declarable/generic/nlp/` — SkipGramRound /
CbowRound apply one negative-sampling SGD round in-place on syn0/syn1neg.
TPU redesign: pure-functional batched rounds returning updated tables
(functional scatter-update; XLA fuses gather+dot+scatter). The
`nlp/sequence_vectors.py` trainer uses its own fused jit step; these ops
exist for op-level parity and for graph-recorded training.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


def _sg_round(syn0, syn1neg, target, context, neg_ids, lr):
    """One skip-gram negative-sampling update for a batch of pairs.

    target/context: [B] int ids; neg_ids: [B, K] negatives.
    Returns (new_syn0, new_syn1neg, loss)."""
    v = syn0[target]                               # [B, D]
    ids = jnp.concatenate([context[:, None], neg_ids], axis=1)  # [B, 1+K]
    labels = jnp.concatenate([jnp.ones_like(context[:, None]),
                              jnp.zeros_like(neg_ids)],
                             axis=1).astype(syn0.dtype)
    u = syn1neg[ids]                               # [B, 1+K, D]
    logits = jnp.einsum("bkd,bd->bk", u, v)
    p = jax.nn.sigmoid(logits)
    g = (labels - p) * lr                          # [B, 1+K]
    dv = jnp.einsum("bk,bkd->bd", g, u)
    du = jnp.einsum("bk,bd->bkd", g, v)
    loss = -jnp.mean(labels * jax.nn.log_sigmoid(logits) +
                     (1 - labels) * jax.nn.log_sigmoid(-logits))
    syn0 = syn0.at[target].add(dv)
    syn1neg = syn1neg.at[ids.reshape(-1)].add(
        du.reshape(-1, du.shape[-1]))
    return syn0, syn1neg, loss


@op("skipgram", "nlp", differentiable=False)
def skipgram(syn0, syn1neg, target, context, neg_ids, lr=0.025):
    """Batched SkipGramRound (reference SkipGramRound.java / nlp/sg_cb.cpp)."""
    return _sg_round(syn0, syn1neg, jnp.atleast_1d(target),
                     jnp.atleast_1d(context), jnp.atleast_2d(neg_ids),
                     jnp.asarray(lr, syn0.dtype))


@op("cbow", "nlp", differentiable=False)
def cbow(syn0, syn1neg, context_ids, context_mask, target, neg_ids,
         lr=0.025):
    """Batched CbowRound: mean of context vectors predicts the target.

    context_ids: [B, C] (padded), context_mask: [B, C] 0/1,
    target: [B], neg_ids: [B, K]."""
    target = jnp.atleast_1d(target)
    mask = context_mask.astype(syn0.dtype)
    counts = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
    ctx_vecs = syn0[context_ids] * mask[..., None]
    h = ctx_vecs.sum(axis=1) / counts              # [B, D]
    ids = jnp.concatenate([target[:, None], neg_ids], axis=1)
    labels = jnp.concatenate([jnp.ones_like(target[:, None]),
                              jnp.zeros_like(neg_ids)],
                             axis=1).astype(syn0.dtype)
    u = syn1neg[ids]
    logits = jnp.einsum("bkd,bd->bk", u, h)
    p = jax.nn.sigmoid(logits)
    g = (labels - p) * lr
    dh = jnp.einsum("bk,bkd->bd", g, u)            # grad to the mean vector
    du = jnp.einsum("bk,bd->bkd", g, h)
    loss = -jnp.mean(labels * jax.nn.log_sigmoid(logits) +
                     (1 - labels) * jax.nn.log_sigmoid(-logits))
    syn1neg = syn1neg.at[ids.reshape(-1)].add(du.reshape(-1, du.shape[-1]))
    # distribute dh across contributing context rows
    per_row = (dh[:, None, :] / counts[..., None]) * mask[..., None]
    syn0 = syn0.at[context_ids.reshape(-1)].add(
        per_row.reshape(-1, per_row.shape[-1]))
    return syn0, syn1neg, loss
