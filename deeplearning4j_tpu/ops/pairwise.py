"""Broadcastable pairwise ops.

Reference: `libnd4j/include/ops/declarable/headers/broadcastable.h` and the
legacy pairwise/broadcast loop families. XLA broadcasting subsumes the
reference's TAD-based broadcast machinery entirely.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op

op("add", "pairwise")(jnp.add)
op("subtract", "pairwise", aliases=("sub",))(jnp.subtract)
op("multiply", "pairwise", aliases=("mul",))(jnp.multiply)
op("divide", "pairwise", aliases=("div",))(jnp.divide)
op("realdiv", "pairwise")(jnp.true_divide)
op("truncatediv", "pairwise")(lambda x, y: jnp.trunc(x / y))
op("floordiv", "pairwise")(jnp.floor_divide)
op("mod", "pairwise")(jnp.mod)
op("floormod", "pairwise")(jnp.mod)
op("reversesubtract", "pairwise", aliases=("rsub",))(lambda x, y: y - x)
op("reversedivide", "pairwise", aliases=("rdiv",))(lambda x, y: y / x)
op("reversemod", "pairwise")(lambda x, y: jnp.mod(y, x))
op("maximum", "pairwise")(jnp.maximum)
op("minimum", "pairwise")(jnp.minimum)
op("Pow", "pairwise", aliases=("pow",))(jnp.power)
op("squaredsubtract", "pairwise")(lambda x, y: jnp.square(x - y))
op("cross", "pairwise")(jnp.cross)


@op("divide_no_nan", "pairwise")
def divide_no_nan(x, y):
    return jnp.where(y == 0, jnp.zeros_like(x), x / jnp.where(y == 0, 1, y))


# -- comparison (bool output) ------------------------------------------
op("equals", "pairwise", differentiable=False)(jnp.equal)
op("not_equals", "pairwise", differentiable=False)(jnp.not_equal)
op("greater", "pairwise", differentiable=False)(jnp.greater)
op("greater_equal", "pairwise", differentiable=False)(jnp.greater_equal)
op("less", "pairwise", differentiable=False)(jnp.less)
op("less_equal", "pairwise", differentiable=False)(jnp.less_equal)

# scalar comparison variants (reference *_scalar ops) — same kernels
for _n, _f in [("eq_scalar", jnp.equal), ("neq_scalar", jnp.not_equal),
               ("gt_scalar", jnp.greater), ("gte_scalar", jnp.greater_equal),
               ("lt_scalar", jnp.less), ("lte_scalar", jnp.less_equal)]:
    op(_n, "pairwise", differentiable=False)(_f)

# -- boolean ------------------------------------------------------------
op("boolean_and", "pairwise", differentiable=False)(jnp.logical_and)
op("boolean_or", "pairwise", differentiable=False)(jnp.logical_or)
op("boolean_xor", "pairwise", differentiable=False)(jnp.logical_xor)
op("boolean_not", "pairwise", differentiable=False)(jnp.logical_not)


@op("select", "pairwise")
def select(cond, x, y):
    return jnp.where(cond, x, y)


@op("Where", "pairwise", differentiable=False, aliases=("where_np",))
def where(cond, x=None, y=None):
    if x is None:
        return jnp.stack(jnp.where(cond), axis=-1)
    return jnp.where(cond, x, y)


# -- merge family (n-ary elementwise) ----------------------------------
@op("mergeadd", "pairwise", aliases=("accumulate",))
def mergeadd(*xs):
    r = xs[0]
    for x in xs[1:]:
        r = r + x
    return r


@op("mergeavg", "pairwise")
def mergeavg(*xs):
    return mergeadd(*xs) / len(xs)


@op("mergemax", "pairwise")
def mergemax(*xs):
    r = xs[0]
    for x in xs[1:]:
        r = jnp.maximum(r, x)
    return r


@op("mergemaxindex", "pairwise", differentiable=False)
def mergemaxindex(*xs):
    return jnp.argmax(jnp.stack(xs, axis=0), axis=0)
