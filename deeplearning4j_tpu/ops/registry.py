"""Op registry: name → op lookup with coverage accounting.

Reference: `libnd4j/include/ops/declarable/OpRegistrator.h:67` (hash/name
registry populated by DECLARE_OP macros) and the JVM `DynamicCustomOp` mirror.
On TPU an "op" is a pure function over jax.Arrays that XLA fuses; the registry
exists for (a) name-parity accounting against the reference's 511 declarable
ops (OpTracker analog, `libnd4j/include/helpers/OpTracker.h`), (b) the
define-then-run graph layer which records ops by name, and (c) eager dispatch
from the NDArray API.

Every op is registered with the reference op name so coverage can be
enumerated by tests.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class OpDef:
    name: str
    fn: Callable
    category: str
    differentiable: bool = True
    aliases: tuple = ()


class OpRegistry:
    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        self._ops: Dict[str, OpDef] = {}
        self._executed: set = set()  # coverage accounting

    @classmethod
    def get(cls) -> "OpRegistry":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = OpRegistry()
        return cls._instance

    def register(self, opdef: OpDef):
        for key in (opdef.name, *opdef.aliases):
            if key in self._ops:
                raise ValueError(f"op already registered: {key}")
            self._ops[key] = opdef

    def lookup(self, name: str) -> OpDef:
        try:
            return self._ops[name]
        except KeyError:
            raise KeyError(f"unknown op: {name!r}") from None

    def has(self, name: str) -> bool:
        return name in self._ops

    def names(self) -> Sequence[str]:
        return sorted({d.name for d in self._ops.values()})

    def by_category(self, category: str):
        return sorted({d.name for d in self._ops.values() if d.category == category})

    def categories(self):
        return sorted({d.name for d in self._ops.values()} and
                      {d.category for d in self._ops.values()})

    def mark_executed(self, name: str):
        self._executed.add(name)

    def coverage(self):
        """(executed, total) — OpValidation-style coverage accounting."""
        all_names = set(self.names())
        return sorted(self._executed & all_names), sorted(all_names)

    def __len__(self):
        return len({d.name for d in self._ops.values()})


def op(name: str, category: str, differentiable: bool = True,
       aliases: Sequence[str] = ()):
    """Decorator registering a pure jax-level function as a named op."""
    def deco(fn: Callable):
        OpRegistry.get().register(OpDef(name=name, fn=fn, category=category,
                                        differentiable=differentiable,
                                        aliases=tuple(aliases)))
        return fn
    return deco


def exec_op(name: str, *args, **kwargs):
    """Eager execution by name (Nd4j.exec(CustomOp) analog).

    Accepts NDArray or jax.Array inputs; returns raw jax output(s) — the
    NDArray facade wraps at its own level. Honors the executioner's
    profiling mode (OpProfiler timing / NaN-INF panic checks).
    """
    from ..ndarray.ndarray import NDArray
    from . import executioner
    reg = OpRegistry.get()
    d = reg.lookup(name)
    reg.mark_executed(d.name)
    args = [a.jax() if isinstance(a, NDArray) else a for a in args]
    kwargs = {k: (v.jax() if isinstance(v, NDArray) else v)
              for k, v in kwargs.items()}
    return executioner.wrap_execution(d.name, d.fn, args, kwargs)
