"""Op executioner config: profiling modes + OpProfiler aggregation.

Reference: `DefaultOpExecutioner.java:59` profiling hooks, `OpExecutioner
.ProfilingMode` (`OpExecutioner.java:52`: NAN_PANIC / INF_PANIC /
ANY_PANIC / OPERATIONS), and the `OpProfiler` singleton
(`linalg/profiler/OpProfiler.java:41`) aggregating per-op-class timings.

TPU scope note: inside jit, ops fuse into one XLA program — these hooks
apply to *eager* op execution (`exec_op` / NDArray methods), which is the
debugging path where the reference uses them too (panic modes force a
device sync per op by design).
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict


class ProfilingMode:
    DISABLED = "DISABLED"
    NAN_PANIC = "NAN_PANIC"
    INF_PANIC = "INF_PANIC"
    ANY_PANIC = "ANY_PANIC"
    OPERATIONS = "OPERATIONS"   # timing aggregation


class OpProfiler:
    """Per-op-name timing aggregation (reference OpProfiler.getInstance)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        self._times: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)

    @classmethod
    def get_instance(cls) -> "OpProfiler":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = OpProfiler()
        return cls._instance

    def record(self, op_name: str, seconds: float):
        with self._lock:
            self._times[op_name] += seconds
            self._counts[op_name] += 1

    def reset(self):
        with self._lock:
            self._times.clear()
            self._counts.clear()

    def stats(self):
        with self._lock:
            return sorted(
                ({"op": n, "total_seconds": self._times[n],
                  "invocations": self._counts[n],
                  "avg_us": 1e6 * self._times[n] / self._counts[n]}
                 for n in self._times),
                key=lambda d: -d["total_seconds"])

    def print_out_dashboard(self, log_fn=print):
        log_fn(f"{'op':<30} {'calls':>8} {'total ms':>10} {'avg us':>10}")
        for s in self.stats():
            log_fn(f"{s['op']:<30} {s['invocations']:>8} "
                   f"{1e3 * s['total_seconds']:>10.2f} {s['avg_us']:>10.1f}")


_mode = ProfilingMode.DISABLED


def set_profiling_mode(mode: str):
    """Reference Nd4j.getExecutioner().setProfilingMode(...)."""
    global _mode
    _mode = mode


def get_profiling_mode() -> str:
    return _mode


def check_result(op_name: str, result):
    """Panic-mode output validation (DefaultOpExecutioner NaN/Inf checks)."""
    import jax.numpy as jnp
    import numpy as np

    def _check(x):
        if not hasattr(x, "dtype") or not jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.inexact):
            return
        a = np.asarray(x)
        if _mode in (ProfilingMode.NAN_PANIC, ProfilingMode.ANY_PANIC) \
                and np.isnan(a).any():
            raise FloatingPointError(f"NaN detected in output of {op_name!r}")
        if _mode in (ProfilingMode.INF_PANIC, ProfilingMode.ANY_PANIC) \
                and np.isinf(a).any():
            raise FloatingPointError(f"Inf detected in output of {op_name!r}")

    if isinstance(result, (tuple, list)):
        for r in result:
            _check(r)
    else:
        _check(result)


def wrap_execution(op_name: str, fn, args, kwargs):
    """exec_op hook: timing + panic checks per the active mode."""
    if _mode == ProfilingMode.DISABLED:
        return fn(*args, **kwargs)
    t0 = time.perf_counter()
    result = fn(*args, **kwargs)
    if _mode == ProfilingMode.OPERATIONS:
        import jax
        jax.block_until_ready(result)
        OpProfiler.get_instance().record(op_name, time.perf_counter() - t0)
    else:
        check_result(op_name, result)
    return result
