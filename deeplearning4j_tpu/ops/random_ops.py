"""Random ops.

Reference: `libnd4j/include/ops/declarable/headers/random.h` backed by a
stateful philox RNG (`include/helpers/RandomLauncher.h`). JAX keys are
counter-based philox too, but *splittable and explicit* — the TPU-correct
design (stateful RNG breaks SPMD determinism). Every op takes `key`; the
eager facade supplies one from the global stream (factory._GlobalRng).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


@op("randomuniform", "random", differentiable=False, aliases=("random_uniform",))
def randomuniform(key, shape, minval=0.0, maxval=1.0, dtype=jnp.float32):
    if jnp.issubdtype(dtype, jnp.integer):
        return jax.random.randint(key, tuple(shape), int(minval), int(maxval), dtype)
    return jax.random.uniform(key, tuple(shape), dtype, minval, maxval)


@op("random_normal", "random", differentiable=False)
def random_normal(key, shape, mean=0.0, stddev=1.0, dtype=jnp.float32):
    return mean + stddev * jax.random.normal(key, tuple(shape), dtype)


@op("random_bernoulli", "random", differentiable=False)
def random_bernoulli(key, shape, p=0.5, dtype=jnp.float32):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(dtype)


@op("random_exponential", "random", differentiable=False)
def random_exponential(key, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(key, tuple(shape), dtype) / lam


@op("random_gamma", "random", differentiable=False)
def random_gamma(key, shape, alpha, beta=1.0, dtype=jnp.float32):
    return jax.random.gamma(key, alpha, tuple(shape), dtype) / beta


@op("random_poisson", "random", differentiable=False)
def random_poisson(key, shape, lam, dtype=jnp.int32):
    return jax.random.poisson(key, lam, tuple(shape), dtype)


@op("random_multinomial", "random", differentiable=False)
def random_multinomial(key, logits, num_samples, dtype=jnp.int32):
    # categorical's `shape` must broadcast with logits' batch dims, so
    # give each of the num_samples draws a singleton axis to fill
    return jax.random.categorical(
        key, logits[:, None, :], axis=-1,
        shape=(logits.shape[0], int(num_samples))).astype(dtype)


@op("random_shuffle", "random", differentiable=False)
def random_shuffle(key, x, axis=0):
    return jax.random.permutation(key, x, axis=axis)


@op("random_crop", "random", differentiable=False)
def random_crop(key, x, size):
    size = tuple(int(s) for s in size)
    starts = [jax.random.randint(key_i, (), 0, d - s + 1)
              for key_i, d, s in zip(jax.random.split(key, len(size)), x.shape, size)]
    return jax.lax.dynamic_slice(x, starts, size)


@op("dropout_inverted", "random", differentiable=False)
def dropout_inverted(key, x, p):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@op("get_seed", "random", differentiable=False)
def get_seed():
    from ..ndarray import factory
    return jnp.asarray(factory.get_random().get_seed())


@op("set_seed", "random", differentiable=False)
def set_seed(seed):
    from ..ndarray import factory
    factory.set_seed(int(seed))
    return jnp.asarray(int(seed))
