"""String / compat ops (reference `headers/strings.h`, `headers/compat.h`,
plus hashcode from transforms.h).

String tensors are host-side numpy object arrays (XLA has no string type —
same situation as libnd4j, where utf8 ops run on CPU regardless of
backend). All non-differentiable.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import op


def _as_str_array(x):
    return np.asarray(x, dtype=object)


@op("split_string", "strings", differentiable=False)
def split_string(x, delimiter=" "):
    """Split each string; returns (values, row_lengths)."""
    arr = _as_str_array(x).ravel()
    values = []
    lengths = []
    for s in arr:
        parts = str(s).split(delimiter) if delimiter else str(s).split()
        values.extend(parts)
        lengths.append(len(parts))
    return (np.asarray(values, object), np.asarray(lengths, np.int64))


@op("compat_string_split", "strings", differentiable=False)
def compat_string_split(x, delimiter=" "):
    """TF-compat StringSplit: returns (indices [N,2], values, dense_shape)."""
    arr = _as_str_array(x).ravel()
    indices = []
    values = []
    max_cols = 0
    for r, s in enumerate(arr):
        parts = str(s).split(delimiter) if delimiter else str(s).split()
        max_cols = max(max_cols, len(parts))
        for c, p in enumerate(parts):
            indices.append([r, c])
            values.append(p)
    return (np.asarray(indices, np.int64).reshape(-1, 2),
            np.asarray(values, object),
            np.asarray([len(arr), max_cols], np.int64))


@op("compat_sparse_to_dense", "strings", differentiable=False)
def compat_sparse_to_dense(indices, dense_shape, values, default_value=0):
    """Densify COO (indices [N, rank]) — string or numeric values."""
    vals = np.asarray(values)
    shape = tuple(int(s) for s in np.asarray(dense_shape))
    if vals.dtype == object:
        out = np.full(shape, default_value if isinstance(default_value, str)
                      else "", object)
    else:
        out = np.full(shape, default_value, vals.dtype)
    idx = np.asarray(indices).reshape(-1, len(shape))
    for i, v in zip(idx, vals.ravel()):
        out[tuple(int(j) for j in i)] = v
    return out


@op("hashcode", "transforms", differentiable=False)
def hashcode(x):
    """Java-style deterministic content hash (reference transforms.h)."""
    data = np.asarray(x).ravel()
    h = 1
    if data.dtype == object:
        for s in data:
            h = (31 * h + (hash(str(s)) & 0xFFFFFFFF)) & 0xFFFFFFFFFFFFFFFF
    else:
        for b in data.tobytes():
            h = (31 * h + b) & 0xFFFFFFFFFFFFFFFF
    if h >= 1 << 63:
        h -= 1 << 64
    # host numpy scalar: jnp would truncate int64 under the default x32 mode
    return np.int64(h)
