"""Op descriptors: machine-readable IR of the op surface.

Reference: `org/nd4j/ir` (24k generated LoC of OpNamespace/MapperNamespace
protobuf descriptors describing every op's args) consumed by the
samediff-import mapping rules and codegen. Here descriptors are derived by
introspection from the live registry — no codegen step, always in sync —
and export to JSON for external tooling.
"""
from __future__ import annotations

import dataclasses
import inspect
import json
from typing import Any, Dict, List, Optional

from .registry import OpRegistry


@dataclasses.dataclass
class ArgDescriptor:
    """One op argument (reference OpNamespace$ArgDescriptor)."""
    name: str
    arg_type: str          # INPUT_TENSOR | DOUBLE | INT64 | BOOL | STRING...
    required: bool
    default: Optional[str] = None


@dataclasses.dataclass
class OpDescriptor:
    """Reference OpNamespace$OpDescriptor."""
    name: str
    category: str
    differentiable: bool
    aliases: List[str]
    args: List[ArgDescriptor]


def _classify_default(v) -> str:
    if isinstance(v, bool):
        return "BOOL"
    if isinstance(v, int):
        return "INT64"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, (tuple, list)):
        return "INT64_ARRAY"
    return "INPUT_TENSOR"


def describe(op_name: str) -> OpDescriptor:
    reg = OpRegistry.get()
    d = reg.lookup(op_name)
    args: List[ArgDescriptor] = []
    try:
        sig = inspect.signature(d.fn)
        for p in sig.parameters.values():
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                args.append(ArgDescriptor(p.name, "INPUT_TENSOR_ARRAY",
                                          required=False))
                continue
            if p.kind == inspect.Parameter.VAR_KEYWORD:
                continue
            if p.default is inspect.Parameter.empty:
                args.append(ArgDescriptor(p.name, "INPUT_TENSOR",
                                          required=True))
            else:
                args.append(ArgDescriptor(
                    p.name, _classify_default(p.default), required=False,
                    default=repr(p.default)))
    except (TypeError, ValueError):
        pass
    return OpDescriptor(name=d.name, category=d.category,
                        differentiable=d.differentiable,
                        aliases=list(d.aliases), args=args)


def all_descriptors() -> Dict[str, OpDescriptor]:
    reg = OpRegistry.get()
    return {n: describe(n) for n in reg.names()}


def to_json(path: Optional[str] = None) -> str:
    """Export the full descriptor set (nd4j-op-def.pbtxt role)."""
    data = {n: dataclasses.asdict(d) for n, d in all_descriptors().items()}
    s = json.dumps(data, indent=1, sort_keys=True)
    if path:
        with open(path, "w") as f:
            f.write(s)
    return s
