"""Control-flow ops: cond / while_loop / scan with sub-graph bodies.

Reference: TF-style control flow executed by the session interpreter —
Enter/Exit/Switch/Merge/NextIteration + If/While sub-graph invocation
(`nd4j/.../internal/InferenceSession.java:828`, `ADRs/0020 - New Control
flow.md`, native `libnd4j/include/graph/` control-flow nodes).

TPU-native redesign: bodies are `SubGraph`s (static kwargs) and execution
lowers straight to `lax.cond`/`lax.while_loop`/`lax.scan`, which XLA
compiles as native HLO control flow — traced once, no per-iteration
dispatch. Frame/iteration bookkeeping (FrameIter) disappears entirely.
Parent variables a body closes over arrive as trailing operands
(`cap_names`) and are threaded to each sub-graph by name — they are loop
invariants, not carries.

Differentiability matches XLA semantics: `cond` and `scan` are reverse-mode
differentiable; `while_loop` is forward-mode only (use `scan` with a static
trip count for trainable loops).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import op


def _as_bool(r):
    r = jnp.asarray(r)
    return jnp.all(r) if r.ndim > 0 else r


def _caps_for(graph, cap_env):
    return [cap_env[n] for n in graph.captured]


@op("cond", "controlflow", aliases=("If",))
def cond(pred, *args, true_graph, false_graph, n_base, cap_names=()):
    """lax.cond over SubGraph branches (reference If op)."""
    base = args[:n_base]
    cap_env = dict(zip(cap_names, args[n_base:]))
    res = lax.cond(_as_bool(pred),
                   lambda ops: true_graph.call_tuple(
                       *ops, *_caps_for(true_graph, cap_env)),
                   lambda ops: false_graph.call_tuple(
                       *ops, *_caps_for(false_graph, cap_env)),
                   tuple(base))
    return res[0] if len(res) == 1 else res


@op("while_loop", "controlflow", aliases=("While",))
def while_loop(*args, cond_graph, body_graph, n_loop_vars, cap_names=()):
    """lax.while_loop over SubGraph cond/body (reference While op)."""
    init = tuple(args[:n_loop_vars])
    cap_env = dict(zip(cap_names, args[n_loop_vars:]))

    def c(carry):
        return _as_bool(cond_graph(*carry, *_caps_for(cond_graph, cap_env)))

    def b(carry):
        return body_graph.call_tuple(*carry,
                                     *_caps_for(body_graph, cap_env))

    res = lax.while_loop(c, b, init)
    return res[0] if len(res) == 1 else res


@op("scan", "controlflow")
def scan(*args, body_graph, n_carry, n_scan, cap_names=(), length=None,
         reverse=False):
    """lax.scan with a SubGraph body.

    args = (*carry_init, *xs, *captured). Body receives
    (*carry, *x_slices, *captured) and returns (*new_carry, *ys). Output =
    (*final_carry, *stacked_ys)."""
    carry_init = tuple(args[:n_carry])
    xs = tuple(args[n_carry:n_carry + n_scan])
    cap_env = dict(zip(cap_names, args[n_carry + n_scan:]))
    caps = _caps_for(body_graph, cap_env)

    def step(carry, x):
        x_slices = x if isinstance(x, tuple) else (x,)
        res = body_graph.call_tuple(*carry, *x_slices, *caps)
        return tuple(res[:n_carry]), tuple(res[n_carry:])

    final, ys = lax.scan(step, carry_init,
                         (xs if len(xs) != 1 else xs[0]) if xs else None,
                         length=length, reverse=reverse)
    res = tuple(final) + tuple(ys)
    return res[0] if len(res) == 1 else res


@op("enter", "controlflow", aliases=("Enter",))
def enter(x, frame_name=None):
    """Frame ops are identity on TPU (XLA has no frames); kept for parity
    with imported TF1 graphs."""
    return x


@op("exit", "controlflow", aliases=("Exit",))
def exit_(x, frame_name=None):
    return x


@op("next_iteration", "controlflow", aliases=("NextIteration",))
def next_iteration(x):
    return x


@op("switch", "controlflow", aliases=("Switch",))
def switch(x, pred):
    """Reference Switch: route to one of two outputs. Functionally: both
    outputs exist; consumers select (XLA computes both sides of a cond
    anyway). Returns (false_out, true_out) with the non-taken side zeroed."""
    p = _as_bool(pred)
    z = jnp.zeros_like(x)
    return jnp.where(p, z, x), jnp.where(p, x, z)


@op("merge", "controlflow", aliases=("Merge",))
def merge(a, b):
    """Reference Merge: first-available input.

    Functional analog: sum of the two inputs — correct ONLY when both are
    wired DIRECTLY to the two outputs of the same `switch` op (one side is
    exactly zero). Do not place value-mapping ops (exp, cos, softmax, …)
    between switch and merge: they turn the zeroed branch into nonzero
    garbage that corrupts the sum. The TF importer never hits this — it
    lowers Switch/Merge pairs to `jnp.where` selects on the predicate
    (modelimport/tf/mappings.py) — but direct registry users must keep the
    switch→merge wiring tight, or use `lax.cond`/the `cond` op instead."""
    return a + b
