"""Convolution / pooling ops.

Reference: `libnd4j/include/ops/declarable/headers/convo.h` (conv1d/2d/3d,
depthwise/separable/pointwise/deconv, {max,avg,pnorm}pool{2d,3d}, upsampling,
im2col/col2im) with per-vendor platform kernels
(`ops/declarable/platform/{cudnn,mkldnn}/conv2d.*`).

TPU: all of these lower to `lax.conv_general_dilated` / `lax.reduce_window`,
which XLA maps straight onto the MXU with fused layout handling — dimension
numbers make NCHW/NHWC equally native, so there is no im2col materialization
(the reference's im2col+gemm strategy is an anti-pattern on TPU).

Convention: `data_format` "NCHW" (reference default) or "NHWC" (TPU-preferred);
weights are [kH, kW, inC, outC] (HWIO) like the reference's new-style YXIO.
"""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

IntOrPair = Union[int, Sequence[int]]


def _pair(v: IntOrPair, n=2) -> Tuple[int, ...]:
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, kernel, strides, dilation, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and padding and \
            isinstance(padding[0], (list, tuple)):
        return [tuple(int(x) for x in p) for p in padding]  # per-side pairs
    p = _pair(padding, n)
    return [(x, x) for x in p]


def _dn(data_format: str, n: int):
    if n == 1:
        return ("NCW", "WIO", "NCW") if data_format == "NCW" else ("NWC", "WIO", "NWC")
    if n == 2:
        return (("NCHW", "HWIO", "NCHW") if data_format == "NCHW"
                else ("NHWC", "HWIO", "NHWC"))
    return (("NCDHW", "DHWIO", "NCDHW") if data_format == "NCDHW"
            else ("NDHWC", "DHWIO", "NDHWC"))


@op("conv2d", "conv")
def conv2d(x, weights, bias=None, strides=(1, 1), padding="SAME",
           dilation=(1, 1), data_format="NCHW", groups=1):
    """groups > 1 = grouped convolution (weights [kh, kw, inC/groups, outC]),
    lowered to XLA's native feature_group_count — no per-group slicing."""
    dn = lax.conv_dimension_numbers(x.shape, weights.shape, _dn(data_format, 2))
    out = lax.conv_general_dilated(
        x, weights, window_strides=_pair(strides),
        padding=_padding(padding, weights.shape[:2], strides, dilation, 2),
        rhs_dilation=_pair(dilation), dimension_numbers=dn,
        feature_group_count=int(groups))
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW" else bias)
    return out


@op("conv1d", "conv")
def conv1d(x, weights, bias=None, strides=1, padding="SAME", dilation=1,
           data_format="NCW"):
    dn = lax.conv_dimension_numbers(x.shape, weights.shape, _dn(data_format, 1))
    out = lax.conv_general_dilated(
        x, weights, window_strides=_pair(strides, 1),
        padding=_padding(padding, weights.shape[:1], strides, dilation, 1),
        rhs_dilation=_pair(dilation, 1), dimension_numbers=dn)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1) if data_format == "NCW" else bias)
    return out


@op("conv3dnew", "conv", aliases=("conv3d",))
def conv3d(x, weights, bias=None, strides=(1, 1, 1), padding="SAME",
           dilation=(1, 1, 1), data_format="NCDHW"):
    dn = lax.conv_dimension_numbers(x.shape, weights.shape, _dn(data_format, 3))
    out = lax.conv_general_dilated(
        x, weights, window_strides=_pair(strides, 3),
        padding=_padding(padding, weights.shape[:3], strides, dilation, 3),
        rhs_dilation=_pair(dilation, 3), dimension_numbers=dn)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1, 1) if data_format == "NCDHW" else bias)
    return out


@op("depthwise_conv2d", "conv")
def depthwise_conv2d(x, weights, bias=None, strides=(1, 1), padding="SAME",
                     dilation=(1, 1), data_format="NCHW"):
    """weights: [kH, kW, inC, depthMultiplier]."""
    kh, kw, in_c, mult = weights.shape
    w = weights.reshape(kh, kw, 1, in_c * mult)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _dn(data_format, 2))
    out = lax.conv_general_dilated(
        x, w, window_strides=_pair(strides),
        padding=_padding(padding, (kh, kw), strides, dilation, 2),
        rhs_dilation=_pair(dilation), dimension_numbers=dn,
        feature_group_count=in_c)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW" else bias)
    return out


@op("sconv2d", "conv", aliases=("separable_conv2d",))
def sconv2d(x, depth_weights, point_weights=None, bias=None, strides=(1, 1),
            padding="SAME", dilation=(1, 1), data_format="NCHW"):
    out = depthwise_conv2d(x, depth_weights, None, strides, padding, dilation,
                           data_format)
    if point_weights is not None:
        out = conv2d(out, point_weights, None, (1, 1), "SAME", (1, 1), data_format)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW" else bias)
    return out


@op("pointwise_conv2d", "conv")
def pointwise_conv2d(x, weights, bias=None, data_format="NCHW"):
    return conv2d(x, weights, bias, (1, 1), "VALID", (1, 1), data_format)


@op("deconv2d", "conv")
def deconv2d(x, weights, bias=None, strides=(1, 1), padding="SAME",
             dilation=(1, 1), data_format="NCHW"):
    """Transposed conv. weights: [kH, kW, outC, inC] per reference deconv2d."""
    dn = _dn(data_format, 2)
    # transpose_kernel=True reads HWIO as [kH, kW, outC, inC] directly
    out = lax.conv_transpose(
        x, weights, strides=_pair(strides),
        padding=(_padding(padding, weights.shape[:2], strides, dilation, 2)),
        rhs_dilation=_pair(dilation), dimension_numbers=dn,
        transpose_kernel=True)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW" else bias)
    return out


@op("deconv3d", "conv")
def deconv3d(x, weights, bias=None, strides=(1, 1, 1), padding="SAME",
             dilation=(1, 1, 1), data_format="NCDHW"):
    dn = _dn(data_format, 3)
    out = lax.conv_transpose(
        x, weights, strides=_pair(strides, 3),
        padding=(_padding(padding, weights.shape[:3], strides, dilation, 3)),
        rhs_dilation=_pair(dilation, 3), dimension_numbers=dn,
        transpose_kernel=True)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1, 1) if data_format == "NCDHW" else bias)
    return out


@op("dilation2d", "conv")
def dilation2d(x, weights, strides=(1, 1), rates=(1, 1), padding="SAME"):
    """Grayscale morphological dilation (NHWC, weights [kH,kW,C]).

    TF SAME padding with strides: out = ceil(in/s), pad_total =
    max((out-1)*s + effective_k - in, 0), pad_lo = pad_total // 2, where
    effective_k = (k-1)*rate + 1 — NOT the stride-1 total subsampled.
    """
    kh, kw, c = weights.shape
    sh, sw = strides
    ekh = (kh - 1) * rates[0] + 1
    ekw = (kw - 1) * rates[1] + 1
    H, W = x.shape[1], x.shape[2]
    if padding.upper() == "SAME":
        oh, ow = -(-H // sh), -(-W // sw)
        pth = max((oh - 1) * sh + ekh - H, 0)
        ptw = max((ow - 1) * sw + ekw - W, 0)
        pads = ((0, 0), (pth // 2, pth - pth // 2),
                (ptw // 2, ptw - ptw // 2), (0, 0))
    else:
        oh, ow = (H - ekh) // sh + 1, (W - ekw) // sw + 1
        pads = ((0, 0),) * 4
    padded = jnp.pad(x, pads, constant_values=-jnp.inf)
    out = None
    for i in range(kh):
        for j in range(kw):
            r0, c0 = i * rates[0], j * rates[1]
            sl = padded[:, r0:r0 + (oh - 1) * sh + 1:sh,
                        c0:c0 + (ow - 1) * sw + 1:sw, :] + weights[i, j]
            out = sl if out is None else jnp.maximum(out, sl)
    return out


# -- pooling ------------------------------------------------------------
def _pool(x, kernel, strides, padding, data_format, init, reduce_fn, n=2):
    k = _pair(kernel, n)
    s = _pair(strides, n)
    if data_format in ("NCHW", "NCDHW", "NCW"):
        window = (1, 1) + k
        stride = (1, 1) + s
    else:
        window = (1,) + k + (1,)
        stride = (1,) + s + (1,)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        if isinstance(padding, (list, tuple)) and padding and \
                isinstance(padding[0], (list, tuple)):
            pairs = [tuple(int(a) for a in p) for p in padding]
        else:
            pairs = [(int(p), int(p)) for p in _pair(padding, n)]
        pad = ([(0, 0), (0, 0)] + pairs
               if data_format in ("NCHW", "NCDHW", "NCW")
               else [(0, 0)] + pairs + [(0, 0)])
    return lax.reduce_window(x, init, reduce_fn, window, stride, pad)


@op("maxpool2d", "pooling")
def maxpool2d(x, kernel=(2, 2), strides=None, padding="VALID", data_format="NCHW"):
    strides = strides if strides is not None else kernel
    return _pool(x, kernel, strides, padding, data_format, -jnp.inf, lax.max)


@op("avgpool2d", "pooling")
def avgpool2d(x, kernel=(2, 2), strides=None, padding="VALID", data_format="NCHW",
              include_pad=True):
    strides = strides if strides is not None else kernel
    s = _pool(x, kernel, strides, padding, data_format, 0.0, lax.add)
    if include_pad or (isinstance(padding, str) and padding.upper() == "VALID"):
        k = _pair(kernel)
        return s / (k[0] * k[1])
    ones = jnp.ones_like(x)
    counts = _pool(ones, kernel, strides, padding, data_format, 0.0, lax.add)
    return s / counts


@op("pnormpool2d", "pooling")
def pnormpool2d(x, kernel=(2, 2), strides=None, padding="VALID", p=2,
                data_format="NCHW"):
    strides = strides if strides is not None else kernel
    s = _pool(jnp.abs(x) ** p, kernel, strides, padding, data_format, 0.0, lax.add)
    return s ** (1.0 / p)


@op("maxpool3dnew", "pooling", aliases=("maxpool3d",))
def maxpool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID",
              data_format="NCDHW"):
    strides = strides if strides is not None else kernel
    return _pool(x, kernel, strides, padding, data_format, -jnp.inf, lax.max, n=3)


@op("avgpool3dnew", "pooling", aliases=("avgpool3d",))
def avgpool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID",
              data_format="NCDHW", include_pad=True):
    strides = strides if strides is not None else kernel
    s = _pool(x, kernel, strides, padding, data_format, 0.0, lax.add, n=3)
    if include_pad or (isinstance(padding, str)
                       and padding.upper() == "VALID"):
        k = _pair(kernel, 3)
        return s / (k[0] * k[1] * k[2])
    counts = _pool(jnp.ones_like(x), kernel, strides, padding, data_format,
                   0.0, lax.add, n=3)
    return s / counts


@op("max_pool_with_argmax", "pooling", differentiable=False)
def max_pool_with_argmax(x, kernel=(2, 2), strides=None, padding="VALID",
                         data_format="NHWC"):
    """Max pool returning TF-style flat argmax indices into the NHWC input.

    Trick: pack (value, flat_index) into one ordered key — reduce_window has
    no argmax variant, so we max over value*K + index_complement and decode.
    Simpler and XLA-fusable: per-kernel-offset shifted views stacked then
    argmaxed (kernel sizes are small static ints)."""
    strides = strides if strides is not None else kernel
    kh, kw = _pair(kernel)
    sh, sw = _pair(strides)
    b, h, w, c = x.shape
    if isinstance(padding, str) and padding.upper() == "SAME":
        out_h, out_w = -(-h // sh), -(-w // sw)
        ph = max((out_h - 1) * sh + kh - h, 0)
        pw = max((out_w - 1) * sw + kw - w, 0)
    else:
        out_h = (h - kh) // sh + 1
        out_w = (w - kw) // sw + 1
        ph = pw = 0
    flat_idx = (jnp.arange(h)[:, None, None] * w * c
                + jnp.arange(w)[None, :, None] * c
                + jnp.arange(c)[None, None, :])
    flat_idx = jnp.broadcast_to(flat_idx[None], x.shape)
    xp = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)),
                 constant_values=-jnp.inf)
    ip = jnp.pad(flat_idx, ((0, 0), (0, ph), (0, pw), (0, 0)))
    vals, idxs = [], []
    for i in range(kh):
        for j in range(kw):
            vals.append(xp[:, i:i + out_h * sh:sh, j:j + out_w * sw:sw, :])
            idxs.append(ip[:, i:i + out_h * sh:sh, j:j + out_w * sw:sw, :])
    vstack = jnp.stack(vals)      # [kh*kw, B, oh, ow, C]
    istack = jnp.stack(idxs)
    win = jnp.argmax(vstack, axis=0)
    out = jnp.take_along_axis(vstack, win[None], axis=0)[0]
    arg = jnp.take_along_axis(istack, win[None], axis=0)[0]
    return out, arg.astype(jnp.int64)


@op("upsampling2d", "conv")
def upsampling2d(x, factor_h=2, factor_w=2, data_format="NCHW"):
    if data_format == "NCHW":
        return jnp.repeat(jnp.repeat(x, factor_h, axis=2), factor_w, axis=3)
    return jnp.repeat(jnp.repeat(x, factor_h, axis=1), factor_w, axis=2)


@op("upsampling3d", "conv")
def upsampling3d(x, fd=2, fh=2, fw=2, data_format="NCDHW"):
    ax = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    x = jnp.repeat(x, fd, axis=ax[0])
    x = jnp.repeat(x, fh, axis=ax[1])
    return jnp.repeat(x, fw, axis=ax[2])


@op("im2col", "conv")
def im2col(x, kh, kw, sh=1, sw=1, ph=0, pw=0, dh=1, dw=1):
    """[B,C,H,W] → [B,C,kh,kw,outH,outW]. Provided for parity/tests; conv on
    TPU never materializes this (XLA fuses im2col into the MXU matmul)."""
    b, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    out_h = (h + 2 * ph - (kh - 1) * dh - 1) // sh + 1
    out_w = (w + 2 * pw - (kw - 1) * dw - 1) // sw + 1
    cols = jnp.zeros((b, c, kh, kw, out_h, out_w), x.dtype)
    for i in range(kh):
        for j in range(kw):
            patch = x[:, :, i * dh:i * dh + out_h * sh:sh, j * dw:j * dw + out_w * sw:sw]
            cols = cols.at[:, :, i, j].set(patch)
    return cols


@op("col2im", "conv")
def col2im(cols, sh=1, sw=1, ph=0, pw=0, h=None, w=None, dh=1, dw=1):
    b, c, kh, kw, out_h, out_w = cols.shape
    img = jnp.zeros((b, c, h + 2 * ph, w + 2 * pw), cols.dtype)
    for i in range(kh):
        for j in range(kw):
            img = img.at[:, :, i * dh:i * dh + out_h * sh:sh,
                         j * dw:j * dw + out_w * sw:sw].add(cols[:, :, i, j])
    return img[:, :, ph:ph + h, pw:pw + w]


@op("extract_image_patches", "conv", differentiable=False)
def extract_image_patches(x, ksizes, strides, rates, padding="VALID"):
    """NHWC TF-style patch extraction."""
    kh, kw = ksizes
    cols = im2col(jnp.transpose(x, (0, 3, 1, 2)), kh, kw, strides[0], strides[1],
                  (kh // 2 if padding.upper() == "SAME" else 0),
                  (kw // 2 if padding.upper() == "SAME" else 0), rates[0], rates[1])
    b, c, _, _, oh, ow = cols.shape
    return jnp.transpose(cols, (0, 4, 5, 2, 3, 1)).reshape(b, oh, ow, kh * kw * c)
