"""Linear-algebra ops.

Reference: `libnd4j/include/ops/declarable/headers/blas.h` (matmul,
batched_gemm, tensormmul) + parity ops (cholesky, qr, svd, lu, solve,
triangular_solve, matrix_inverse, determinant, eig, lstsq, sqrtm) backed by
hand-written eigensolvers (`libnd4j/include/helpers/EigenValsAndVecs.h`).

TPU: matmul families hit the MXU directly; decompositions use jax.lax.linalg
(XLA custom calls). bf16 accumulation policy follows Environment.matmul_precision.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


@op("matmul", "blas", aliases=("mmul", "gemm"))
def matmul(a, b, transpose_a=False, transpose_b=False, alpha=1.0, beta=0.0, c=None):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    out = jnp.matmul(a, b)
    if alpha != 1.0:
        out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out


@op("batched_gemm", "blas")
def batched_gemm(a, b, transpose_a=False, transpose_b=False):
    return matmul(a, b, transpose_a, transpose_b)


@op("tensormmul", "blas", aliases=("tensordot",))
def tensormmul(a, b, axes_a, axes_b):
    return jnp.tensordot(a, b, axes=(tuple(axes_a), tuple(axes_b)))


op("cholesky", "linalg")(jnp.linalg.cholesky)
op("qr", "linalg")(lambda x, full_matrices=False: jnp.linalg.qr(
    x, mode="complete" if full_matrices else "reduced"))
op("svd", "linalg")(lambda x, full_matrices=False, compute_uv=True:
                    jnp.linalg.svd(x, full_matrices=full_matrices,
                                   compute_uv=compute_uv))
op("matrix_inverse", "linalg")(jnp.linalg.inv)
op("matrix_determinant", "linalg")(jnp.linalg.det)
op("log_matrix_determinant", "linalg")(
    lambda x: jnp.linalg.slogdet(x)[1])
op("logdet", "linalg")(lambda x: 2.0 * jnp.sum(
    jnp.log(jnp.diagonal(jnp.linalg.cholesky(x), axis1=-2, axis2=-1)), axis=-1))
op("eig", "linalg")(jnp.linalg.eig)
op("self_adjoint_eig", "linalg")(jnp.linalg.eigh)


@op("lu", "linalg")
def lu(x):
    lu_mat, piv, perm = lax.linalg.lu(x)
    return lu_mat, perm.astype(jnp.int32)


@op("solve", "linalg")
def solve(a, b, adjoint=False):
    if adjoint:
        a = jnp.swapaxes(a, -1, -2)
    return jnp.linalg.solve(a, b)


@op("triangular_solve", "linalg")
def triangular_solve(a, b, lower=True, adjoint=False):
    return lax.linalg.triangular_solve(a, b, left_side=True, lower=lower,
                                       transpose_a=adjoint)


@op("lstsq", "linalg", aliases=("solve_ls",))
def lstsq(a, b, l2_regularizer=0.0, fast=True):
    if l2_regularizer > 0.0:
        at = jnp.swapaxes(a, -1, -2)
        n = a.shape[-1]
        return jnp.linalg.solve(at @ a + l2_regularizer * jnp.eye(n, dtype=a.dtype),
                                at @ b)
    return jnp.linalg.lstsq(a, b)[0]


@op("sqrtm", "linalg")
def sqrtm(x):
    """Matrix square root via eigendecomposition (symmetric assumption fast
    path; general case via Denman–Beavers iteration, scan-friendly)."""
    def db_iter(carry, _):
        y, z = carry
        y_next = 0.5 * (y + jnp.linalg.inv(z))
        z_next = 0.5 * (z + jnp.linalg.inv(y))
        return (y_next, z_next), None

    (y, _), _ = lax.scan(db_iter, (x, jnp.eye(x.shape[-1], dtype=x.dtype)),
                         None, length=20)
    return y


@op("cross_batched", "linalg")
def cross_batched(a, b):
    return jnp.cross(a, b, axis=-1)


@op("knn_mindistance", "linalg", differentiable=False)
def knn_mindistance(point, lowest, highest):
    closest = jnp.clip(point, lowest, highest)
    return jnp.sqrt(jnp.sum(jnp.square(point - closest), axis=-1))


@op("einsum", "blas")
def einsum(*operands, equation):
    """General contraction (TF/ONNX Einsum import target) — MXU-native."""
    return jnp.einsum(equation, *operands)
