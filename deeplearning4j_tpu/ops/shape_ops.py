"""Shape / gather-scatter / layout ops.

Reference: `libnd4j/include/ops/declarable/headers/shape.h`, `parity_ops.h`
(gather/scatter/slice/stack families), `headers/list.h` TensorArray ops.
Scatter ops map to jax `.at[]` ops which XLA lowers to efficient dynamic
update slices; TensorArray-style list ops become `lax.scan` patterns at the
graph layer and are represented eagerly as Python lists.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import op

op("reshape", "shape")(lambda x, shape: jnp.reshape(x, tuple(int(s) for s in shape)))
op("reshapeas", "shape")(lambda x, y: jnp.reshape(x, y.shape))
op("flatten", "shape")(lambda *xs: jnp.concatenate([x.ravel() for x in xs]))
op("flatten_2d", "shape")(lambda x, axis=1: jnp.reshape(
    x, (int(np.prod(x.shape[:axis], dtype=np.int64)), -1)))
op("transpose", "shape")(lambda x, axes=None: jnp.transpose(x, axes))
op("permute", "shape")(lambda x, axes: jnp.transpose(x, axes))
op("squeeze", "shape")(lambda x, axis=None: jnp.squeeze(x, axis=axis))
op("expand_dims", "shape")(lambda x, axis: jnp.expand_dims(x, axis))
op("broadcast_to", "shape")(lambda x, shape: jnp.broadcast_to(x, tuple(shape)))
op("tile", "shape")(lambda x, reps: jnp.tile(x, reps))
op("tile_to_shape", "shape")(lambda x, shape: jnp.broadcast_to(x, tuple(shape)))
op("repeat", "shape")(lambda x, repeats, axis=None: jnp.repeat(x, repeats, axis=axis))
op("concat", "shape")(lambda *xs, axis=0: jnp.concatenate(xs, axis=axis))
op("stack", "shape", aliases=("parallel_stack",))(lambda *xs, axis=0: jnp.stack(xs, axis=axis))
op("unstack", "shape")(lambda x, axis=0: [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)])
op("split", "shape")(lambda x, num, axis=0: jnp.split(x, num, axis=axis))
# sizes are static shape metadata: keep the cumsum on host (numpy) so the
# op stays jittable with traced x
op("split_v", "shape")(lambda x, sizes, axis=0: jnp.split(x, np.cumsum(np.asarray(sizes))[:-1].tolist(), axis=axis))
op("tear", "shape")(lambda x, axis=0: [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)])
op("reverse", "shape")(lambda x, dims=None: jnp.flip(x, axis=tuple(dims) if dims is not None else None))
op("roll", "shape")(lambda x, shift, axis=None: jnp.roll(x, shift, axis=axis))
op("order", "shape", differentiable=False)(lambda x, order="c": x)  # layout is XLA's concern

op("rank", "shape", differentiable=False)(lambda x: jnp.asarray(x.ndim))
op("shape_of", "shape", differentiable=False, aliases=("shape",))(lambda x: jnp.asarray(x.shape, jnp.int64))
op("shapes_of", "shape", differentiable=False)(lambda *xs: [jnp.asarray(x.shape, jnp.int64) for x in xs])
op("size", "shape", differentiable=False)(lambda x: jnp.asarray(x.size))
op("size_at", "shape", differentiable=False)(lambda x, dim: jnp.asarray(x.shape[dim]))
op("set_shape", "shape", differentiable=False)(lambda x, shape: jnp.reshape(x, tuple(shape)))
op("evaluate_reduction_shape", "shape", differentiable=False)(
    lambda shape, dims, keep_dims=False: jnp.asarray(
        [1 if i in dims else s for i, s in enumerate(shape.tolist())] if keep_dims
        else [s for i, s in enumerate(shape.tolist()) if i not in dims], jnp.int64))


@op("broadcast_dynamic_shape", "shape", differentiable=False)
def broadcast_dynamic_shape(a, b):
    return jnp.asarray(jnp.broadcast_shapes(tuple(a.tolist()), tuple(b.tolist())),
                       jnp.int64)


op("eye", "shape", differentiable=False)(
    lambda rows, cols=None, batch_shape=None, dtype=jnp.float32:
        jnp.broadcast_to(jnp.eye(rows, cols, dtype=dtype),
                         tuple(batch_shape or ()) + (rows, cols or rows)))
op("fill", "shape", differentiable=False)(lambda shape, value, dtype=None: jnp.full(tuple(shape), value, dtype=dtype))
op("create", "shape", differentiable=False)(lambda shape, dtype=jnp.float32: jnp.zeros(tuple(shape), dtype))
op("range", "shape", differentiable=False)(lambda start, limit=None, delta=1, dtype=None: jnp.arange(start, limit, delta, dtype=dtype))
op("lin_space", "shape", differentiable=False)(lambda start, stop, num: jnp.linspace(start, stop, int(num)))
op("meshgrid", "shape")(lambda *xs, indexing="xy": jnp.meshgrid(*xs, indexing=indexing))


@op("onehot", "shape", differentiable=False)
def onehot(indices, depth, on_value=1.0, off_value=0.0, axis=-1, dtype=jnp.float32):
    oh = jax.nn.one_hot(indices, depth, axis=axis, dtype=dtype)
    return oh * (on_value - off_value) + off_value


@op("sequence_mask", "shape", differentiable=False)
def sequence_mask(lengths, maxlen=None, dtype=jnp.bool_):
    maxlen = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    return (jnp.arange(maxlen)[None, :] < lengths[..., None]).astype(dtype)


@op("reverse_sequence", "shape")
def reverse_sequence(x, seq_lengths, seq_axis=1, batch_axis=0):
    def rev_one(row, n):
        idx = jnp.arange(row.shape[seq_axis - 1 if seq_axis > batch_axis else seq_axis])
        src = jnp.where(idx < n, n - 1 - idx, idx)
        return jnp.take(row, src, axis=seq_axis - 1 if seq_axis > batch_axis else seq_axis)
    return jax.vmap(rev_one, in_axes=(batch_axis, 0), out_axes=batch_axis)(x, seq_lengths)


# -- gather / scatter ---------------------------------------------------
op("gather", "gather")(lambda x, indices, axis=0: jnp.take(x, indices, axis=axis))
op("gather_nd", "gather")(lambda x, indices: x[tuple(jnp.moveaxis(indices, -1, 0))])
op("embedding_lookup", "gather")(lambda params, ids, *a, **k: jnp.take(params, ids, axis=0))


@op("invert_permutation", "gather", differentiable=False)
def invert_permutation(p):
    return jnp.zeros_like(p).at[p].set(jnp.arange(p.shape[0], dtype=p.dtype))


def _scatter(method):
    def f(ref, indices, updates):
        return getattr(ref.at[indices], method)(updates)
    return f


op("scatter_add", "scatter")(_scatter("add"))
op("scatter_sub", "scatter")(_scatter("subtract"))
op("scatter_mul", "scatter")(_scatter("multiply"))
op("scatter_div", "scatter")(_scatter("divide"))
op("scatter_max", "scatter")(_scatter("max"))
op("scatter_min", "scatter")(_scatter("min"))
op("scatter_upd", "scatter", aliases=("scatter_update",))(_scatter("set"))


def _scatter_nd(method):
    def f(indices, updates, shape_or_ref):
        if hasattr(shape_or_ref, "shape") and shape_or_ref.ndim > 0 and not isinstance(shape_or_ref, (list, tuple)):
            ref = shape_or_ref if shape_or_ref.dtype == updates.dtype else jnp.zeros(tuple(shape_or_ref.tolist()), updates.dtype)
        else:
            ref = jnp.zeros(tuple(int(s) for s in shape_or_ref), updates.dtype)
        idx = tuple(jnp.moveaxis(indices, -1, 0))
        return getattr(ref.at[idx], method)(updates)
    return f


@op("scatter_nd", "scatter")
def scatter_nd(indices, updates, shape):
    ref = jnp.zeros(tuple(int(s) for s in (shape.tolist() if hasattr(shape, "tolist") else shape)), updates.dtype)
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


@op("scatter_nd_add", "scatter")
def scatter_nd_add(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


@op("scatter_nd_sub", "scatter")
def scatter_nd_sub(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].subtract(updates)


@op("scatter_nd_update", "scatter")
def scatter_nd_update(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].set(updates)


@op("scatter_nd_max", "scatter")
def scatter_nd_max(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].max(updates)


@op("scatter_nd_min", "scatter")
def scatter_nd_min(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].min(updates)


# -- slicing ------------------------------------------------------------
@op("slice", "shape")
def slice_op(x, begin, size):
    begin = [int(b) for b in begin]
    size = [x.shape[i] - begin[i] if int(s) == -1 else int(s) for i, s in enumerate(size)]
    return lax.slice(x, begin, [b + s for b, s in zip(begin, size)])


@op("strided_slice", "shape")
def strided_slice(x, begin, end, strides=None):
    strides = strides or [1] * len(begin)
    idx = tuple(slice(int(b), int(e), int(s)) for b, e, s in zip(begin, end, strides))
    return x[idx]


@op("dynamic_partition", "shape", differentiable=False)
def dynamic_partition(x, partitions, num_partitions):
    return [x[partitions == i] for i in range(num_partitions)]


@op("dynamic_stitch", "shape")
def dynamic_stitch(indices, data):
    n = sum(int(i.size) for i in indices)
    sample = data[0].reshape((indices[0].size,) + data[0].shape[indices[0].ndim:])
    out = jnp.zeros((n,) + sample.shape[1:], sample.dtype)
    for idx, d in zip(indices, data):
        out = out.at[idx.ravel()].set(d.reshape((-1,) + sample.shape[1:]))
    return out


# -- space/depth layout -------------------------------------------------
@op("space_to_depth", "shape")
def space_to_depth(x, block_size, data_format="NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    b, h, w, c = x.shape
    x = x.reshape(b, h // block_size, block_size, w // block_size, block_size, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, h // block_size, w // block_size,
                                                     c * block_size * block_size)
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@op("depth_to_space", "shape")
def depth_to_space(x, block_size, data_format="NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    b, h, w, c = x.shape
    oc = c // (block_size * block_size)
    x = x.reshape(b, h, w, block_size, block_size, oc)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, h * block_size, w * block_size, oc)
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@op("batch_to_space", "shape", aliases=("batch_to_space_nd",))
def batch_to_space(x, block_shape, crops):
    if isinstance(block_shape, int):
        block_shape = [block_shape] * 2
    block_shape = [int(b) for b in (block_shape.tolist() if hasattr(block_shape, "tolist") else block_shape)]
    crops = [[int(c) for c in row] for row in (crops.tolist() if hasattr(crops, "tolist") else crops)]
    b = x.shape[0]
    prod = 1
    for s in block_shape:
        prod *= s
    nb = b // prod
    spatial = list(x.shape[1:1 + len(block_shape)])
    rem = list(x.shape[1 + len(block_shape):])
    x = x.reshape(block_shape + [nb] + spatial + rem)
    perm = [len(block_shape)]
    for i in range(len(block_shape)):
        perm += [len(block_shape) + 1 + i, i]
    perm += list(range(2 * len(block_shape) + 1, x.ndim))
    x = jnp.transpose(x, perm)
    new_spatial = [spatial[i] * block_shape[i] for i in range(len(block_shape))]
    x = x.reshape([nb] + new_spatial + rem)
    idx = (slice(None),) + tuple(slice(c[0], x.shape[i + 1] - c[1]) for i, c in enumerate(crops))
    return x[idx]


@op("space_to_batch", "shape", aliases=("space_to_batch_nd",))
def space_to_batch(x, block_shape, paddings):
    if isinstance(block_shape, int):
        block_shape = [block_shape] * 2
    block_shape = [int(b) for b in (block_shape.tolist() if hasattr(block_shape, "tolist") else block_shape)]
    paddings = [[int(c) for c in row] for row in (paddings.tolist() if hasattr(paddings, "tolist") else paddings)]
    pad_width = [(0, 0)] + [tuple(p) for p in paddings] + [(0, 0)] * (x.ndim - 1 - len(paddings))
    x = jnp.pad(x, pad_width)
    b = x.shape[0]
    spatial = list(x.shape[1:1 + len(block_shape)])
    rem = list(x.shape[1 + len(block_shape):])
    shape = [b]
    for i, s in enumerate(spatial):
        shape += [s // block_shape[i], block_shape[i]]
    shape += rem
    x = x.reshape(shape)
    perm = []
    for i in range(len(block_shape)):
        perm.append(2 + 2 * i)
    perm.append(0)
    for i in range(len(block_shape)):
        perm.append(1 + 2 * i)
    perm += list(range(1 + 2 * len(block_shape), x.ndim))
    x = jnp.transpose(x, perm)
    prod = 1
    for s in block_shape:
        prod *= s
    return x.reshape([b * prod] + [spatial[i] // block_shape[i] for i in range(len(block_shape))] + rem)


@op("pad", "shape")
def pad(x, paddings, mode="CONSTANT", constant_values=0):
    paddings = [tuple(int(c) for c in row) for row in
                (paddings.tolist() if hasattr(paddings, "tolist") else paddings)]
    mode = mode.upper() if isinstance(mode, str) else {0: "CONSTANT", 1: "REFLECT", 2: "SYMMETRIC"}[mode]
    if mode == "CONSTANT":
        return jnp.pad(x, paddings, constant_values=constant_values)
    return jnp.pad(x, paddings, mode=mode.lower())


@op("mirror_pad", "shape")
def mirror_pad(x, paddings, mode="REFLECT"):
    return pad(x, paddings, mode=mode)


@op("unique", "shape", differentiable=False)
def unique(x):
    vals, idx = jnp.unique(x, return_inverse=True, size=x.size)
    return vals, idx.reshape(x.shape)


@op("unique_with_counts", "shape", differentiable=False)
def unique_with_counts(x):
    vals, idx, counts = jnp.unique(x, return_inverse=True, return_counts=True, size=x.size)
    return vals, idx.reshape(x.shape), counts


@op("listdiff", "shape", differentiable=False)
def listdiff(x, y):
    mask = ~jnp.isin(x, y)
    return x[mask], jnp.where(mask)[0]


op("diag", "shape")(lambda x: jnp.diag(x) if x.ndim <= 1 else jnp.diagflat(x))
op("diag_part", "shape")(lambda x: jnp.diagonal(x, axis1=-2, axis2=-1))
op("matrix_diag", "shape")(lambda x: jnp.apply_along_axis(jnp.diag, -1, x) if x.ndim > 1 else jnp.diag(x))
op("matrix_diag_part", "shape")(lambda x: jnp.diagonal(x, axis1=-2, axis2=-1))


@op("matrix_set_diag", "shape")
def matrix_set_diag(x, diagonal):
    n = min(x.shape[-2], x.shape[-1])
    i = jnp.arange(n)
    return x.at[..., i, i].set(diagonal[..., :n])


op("tri", "shape", differentiable=False)(lambda rows, cols=None, k=0, dtype=jnp.float32: jnp.tri(rows, cols, k, dtype=dtype))
op("triu", "shape")(lambda x, k=0: jnp.triu(x, k))
op("trace", "shape")(lambda x: jnp.trace(x, axis1=-2, axis2=-1))


@op("bitcast", "shape", differentiable=False)
def bitcast(x, dtype):
    from ..common.dtype import DataType
    return lax.bitcast_convert_type(x, DataType.from_any(dtype).jax)


@op("assign", "shape")
def assign(x, y):
    return jnp.broadcast_to(y, x.shape).astype(x.dtype)


@op("identity_n", "shape")
def identity_n(*xs):
    return list(xs)


@op("tf_strided_slice", "shape")
def tf_strided_slice(x, spec):
    """Strided slice with full TF mask semantics, pre-resolved to a static
    index spec at import time (`modelimport/tf/slicing.py`) — also the
    lowering target of SDVariable.__getitem__ (serializable, unlike a
    recorded lambda).

    spec: sequence of ("slice", b, e, s) | ("int", i) | ("newaxis",) |
    ("ellipsis",) | ("all",) entries.
    """
    idx = []
    for entry in spec:
        kind = entry[0]
        if kind == "slice":
            b, e, s = entry[1:]
            idx.append(slice(b, e, s))
        elif kind == "int":
            idx.append(int(entry[1]))
        elif kind == "newaxis":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
        else:
            idx.append(slice(None))
    return x[tuple(idx)]
