"""Recurrent ops: LSTM/GRU/SRU cells and layers.

Reference: `libnd4j/include/ops/declarable/headers/recurrent.h`
(lstmLayer/lstmLayerCell, gru/gruCell, sru/sru_bi, static/dynamic rnn).

TPU: time loops are `lax.scan` — one compiled program, weights resident in
VMEM across steps, per-step matmuls batched onto the MXU. Gate math follows
the reference (`ops/declarable/helpers/impl/lstmLayer.cpp` gate order
i,f,o,c → here standard [i,f,g,o] blocks, documented per function).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


@op("lstmLayerCell", "recurrent", aliases=("lstmCell",))
def lstm_cell(x, h_prev, c_prev, w_x, w_h, b=None, forget_bias=0.0):
    """One LSTM step. Gate blocks ordered [i, f, g(cell), o] along axis -1.

    x: [B, In]; h_prev/c_prev: [B, H]; w_x: [In, 4H]; w_h: [H, 4H]; b: [4H].
    """
    z = jnp.matmul(x, w_x) + jnp.matmul(h_prev, w_h)
    if b is not None:
        z = z + b
    h_sz = h_prev.shape[-1]
    i, f, g, o = (z[..., :h_sz], z[..., h_sz:2 * h_sz],
                  z[..., 2 * h_sz:3 * h_sz], z[..., 3 * h_sz:])
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


def _mask_tm(mask, x_tm):
    """[B, T] keep-mask -> [T, B, 1] aligned with time-major x."""
    if mask.shape[::-1] != x_tm.shape[:2]:
        raise ValueError(
            f"mask shape {mask.shape} does not match sequence [B, T] = "
            f"{x_tm.shape[:2][::-1]}")
    return jnp.swapaxes(mask, 0, 1)[..., None].astype(bool)


@op("lstmLayer", "recurrent", aliases=("lstm",))
def lstm_layer(x, w_x, w_h, b=None, h0=None, c0=None, forget_bias=0.0,
               time_major=False, return_sequence=True, mask=None):
    """Full-sequence LSTM via lax.scan.

    x: [B, T, In] (or [T, B, In] when time_major); returns (h_seq, h_T, c_T).
    mask: optional [B, T] keep-mask (Keras Masking semantics): masked steps
    carry h/c through unchanged, so the emitted output repeats the previous
    valid step's output and h_T/c_T are the last VALID step's state.
    """
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # [T, B, In]
    B = x.shape[1]
    H = w_h.shape[0]
    h0 = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c0 = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)

    if mask is None:
        def step(carry, x_t):
            h, c = carry
            h, c = lstm_cell(x_t, h, c, w_x, w_h, b, forget_bias)
            return (h, c), h

        (h_last, c_last), h_seq = lax.scan(step, (h0, c0), x)
    else:
        def step(carry, inp):
            h, c = carry
            x_t, m_t = inp
            h_new, c_new = lstm_cell(x_t, h, c, w_x, w_h, b, forget_bias)
            h_new = jnp.where(m_t, h_new, h)
            c_new = jnp.where(m_t, c_new, c)
            return (h_new, c_new), h_new

        (h_last, c_last), h_seq = lax.scan(step, (h0, c0),
                                           (x, _mask_tm(mask, x)))
    if not time_major:
        h_seq = jnp.swapaxes(h_seq, 0, 1)
    if return_sequence:
        return h_seq, h_last, c_last
    return h_last, c_last


@op("lstmLayer_bidirectional", "recurrent")
def lstm_layer_bidirectional(x, w_x_f, w_h_f, b_f, w_x_b, w_h_b, b_b,
                             mode="concat"):
    """Bidirectional LSTM, merge modes per reference Bidirectional.Mode:
    concat | add | mul | ave."""
    fwd, hf, cf = lstm_layer(x, w_x_f, w_h_f, b_f)
    bwd, hb, cb = lstm_layer(jnp.flip(x, axis=1), w_x_b, w_h_b, b_b)
    bwd = jnp.flip(bwd, axis=1)
    if mode == "concat":
        return jnp.concatenate([fwd, bwd], axis=-1), (hf, cf), (hb, cb)
    if mode == "add":
        return fwd + bwd, (hf, cf), (hb, cb)
    if mode == "mul":
        return fwd * bwd, (hf, cf), (hb, cb)
    return (fwd + bwd) / 2, (hf, cf), (hb, cb)


@op("gruCell", "recurrent")
def gru_cell(x, h_prev, w_ru, w_c, b_ru=None, b_c=None):
    """GRU step, reference gruCell gate layout: [r, u] fused then candidate.

    x: [B, In]; h_prev: [B, H]; w_ru: [In+H, 2H]; w_c: [In+H, H].
    """
    xh = jnp.concatenate([x, h_prev], axis=-1)
    ru = jnp.matmul(xh, w_ru)
    if b_ru is not None:
        ru = ru + b_ru
    H = h_prev.shape[-1]
    r = jax.nn.sigmoid(ru[..., :H])
    u = jax.nn.sigmoid(ru[..., H:])
    xrh = jnp.concatenate([x, r * h_prev], axis=-1)
    c = jnp.matmul(xrh, w_c)
    if b_c is not None:
        c = c + b_c
    c = jnp.tanh(c)
    return u * h_prev + (1.0 - u) * c


@op("gru_block_cell", "recurrent")
def gru_block_cell(x, h_prev, w_ru, w_c, b_ru=None, b_c=None):
    """gruCell with all four reference outputs (r, u, c, h) — the TF
    GRUBlockCell port layout (reference gruCell declares 4 outputs)."""
    xh = jnp.concatenate([x, h_prev], axis=-1)
    ru = jnp.matmul(xh, w_ru)
    if b_ru is not None:
        ru = ru + b_ru
    H = h_prev.shape[-1]
    r = jax.nn.sigmoid(ru[..., :H])
    u = jax.nn.sigmoid(ru[..., H:])
    xrh = jnp.concatenate([x, r * h_prev], axis=-1)
    c = jnp.matmul(xrh, w_c)
    if b_c is not None:
        c = c + b_c
    c = jnp.tanh(c)
    return r, u, c, u * h_prev + (1.0 - u) * c


@op("gru", "recurrent")
def gru(x, h0, w_ru, w_c, b_ru=None, b_c=None, time_major=False, mask=None):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    if mask is None:
        def step(h, x_t):
            h = gru_cell(x_t, h, w_ru, w_c, b_ru, b_c)
            return h, h

        h_last, h_seq = lax.scan(step, h0, x)
    else:
        def step(h, inp):
            x_t, m_t = inp
            h_new = gru_cell(x_t, h, w_ru, w_c, b_ru, b_c)
            h_new = jnp.where(m_t, h_new, h)
            return h_new, h_new

        h_last, h_seq = lax.scan(step, h0, (x, _mask_tm(mask, x)))
    if not time_major:
        h_seq = jnp.swapaxes(h_seq, 0, 1)
    return h_seq, h_last


@op("gru_onnx", "recurrent")
def gru_onnx(x, w, r, b=None, h0=None, linear_before_reset=0,
             time_major=True, mask=None):
    """GRU with the ONNX weight layout and both candidate conventions
    (reference gruCell kernel: `libnd4j/include/ops/declarable/headers/
    recurrent.h` gruCell; the ONNX importer needs linear_before_reset=1,
    which torch exports, and which the fused [x, r*h] gruCell above cannot
    express).

    x [T, B, In]; w [3H, In] gate rows (z, r, h); r [3H, H]; b [6H]
    (Wb z,r,h then Rb z,r,h). Returns (h_seq [T, B, H], h_last [B, H]).
    """
    H = r.shape[-1]
    if b is None:
        b = jnp.zeros((6 * H,), x.dtype)
    wz, wr, wh = w[:H], w[H:2 * H], w[2 * H:]
    rz, rr, rh = r[:H], r[H:2 * H], r[2 * H:]
    wbz, wbr, wbh = b[:H], b[H:2 * H], b[2 * H:3 * H]
    rbz, rbr, rbh = b[3 * H:4 * H], b[4 * H:5 * H], b[5 * H:]
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    if h0 is None:
        h0 = jnp.zeros((x.shape[1], H), x.dtype)

    def cell(h, x_t):
        z = jax.nn.sigmoid(x_t @ wz.T + h @ rz.T + wbz + rbz)
        g = jax.nn.sigmoid(x_t @ wr.T + h @ rr.T + wbr + rbr)
        if linear_before_reset:
            hh = jnp.tanh(x_t @ wh.T + g * (h @ rh.T + rbh) + wbh)
        else:
            hh = jnp.tanh(x_t @ wh.T + (g * h) @ rh.T + rbh + wbh)
        return z * h + (1.0 - z) * hh

    if mask is None:
        def step(h, x_t):
            h = cell(h, x_t)
            return h, h

        h_last, h_seq = lax.scan(step, h0, x)
    else:
        def step(h, inp):
            x_t, m_t = inp
            h_new = jnp.where(m_t, cell(h, x_t), h)
            return h_new, h_new

        h_last, h_seq = lax.scan(step, h0, (x, _mask_tm(mask, x)))
    if not time_major:
        h_seq = jnp.swapaxes(h_seq, 0, 1)
    return h_seq, h_last


@op("sruCell", "recurrent")
def sru_cell(x_t, c_prev, w, b):
    """Simple Recurrent Unit step (reference sru op family).

    w: [In, 3H] producing [x_tilde, f, r]."""
    z = jnp.matmul(x_t, w)
    H = c_prev.shape[-1]
    x_tilde, f_in, r_in = z[..., :H], z[..., H:2 * H], z[..., 2 * H:]
    f = jax.nn.sigmoid(f_in + b[..., :H])
    r = jax.nn.sigmoid(r_in + b[..., H:])
    c = f * c_prev + (1 - f) * x_tilde
    h = r * jnp.tanh(c) + (1 - r) * x_t[..., :H]
    return h, c


@op("sru", "recurrent")
def sru(x, c0, w, b, time_major=False):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(c, x_t):
        h, c = sru_cell(x_t, c, w, b)
        return c, h

    c_last, h_seq = lax.scan(step, c0, x)
    if not time_major:
        h_seq = jnp.swapaxes(h_seq, 0, 1)
    return h_seq, c_last


@op("static_rnn", "recurrent", aliases=("dynamic_rnn",))
def simple_rnn(x, w_x, w_h, b=None, h0=None, activation=jnp.tanh,
               time_major=False, mask=None):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    B = x.shape[1]
    H = w_h.shape[0]
    h0 = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    if mask is None:
        def step(h, x_t):
            z = jnp.matmul(x_t, w_x) + jnp.matmul(h, w_h)
            if b is not None:
                z = z + b
            h = activation(z)
            return h, h

        h_last, h_seq = lax.scan(step, h0, x)
    else:
        def step(h, inp):
            x_t, m_t = inp
            z = jnp.matmul(x_t, w_x) + jnp.matmul(h, w_h)
            if b is not None:
                z = z + b
            h_new = jnp.where(m_t, activation(z), h)
            return h_new, h_new

        h_last, h_seq = lax.scan(step, h0, (x, _mask_tm(mask, x)))
    if not time_major:
        h_seq = jnp.swapaxes(h_seq, 0, 1)
    return h_seq, h_last


@op("lstmBlockCell", "recurrent")
def lstm_block_cell(x, h_prev, c_prev, w, b, wci=None, wcf=None, wco=None,
                    forget_bias=1.0, clip_value=0.0):
    """TF-style LSTMBlockCell: fused weights w [In+H, 4H] ordered
    [i, c(g), f, o], optional peephole weights (reference lstmBlockCell)."""
    z = jnp.matmul(jnp.concatenate([x, h_prev], axis=-1), w) + b
    H = h_prev.shape[-1]
    i, g, f, o = (z[..., :H], z[..., H:2 * H], z[..., 2 * H:3 * H],
                  z[..., 3 * H:])
    if wci is not None:
        i = i + c_prev * wci
    if wcf is not None:
        f = f + c_prev * wcf
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    if clip_value > 0:
        c = jnp.clip(c, -clip_value, clip_value)
    if wco is not None:
        o = o + c * wco
    o = jax.nn.sigmoid(o)
    h = o * jnp.tanh(c)
    return i, c, f, o, g, jnp.tanh(c), h


@op("lstmBlock", "recurrent")
def lstm_block(x, h0, c0, w, b, wci=None, wcf=None, wco=None,
               forget_bias=1.0, clip_value=0.0, time_major=True):
    """Full-sequence TF-style block LSTM (reference lstmBlock)."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(carry, x_t):
        h, c = carry
        outs = lstm_block_cell(x_t, h, c, w, b, wci, wcf, wco,
                               forget_bias, clip_value)
        c_new, h_new = outs[1], outs[6]
        return (h_new, c_new), h_new

    (h_last, c_last), h_seq = lax.scan(step, (h0, c0), x)
    if not time_major:
        h_seq = jnp.swapaxes(h_seq, 0, 1)
    return h_seq, h_last, c_last


@op("sru_bi", "recurrent")
def sru_bi(x, w_f, b_f, w_b, b_b, c0_f=None, c0_b=None, time_major=False):
    """Bidirectional SRU (reference sru_bi): fwd + reversed bwd, concat."""
    B = x.shape[0] if not time_major else x.shape[1]
    H = w_f.shape[1] // 3
    if c0_f is None:
        c0_f = jnp.zeros((B, H), x.dtype)
    if c0_b is None:
        c0_b = jnp.zeros((B, H), x.dtype)
    # sru signature is (x, c0, w, b)
    fwd, cf = sru(x, c0_f, w_f, b_f, time_major=time_major)
    axis = 0 if time_major else 1
    bwd, cb = sru(jnp.flip(x, axis=axis), c0_b, w_b, b_b,
                  time_major=time_major)
    bwd = jnp.flip(bwd, axis=axis)
    return jnp.concatenate([fwd, bwd], axis=-1), cf, cb


@op("static_bidirectional_rnn", "recurrent",
    aliases=("dynamic_bidirectional_rnn",))
def bidirectional_rnn(x, w_x_f, w_h_f, b_f, w_x_b, w_h_b, b_b, h0_f=None,
                      h0_b=None, activation=jnp.tanh, time_major=False):
    """Bidirectional Elman RNN (reference static/dynamic_bidirectional_rnn;
    on TPU both lower to the same lax.scan — XLA unrolls nothing)."""
    fwd_seq, hf = simple_rnn(x, w_x_f, w_h_f, b_f, h0_f, activation,
                             time_major)
    axis = 0 if time_major else 1
    bwd_seq, hb = simple_rnn(jnp.flip(x, axis=axis), w_x_b, w_h_b, b_b,
                             h0_b, activation, time_major)
    bwd_seq = jnp.flip(bwd_seq, axis=axis)
    return jnp.concatenate([fwd_seq, bwd_seq], axis=-1), hf, hb
