"""Elementwise transform ops.

Reference: `libnd4j/include/ops/declarable/headers/transforms.h`, legacy
transform families in `libnd4j/include/loops/legacy_ops.h`, activation ops in
`headers/nn.h`. On TPU each of these is a single XLA HLO that fuses into
surrounding computations — the hand-written template kernels of the reference
(`loops/cpu/transform/*.hpp`) have no analog; jnp/lax *is* the kernel.

All `_bp` (backprop) variants of the reference come free via `jax.grad`, so
they are not separately registered.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

# -- basic unary math ---------------------------------------------------
op("abs", "transforms")(jnp.abs)
op("neg", "transforms")(jnp.negative)
op("exp", "transforms")(jnp.exp)
op("expm1", "transforms")(jnp.expm1)
op("log", "transforms")(jnp.log)
op("Log1p", "transforms", aliases=("log1p",))(jnp.log1p)
op("log2", "transforms")(jnp.log2)
op("sqrt", "transforms")(jnp.sqrt)
op("rsqrt", "transforms")(lax.rsqrt)
op("square", "transforms")(jnp.square)
op("cube", "transforms")(lambda x: x * x * x)
op("reciprocal", "transforms")(jnp.reciprocal)
op("sign", "transforms")(jnp.sign)
op("Floor", "transforms", aliases=("floor",))(jnp.floor)
op("ceil", "transforms")(jnp.ceil)
op("rint", "transforms")(jnp.rint)
op("round", "transforms")(jnp.round)

# -- trig ---------------------------------------------------------------
op("sin", "transforms")(jnp.sin)
op("cos", "transforms")(jnp.cos)
op("tan", "transforms")(jnp.tan)
op("asin", "transforms")(jnp.arcsin)
op("acos", "transforms")(jnp.arccos)
op("atan", "transforms")(jnp.arctan)
op("sinh", "transforms")(jnp.sinh)
op("cosh", "transforms")(jnp.cosh)
op("tanh", "transforms")(jnp.tanh)
op("asinh", "transforms")(jnp.arcsinh)
op("acosh", "transforms")(jnp.arccosh)
op("atanh", "transforms")(jnp.arctanh)
op("tf_atan2", "transforms", aliases=("atan2",))(jnp.arctan2)

# -- special ------------------------------------------------------------
op("isnan", "transforms", differentiable=False)(jnp.isnan)
op("isinf", "transforms", differentiable=False)(jnp.isinf)
op("isfinite", "transforms", differentiable=False)(jnp.isfinite)
op("erf", "transforms")(jax.scipy.special.erf)
op("erfc", "transforms")(jax.scipy.special.erfc)
op("lgamma", "transforms")(jax.scipy.special.gammaln)
op("digamma", "transforms")(jax.scipy.special.digamma)
op("polygamma", "transforms")(jax.scipy.special.polygamma)
op("zeta", "transforms")(jax.scipy.special.zeta)
op("betainc", "transforms")(jax.scipy.special.betainc)
op("igamma", "transforms")(jax.scipy.special.gammainc)
op("igammac", "transforms")(jax.scipy.special.gammaincc)


# -- activations (headers/nn.h) ----------------------------------------
op("sigmoid", "activations")(jax.nn.sigmoid)
op("relu", "activations")(lambda x, cutoff=0.0: jnp.maximum(x, cutoff))
op("relu6", "activations")(jax.nn.relu6)
op("lrelu", "activations", aliases=("leakyrelu",))(
    lambda x, alpha=0.01: jax.nn.leaky_relu(x, alpha))
op("elu", "activations")(lambda x, alpha=1.0: jax.nn.elu(x, alpha))
op("selu", "activations")(jax.nn.selu)
op("gelu", "activations")(jax.nn.gelu)
op("softplus", "activations")(jax.nn.softplus)
op("softsign", "activations")(jax.nn.soft_sign)
op("hardsigmoid", "activations")(jax.nn.hard_sigmoid)
op("hardtanh", "activations")(jax.nn.hard_tanh)
op("swish", "activations")(jax.nn.silu)
op("mish", "activations")(jax.nn.mish)
op("hardswish", "activations")(jax.nn.hard_silu)


@op("thresholdedrelu", "activations")
def thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


@op("rationaltanh", "activations")
def rationaltanh(x):
    # reference: 1.7159 * tanh(2x/3) approximated rationally
    a = 1.7159
    x23 = 0.6666667 * x
    return a * x23 / (1.0 + jnp.abs(x23))


@op("rectifiedtanh", "activations")
def rectifiedtanh(x):
    return jnp.maximum(jnp.tanh(x), 0.0)


@op("crelu", "activations")
def crelu(x):
    return jnp.concatenate([jnp.maximum(x, 0), jnp.maximum(-x, 0)], axis=-1)


@op("prelu", "activations")
def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


@op("cast", "transforms")
def cast(x, dtype):
    from ..common.dtype import DataType
    return x.astype(DataType.from_any(dtype).jax)


for _name, _dt in [("to_double", "float64"), ("to_float32", "float32"),
                   ("to_float16", "float16"), ("to_int32", "int32"),
                   ("to_int64", "int64"), ("to_uint32", "uint32"),
                   ("to_uint64", "uint64")]:
    op(_name, "transforms")(lambda x, _d=_dt: x.astype(_d))

op("identity", "transforms")(lambda x: x)
op("ones_as", "transforms")(jnp.ones_like)
op("zeros_as", "transforms")(jnp.zeros_like)
op("fill_as", "transforms")(lambda x, v: jnp.full_like(x, v))
op("stop_gradient", "transforms")(lax.stop_gradient)
op("noop", "transforms")(lambda *a: a[0] if a else None)


@op("clipbyvalue", "transforms", aliases=("clip_by_value",))
def clipbyvalue(x, clip_min, clip_max):
    return jnp.clip(x, clip_min, clip_max)


@op("clipbynorm", "transforms")
def clipbynorm(x, clip_norm, axis=None):
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=axis is not None))
    return jnp.where(n > clip_norm, x * (clip_norm / jnp.maximum(n, 1e-12)), x)


@op("clipbyavgnorm", "transforms")
def clipbyavgnorm(x, clip_norm, axis=None):
    n = jnp.sqrt(jnp.mean(x * x, axis=axis, keepdims=axis is not None))
    return jnp.where(n > clip_norm, x * (clip_norm / jnp.maximum(n, 1e-12)), x)


@op("clip_by_global_norm", "transforms")
def clip_by_global_norm(xs, clip_norm):
    leaves = jax.tree_util.tree_leaves(xs)
    g = jnp.sqrt(sum(jnp.sum(x * x) for x in leaves))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(g, 1e-12))
    return jax.tree_util.tree_map(lambda x: x * scale, xs), g


@op("standardize", "transforms")
def standardize(x, axis=-1):
    m = jnp.mean(x, axis=axis, keepdims=True)
    s = jnp.std(x, axis=axis, keepdims=True)
    return (x - m) / jnp.maximum(s, 1e-12)


@op("cumsum", "transforms")
def cumsum(x, axis=None, exclusive=False, reverse=False):
    if reverse:
        x = jnp.flip(x, axis=axis)
    r = jnp.cumsum(x, axis=axis)
    if exclusive:
        r = r - x
    if reverse:
        r = jnp.flip(r, axis=axis)
    return r


@op("cumprod", "transforms")
def cumprod(x, axis=None, exclusive=False, reverse=False):
    if reverse:
        x = jnp.flip(x, axis=axis)
    r = jnp.cumprod(x, axis=axis)
    if exclusive:
        r = r / x
    if reverse:
        r = jnp.flip(r, axis=axis)
    return r


op("is_numeric_tensor", "transforms", differentiable=False)(
    lambda x: jnp.asarray(jnp.issubdtype(x.dtype, jnp.number)))
op("is_non_decreasing", "transforms", differentiable=False)(
    lambda x: jnp.all(jnp.diff(x.ravel()) >= 0))
op("is_strictly_increasing", "transforms", differentiable=False)(
    lambda x: jnp.all(jnp.diff(x.ravel()) > 0))


@op("check_numerics", "transforms", differentiable=False)
def check_numerics(x, message=""):
    return x  # panic-mode checking happens in the executioner profiler


@op("ismax", "transforms", differentiable=False)
def ismax(x, axis=None):
    if axis is None:
        return (x == jnp.max(x)).astype(x.dtype)
    return (x == jnp.max(x, axis=axis, keepdims=True)).astype(x.dtype)


@op("zero_fraction", "transforms", differentiable=False)
def zero_fraction(x):
    return jnp.mean((x == 0).astype(jnp.float32))


@op("axpy", "transforms")
def axpy(x, y, alpha=1.0):
    return alpha * x + y


@op("choose", "transforms", differentiable=False)
def choose(x, mode, scalar):
    comps = {0: jnp.equal, 1: jnp.not_equal, 2: jnp.less, 3: jnp.less_equal,
             4: jnp.greater, 5: jnp.greater_equal}
    return x[comps[mode](x, scalar)]
