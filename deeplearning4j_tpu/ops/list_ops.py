"""TensorArray / NDArrayList ops (reference `headers/list.h`).

The reference mutates a native NDArrayList inside the graph interpreter.
Functionally on TPU a "list" is just a tuple of arrays (host-level) or a
stacked array; these ops provide the API-parity surface used by imported
TF1 graphs and the SameDiff TensorArray. All are host-structural
(differentiable through contents where applicable).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op


@op("create_list", "list", differentiable=False)
def create_list(*_, **__):
    return ()


@op("write_list", "list")
def write_list(lst, value, index):
    lst = tuple(lst)
    i = int(index)
    if i < len(lst):
        return lst[:i] + (value,) + lst[i + 1:]
    pad = (jnp.zeros_like(value),) * (i - len(lst))
    return lst + pad + (value,)


@op("read_list", "list")
def read_list(lst, index):
    return lst[int(index)]


@op("pick_list", "list")
def pick_list(lst, *indices):
    idx = [int(i) for i in (indices[0] if len(indices) == 1 and
                            hasattr(indices[0], "__iter__") else indices)]
    return jnp.stack([lst[i] for i in idx])


@op("size_list", "list", differentiable=False)
def size_list(lst):
    return jnp.asarray(len(lst), jnp.int32)


@op("scatter_list", "list")
def scatter_list(lst, indices, array):
    """Scatter array rows into list positions."""
    lst = list(lst)
    for j, i in enumerate(int(x) for x in indices):
        while len(lst) <= i:
            lst.append(jnp.zeros_like(array[0]))
        lst[i] = array[j]
    return tuple(lst)


@op("gather_list", "list")
def gather_list(lst, indices):
    return jnp.stack([lst[int(i)] for i in indices])


@op("stack_list", "list")
def stack_list(lst):
    return jnp.stack(list(lst))


@op("unstack_list", "list")
def unstack_list(array, axis=0):
    return tuple(jnp.squeeze(s, axis)
                 for s in jnp.split(array, array.shape[axis], axis))


@op("split_list", "list")
def split_list(array, sizes):
    out = []
    offset = 0
    for s in (int(x) for x in sizes):
        out.append(array[offset:offset + s])
        offset += s
    return tuple(out)


@op("clone_list", "list")
def clone_list(lst):
    return tuple(lst)


@op("delete_list", "list", differentiable=False)
def delete_list(lst):
    return ()
