"""Gradient compression ops.

Reference: `encode_threshold`/`decode_threshold`, `encode_bitmap`/`decode_bitmap`
(`libnd4j/include/ops/declarable/headers/compression.h`) powering the
Strom-style gradient sharing path (`EncodedGradientsAccumulator`).

TPU note (SURVEY.md §2.5): ICI bandwidth makes dense allreduce cheaper than
sparse threshold exchange, so distributed training here uses dense psum and
these ops exist for API/semantic parity (and for DCN-scale experimentation).
The encoding is dense-friendly: instead of the reference's variable-length
index list (dynamic shape — XLA-hostile), we return a fixed-size (mask-packed)
representation: residual update + sign mask.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op


@op("encode_threshold", "compression", differentiable=False)
def encode_threshold(updates, threshold=1e-3):
    """Returns (residual, encoded) where encoded is a dense int8 sign field
    {-1, 0, +1}: +1 where update > threshold, -1 where update < -threshold.
    The applied quantity is threshold * sign (reference semantics)."""
    pos = updates > threshold
    neg = updates < -threshold
    encoded = pos.astype(jnp.int8) - neg.astype(jnp.int8)
    residual = updates - encoded.astype(updates.dtype) * threshold
    return residual, encoded


@op("decode_threshold", "compression", differentiable=False)
def decode_threshold(encoded, threshold=1e-3, dtype=jnp.float32):
    return encoded.astype(dtype) * threshold


@op("encode_bitmap", "compression", differentiable=False)
def encode_bitmap(updates, threshold=1e-3):
    """Bitmap variant: 2-bit/element in the reference; dense sign field here."""
    return encode_threshold(updates, threshold)


@op("decode_bitmap", "compression", differentiable=False)
def decode_bitmap(encoded, threshold=1e-3, dtype=jnp.float32):
    return encoded.astype(dtype) * threshold
