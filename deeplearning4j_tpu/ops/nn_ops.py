"""Neural-network ops: softmax family, normalization, attention, dropout.

Reference: `libnd4j/include/ops/declarable/headers/nn.h` (softmax, batchnorm,
lrn, biasadd, layer_norm, xw_plus_b, relu_layer) and attention helpers
(`libnd4j/include/helpers/AttentionHelper.h`,
`generic/nn/multi_head_dot_product_attention.cpp` analogs).

TPU notes: softmax/layernorm fuse into one XLA kernel; attention has a
Pallas flash path in `deeplearning4j_tpu/kernels/flash_attention.py` that the
graph layer swaps in for long sequences.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

op("softmax", "nn")(lambda x, axis=-1: jax.nn.softmax(x, axis=axis))
op("log_softmax", "nn")(lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis))


@op("softmax_with_temperature", "nn")
def softmax_with_temperature(x, temperature=1.0, axis=-1):
    return jax.nn.softmax(x / temperature, axis=axis)


@op("biasadd", "nn")
def biasadd(x, bias, nchw=False):
    if nchw:
        return x + bias.reshape((1, -1) + (1,) * (x.ndim - 2))
    return x + bias


@op("xw_plus_b", "nn")
def xw_plus_b(x, w, b, transpose_w=False):
    if transpose_w:
        w = w.T
    return jnp.matmul(x, w) + b


@op("relu_layer", "nn")
def relu_layer(x, w, b):
    return jnp.maximum(jnp.matmul(x, w) + b, 0.0)


@op("layer_norm", "nn")
def layer_norm(x, gain, bias=None, axis=-1, eps=1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps) * gain
    return y + bias if bias is not None else y


@op("batchnorm", "nn")
def batchnorm(x, mean, variance, gamma=None, beta=None, eps=1e-5, axis=-1):
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    mean = mean.reshape(shape)
    variance = variance.reshape(shape)
    y = (x - mean) * lax.rsqrt(variance + eps)
    if gamma is not None:
        y = y * gamma.reshape(shape)
    if beta is not None:
        y = y + beta.reshape(shape)
    return y


@op("fused_batch_norm", "nn")
def fused_batch_norm(x, scale, offset, mean=None, variance=None, eps=1e-3,
                     training=True, data_format="NHWC"):
    axis = 1 if data_format == "NCHW" else -1
    reduce_axes = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    if training or mean is None:
        mean = jnp.mean(x, axis=reduce_axes)
        variance = jnp.var(x, axis=reduce_axes)
    return batchnorm(x, mean, variance, scale, offset, eps, axis), mean, variance


@op("lrn", "nn")
def lrn(x, depth_radius=5, bias=1.0, alpha=1.0, beta=0.5):
    """Local response normalization over the channel (last) axis."""
    sq = jnp.square(x)
    c = x.shape[-1]
    k = 2 * depth_radius + 1
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(depth_radius, depth_radius)])
    win = jnp.stack([padded[..., i:i + c] for i in range(k)], axis=0).sum(axis=0)
    return x / jnp.power(bias + alpha * win, beta)


@op("dropout", "nn")
def dropout(x, rate, key, training=True):
    """Inverted dropout. Explicit key (JAX-style) instead of stateful RNG."""
    if not training or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@op("alpha_dropout", "nn")
def alpha_dropout(x, rate, key, training=True):
    if not training or rate == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return a * jnp.where(mask, x, alpha_p) + b


@op("gaussian_dropout", "nn")
def gaussian_dropout(x, rate, key, training=True):
    if not training or rate == 0.0:
        return x
    stddev = jnp.sqrt(rate / (1.0 - rate))
    return x * (1.0 + stddev * jax.random.normal(key, x.shape, x.dtype))


@op("gaussian_noise", "nn")
def gaussian_noise(x, stddev, key, training=True):
    if not training:
        return x
    return x + stddev * jax.random.normal(key, x.shape, x.dtype)


# -- attention ----------------------------------------------------------
@op("dot_product_attention", "attention")
def dot_product_attention(queries, keys, values, mask=None, scale=True,
                          with_weights=False):
    """Scaled dot-product attention.

    Reference semantics: `generic/nn/dot_product_attention.cpp` — inputs
    [batch, dim, timesteps] in DL4J layout; here we use [..., T, dim]
    (TPU/MXU-friendly trailing contraction) and the layer API adapts.
    """
    d = queries.shape[-1]
    logits = jnp.einsum("...qd,...kd->...qk", queries, keys)
    if scale:
        logits = logits / jnp.sqrt(jnp.asarray(d, logits.dtype))
    if mask is not None:
        big_neg = jnp.finfo(logits.dtype).min
        logits = jnp.where(mask.astype(bool), logits, big_neg)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("...qk,...kd->...qd", weights, values)
    if with_weights:
        return out, weights
    return out


@op("multi_head_dot_product_attention", "attention")
def multi_head_dot_product_attention(queries, keys, values, wq, wk, wv, wo,
                                     mask=None, scale=True):
    """MHA with projection weights, reference
    `generic/nn/multi_head_dot_product_attention.cpp` semantics.

    queries/keys/values: [B, T, E]; wq/wk/wv: [E, H, P]; wo: [H*P, E].
    """
    q = jnp.einsum("bte,ehp->bhtp", queries, wq)
    k = jnp.einsum("bte,ehp->bhtp", keys, wk)
    v = jnp.einsum("bte,ehp->bhtp", values, wv)
    if mask is not None and mask.ndim == 2:
        mask = mask[:, None, None, :]
    attn = dot_product_attention(q, k, v, mask=mask, scale=scale)
    b, h, t, p = attn.shape
    out = attn.transpose(0, 2, 1, 3).reshape(b, t, h * p)
    return jnp.matmul(out, wo)


@op("l2_normalize", "nn")
def l2_normalize(x, axis=-1, eps=1e-12):
    return x * lax.rsqrt(jnp.maximum(jnp.sum(x * x, axis=axis, keepdims=True), eps))
