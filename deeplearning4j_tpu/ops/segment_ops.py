"""Segment reduction ops.

Reference: segment_*/unsorted_segment_* in
`libnd4j/include/ops/declarable/headers/parity_ops.h`. jax.ops.segment_*
lower to one-hot matmuls/scatters that XLA tiles efficiently; num_segments
must be static (XLA static-shape rule) — callers pass it explicitly, the
graph layer infers it from shapes at trace time.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


def _num(segment_ids, num_segments):
    if num_segments is not None:
        return int(num_segments)
    return int(jnp.max(segment_ids)) + 1  # eager-only fallback


@op("segment_sum", "segment", aliases=("unsorted_segment_sum",))
def segment_sum(data, segment_ids, num_segments=None):
    return jax.ops.segment_sum(data, segment_ids, _num(segment_ids, num_segments))


@op("segment_max", "segment", aliases=("unsorted_segment_max",))
def segment_max(data, segment_ids, num_segments=None):
    return jax.ops.segment_max(data, segment_ids, _num(segment_ids, num_segments))


@op("segment_min", "segment", aliases=("unsorted_segment_min",))
def segment_min(data, segment_ids, num_segments=None):
    return jax.ops.segment_min(data, segment_ids, _num(segment_ids, num_segments))


@op("segment_prod", "segment", aliases=("unsorted_segment_prod",))
def segment_prod(data, segment_ids, num_segments=None):
    return jax.ops.segment_prod(data, segment_ids, _num(segment_ids, num_segments))


@op("segment_mean", "segment", aliases=("unsorted_segment_mean",))
def segment_mean(data, segment_ids, num_segments=None):
    n = _num(segment_ids, num_segments)
    sums = jax.ops.segment_sum(data, segment_ids, n)
    counts = jax.ops.segment_sum(jnp.ones_like(data, jnp.float32), segment_ids, n)
    return sums / jnp.maximum(counts, 1.0)


@op("unsorted_segment_sqrt_n", "segment")
def unsorted_segment_sqrt_n(data, segment_ids, num_segments=None):
    n = _num(segment_ids, num_segments)
    sums = jax.ops.segment_sum(data, segment_ids, n)
    counts = jax.ops.segment_sum(jnp.ones_like(data, jnp.float32), segment_ids, n)
    return sums / jnp.sqrt(jnp.maximum(counts, 1.0))
