"""Bitwise ops.

Reference: `libnd4j/include/ops/declarable/headers/bitwise.h`.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import op

op("bitwise_and", "bitwise", differentiable=False)(jnp.bitwise_and)
op("bitwise_or", "bitwise", differentiable=False)(jnp.bitwise_or)
op("bitwise_xor", "bitwise", differentiable=False)(jnp.bitwise_xor)
op("toggle_bits", "bitwise", differentiable=False)(jnp.bitwise_not)
op("shift_bits", "bitwise", differentiable=False)(jnp.left_shift)
op("rshift_bits", "bitwise", differentiable=False)(jnp.right_shift)


@op("cyclic_shift_bits", "bitwise", differentiable=False)
def cyclic_shift_bits(x, shift):
    bits = x.dtype.itemsize * 8
    return (x << shift) | lax.shift_right_logical(x, bits - shift)


@op("cyclic_rshift_bits", "bitwise", differentiable=False)
def cyclic_rshift_bits(x, shift):
    bits = x.dtype.itemsize * 8
    return lax.shift_right_logical(x, shift) | (x << (bits - shift))


@op("bits_hamming_distance", "bitwise", differentiable=False)
def bits_hamming_distance(x, y):
    return jnp.sum(lax.population_count(jnp.bitwise_xor(x, y)))
