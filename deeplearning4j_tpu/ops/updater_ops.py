"""Updater (optimizer step) ops.

Reference: `libnd4j/include/ops/declarable/headers/updaters.h` — one op per
optimizer that transforms a raw gradient into an update in-place, with state
arrays carried alongside (`ops/declarable/generic/updaters/*.cpp`, JVM
`org/nd4j/linalg/learning/*Updater.java`).

TPU-native shape: pure functions `(grad, *state, hyper) -> (update, *state')`
that jit/fuse into the training step; state is part of the step's pytree.
Semantics (bias correction, epsilon placement) follow the reference so
convergence matches DL4J layer-by-layer.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op


@op("sgd_updater", "updater", aliases=("apply_sgd",))
def sgd_updater(grad, lr=0.1):
    return grad * lr


@op("momentum_updater", "updater")
def momentum_updater(grad, v, lr=0.1, momentum=0.9):
    v = momentum * v + grad
    return lr * v, v


@op("nesterovs_updater", "updater")
def nesterovs_updater(grad, v, lr=0.1, momentum=0.9):
    """Nesterov momentum, reference NesterovsUpdater semantics:
    v' = mu*v - lr*g; param step = -mu*v + (1+mu)*v'. Our convention is
    p_new = p - update, so update = mu*v - (1+mu)*v' (positive along +grad:
    first step gives (1+mu)*lr*g)."""
    v_new = momentum * v - lr * grad
    update = momentum * v - (1.0 + momentum) * v_new
    return update, v_new


@op("adam_updater", "updater")
def adam_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                 eps=1e-8, iteration=0):
    """state_u = 2nd moment (v), state_m = 1st moment (m) — reference arg order."""
    t = iteration + 1
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * jnp.square(grad)
    alpha = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    update = alpha * m / (jnp.sqrt(u) + eps)
    return update, u, m


@op("ada_max_updater", "updater")
def ada_max_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                    eps=1e-8, iteration=0):
    t = iteration + 1
    m = beta1 * state_m + (1 - beta1) * grad
    u = jnp.maximum(beta2 * state_u, jnp.abs(grad))
    update = lr / (1 - beta1 ** t) * m / (u + eps)
    return update, u, m


@op("adabelief_updater", "updater")
def adabelief_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                      eps=1e-14, iteration=0):
    t = iteration + 1
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * jnp.square(grad - m) + eps
    alpha = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    update = alpha * m / (jnp.sqrt(u) + eps)
    return update, u, m


@op("nadam_updater", "updater")
def nadam_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                  eps=1e-8, iteration=0):
    t = iteration + 1
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * jnp.square(grad)
    m_hat = m / (1 - beta1 ** t)
    u_hat = u / (1 - beta2 ** t)
    update = lr * (beta1 * m_hat + (1 - beta1) / (1 - beta1 ** t) * grad) \
        / (jnp.sqrt(u_hat) + eps)
    return update, u, m


@op("ams_grad_updater", "updater")
def ams_grad_updater(grad, state_v, state_m, state_h, lr=1e-3, beta1=0.9,
                     beta2=0.999, eps=1e-8, iteration=0):
    t = iteration + 1
    m = beta1 * state_m + (1 - beta1) * grad
    v = beta2 * state_v + (1 - beta2) * jnp.square(grad)
    h = jnp.maximum(state_h, v)
    alpha = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    update = alpha * m / (jnp.sqrt(h) + eps)
    return update, v, m, h


@op("ada_grad_updater", "updater")
def ada_grad_updater(grad, state_h, lr=1e-1, eps=1e-6):
    h = state_h + jnp.square(grad)
    update = lr * grad / (jnp.sqrt(h) + eps)
    return update, h


@op("ada_delta_updater", "updater")
def ada_delta_updater(grad, state_msg, state_msdx, rho=0.95, eps=1e-6):
    msg = rho * state_msg + (1 - rho) * jnp.square(grad)
    update = grad * jnp.sqrt(state_msdx + eps) / jnp.sqrt(msg + eps)
    msdx = rho * state_msdx + (1 - rho) * jnp.square(update)
    return update, msg, msdx


@op("rms_prop_updater", "updater")
def rms_prop_updater(grad, state_g, lr=1e-1, decay=0.95, eps=1e-8):
    g = decay * state_g + (1 - decay) * jnp.square(grad)
    update = lr * grad / (jnp.sqrt(g) + eps)
    return update, g
