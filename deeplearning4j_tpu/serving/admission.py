"""Request admission control: bounded queues, deadlines, load shedding.

Reference: the Clipper (NSDI '17) deadline-aware request frontend and the
Orca (OSDI '22) admission playbook — a serving system under overload must
choose *which* requests to serve, because serving all of them late serves
none of them. The three rules implemented here:

1. **Bounded concurrency** — at most ``max_concurrent`` requests of a
   model dispatch at once; the rest wait for a slot.
2. **Deadline propagation** — every request carries a budget
   (``timeout_s``, default ``DL4J_TPU_SERVING_TIMEOUT_S``). Waiting for a
   slot consumes it; a request whose budget expires *before* dispatch is
   shed right there — it never occupies a padded batch slot its caller
   already gave up on. The leftover budget rides along on the permit so
   the micro-batcher (`InferenceEngine.submit(timeout_s=...)`) can keep
   enforcing it after admission.
3. **Load shedding with retry-after** — once the waiting count crosses
   the high-water mark (``DL4J_TPU_SERVING_HIGH_WATER``, default 3/4 of
   ``DL4J_TPU_SERVING_QUEUE_DEPTH``), new arrivals are refused
   immediately with a ``ShedError`` carrying a retry-after hint derived
   from the queue length and an EWMA of recent service times — the HTTP
   layer turns it into ``429 Retry-After``. The queue therefore never
   grows unboundedly and admitted requests keep a bounded p99 (the
   ``serving_overload`` bench gate).

Admission is FIFO-fair (a ticket queue, not a bare condition variable):
a thread releasing its slot and immediately re-arriving queues *behind*
the waiters instead of barging past them — with a bare cv the releaser
re-acquires before the woken waiter is scheduled and starves it for
whole multiples of the service time, which is exactly the tail the p99
gate exists to catch.

Telemetry (``common.metrics``), labeled per model/version:
``dl4j_serving_requests_total{model,version,outcome}``,
``dl4j_serving_shed_total{model,reason}``,
``dl4j_serving_queue_seconds{model,version}``,
``dl4j_serving_queue_depth{model}``, ``dl4j_serving_active{model}``;
the internals that drive shedding decisions are exported too —
``dl4j_serving_ewma_service_seconds{model}`` (the EWMA behind
``Retry-After`` hints) and ``dl4j_serving_waiters{model}`` (the backlog
the hint is computed from). Each ``admit()`` runs inside a
``serving/admission`` span, so a request's admission wait — and a shed
or deadline expiry, recorded as span errors — lands in its trace.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..common.environment import environment
from ..common.locks import ordered_condition
from ..common.metrics import exponential_buckets, registry
from ..common.tracing import current_context, span


class ShedError(RuntimeError):
    """Refused at admission (queue past high-water / controller closed):
    back off ``retry_after_s`` seconds and retry."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceededError(TimeoutError):
    """The request's deadline budget expired before dispatch."""


class _Permit:
    """One admitted dispatch slot; a context manager so the slot is
    released (and the service-time EWMA updated) however dispatch ends."""

    __slots__ = ("_ctrl", "version", "_deadline", "_t_dispatch", "_done")

    def __init__(self, ctrl: "AdmissionController", version: str,
                 deadline: Optional[float]):
        self._ctrl = ctrl
        self.version = version
        self._deadline = deadline
        self._t_dispatch = time.monotonic()
        self._done = False

    def remaining_s(self) -> Optional[float]:
        """Budget left for the dispatch itself (deadline propagation into
        ``InferenceEngine.submit(timeout_s=...)``); None = no deadline."""
        if self._deadline is None:
            return None
        return max(self._deadline - time.monotonic(), 0.0)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._done:
            return False
        self._done = True
        outcome = "ok" if exc_type is None else (
            "deadline" if issubclass(exc_type, TimeoutError) else "error")
        self._ctrl._release(self, time.monotonic() - self._t_dispatch,
                            outcome)
        return False


class AdmissionController:
    """Admission gate for one served model (all versions share it — the
    capacity being protected is the device, not the executable)."""

    def __init__(self, model: str, *,
                 max_concurrent: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 high_water: Optional[int] = None,
                 default_timeout_s: Optional[float] = "env"):
        env = environment()
        self.model = str(model)
        self.max_concurrent = int(max_concurrent if max_concurrent
                                  is not None else env.serving_max_concurrent())
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else env.serving_queue_depth())
        self.high_water = int(high_water if high_water is not None
                              else env.serving_high_water())
        self.default_timeout_s = (env.serving_default_timeout_s()
                                  if default_timeout_s == "env"
                                  else default_timeout_s)
        self._cv = ordered_condition("admission")
        self._active = 0
        self._queue: list = []  # FIFO waiter tickets (bounded: high_water)
        self._closed = False
        # EWMA of dispatch seconds, seeding the retry-after estimator
        # before the first completion
        self._ewma_service_s = 0.05
        reg = registry()
        self._m_requests = reg.counter(
            "dl4j_serving_requests_total",
            "Serving requests by admission/dispatch outcome",
            labels=("model", "version", "outcome"))
        self._m_shed = reg.counter(
            "dl4j_serving_shed_total",
            "Requests refused at admission, by reason",
            labels=("model", "reason"))
        self._m_queue_lat = reg.histogram(
            "dl4j_serving_queue_seconds",
            "Wait between arrival and dispatch slot for admitted requests",
            labels=("model", "version"),
            buckets=exponential_buckets(1e-4, 2.0, 20))
        self._m_depth = reg.gauge(
            "dl4j_serving_queue_depth",
            "Requests waiting for a dispatch slot",
            labels=("model",)).labels(model=self.model)
        self._m_active = reg.gauge(
            "dl4j_serving_active",
            "Requests currently holding a dispatch slot",
            labels=("model",)).labels(model=self.model)
        self._m_ewma = reg.gauge(
            "dl4j_serving_ewma_service_seconds",
            "EWMA of per-request dispatch service time (drives the "
            "Retry-After hint on shed responses)",
            labels=("model",)).labels(model=self.model)
        self._m_ewma.set(self._ewma_service_s)
        self._m_waiters = reg.gauge(
            "dl4j_serving_waiters",
            "Backlog behind the retry-after estimate: requests waiting "
            "for or holding a dispatch slot",
            labels=("model",)).labels(model=self.model)

    # -- introspection ----------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def retry_after_hint(self) -> float:
        """How long a shed client should back off: the time the current
        backlog needs to clear at the recent service rate, floored so
        clients never hot-loop. Also the ``Retry-After`` source for
        breaker-open 503s (merged with the probe window)."""
        with self._cv:
            backlog = len(self._queue) + self._active
        est = backlog * self._ewma_service_s / max(self.max_concurrent, 1)
        return min(max(est, 0.05), 30.0)

    def ewma_service_s(self) -> float:
        """The service-time EWMA behind the retry-after estimate."""
        with self._cv:
            return self._ewma_service_s

    # -- lifecycle --------------------------------------------------------
    def close(self):
        """Stop admitting (graceful drain): new arrivals and current
        waiters shed with a draining message; in-flight dispatches finish
        normally."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        return self

    def reopen(self):
        with self._cv:
            self._closed = False
        return self

    # -- admission --------------------------------------------------------
    def _shed(self, reason: str, version: str, message: str,
              retry_after: Optional[float] = None) -> ShedError:
        self._m_shed.labels(model=self.model, reason=reason).inc()
        self._m_requests.labels(model=self.model, version=version,
                                outcome="shed").inc()
        return ShedError(message, retry_after if retry_after is not None
                         else self.retry_after_hint())

    def admit(self, timeout_s: Optional[float] = "default",
              version: str = "") -> _Permit:
        """Block until a dispatch slot frees up (within the request's
        deadline budget) and return the permit. Raises ``ShedError`` when
        the queue is past high-water / full / draining, and
        ``DeadlineExceededError`` when the budget expires while waiting —
        in both cases *before* any model work happens. The wait runs in a
        ``serving/admission`` span of the caller's trace; shed/expired
        admissions exit it with error status."""
        if current_context() is not None:
            with span("serving/admission", model=self.model):
                return self._admit(timeout_s, version)
        return self._admit(timeout_s, version)

    def _admit(self, timeout_s: Optional[float] = "default",
               version: str = "") -> _Permit:
        budget = (self.default_timeout_s if timeout_s == "default"
                  else timeout_s)
        deadline = (time.monotonic() + budget
                    if budget is not None and budget > 0 else None)
        t0 = time.monotonic()
        version = str(version)
        ticket = object()
        with self._cv:
            if self._closed:
                raise self._shed(
                    "draining", version,
                    f"model '{self.model}' is draining", retry_after=1.0)
            threshold = min(self.high_water, self.queue_depth)
            if self._active >= self.max_concurrent \
                    and len(self._queue) >= threshold:
                raise self._shed(
                    "queue_full", version,
                    f"model '{self.model}' queue past high-water "
                    f"({len(self._queue)} waiting >= {threshold}); "
                    "retry later")
            self._queue.append(ticket)
            self._m_depth.set(len(self._queue))
            self._m_waiters.set(len(self._queue) + self._active)
            try:
                # FIFO: dispatch only at the queue head with a free slot
                while (self._active >= self.max_concurrent
                       or self._queue[0] is not ticket):
                    if self._closed:
                        raise self._shed(
                            "draining", version,
                            f"model '{self.model}' is draining",
                            retry_after=1.0)
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            self._m_shed.labels(model=self.model,
                                                reason="deadline").inc()
                            self._m_requests.labels(
                                model=self.model, version=version,
                                outcome="deadline").inc()
                            raise DeadlineExceededError(
                                f"deadline budget ({budget}s) expired "
                                f"before dispatch for model "
                                f"'{self.model}'")
                        self._cv.wait(remaining)
                    else:
                        self._cv.wait()
            finally:
                self._queue.remove(ticket)
                self._m_depth.set(len(self._queue))
                self._m_waiters.set(len(self._queue) + self._active)
                self._cv.notify_all()  # the head may have changed
            self._active += 1
            self._m_active.set(self._active)
            self._m_waiters.set(len(self._queue) + self._active)
        ctx = current_context()
        self._m_queue_lat.labels(model=self.model, version=version).observe(
            time.monotonic() - t0, exemplar=ctx.trace_id if ctx else None)
        return _Permit(self, version, deadline)

    def _release(self, permit: _Permit, service_s: float, outcome: str):
        self._m_requests.labels(model=self.model, version=permit.version,
                                outcome=outcome).inc()
        with self._cv:
            if outcome == "ok":
                self._ewma_service_s = (0.8 * self._ewma_service_s
                                        + 0.2 * service_s)
                self._m_ewma.set(self._ewma_service_s)
            self._active -= 1
            self._m_active.set(self._active)
            self._m_waiters.set(len(self._queue) + self._active)
            self._cv.notify_all()

    # -- convenience ------------------------------------------------------
    def run(self, fn: Callable, timeout_s: Optional[float] = "default",
            version: str = ""):
        """``admit()`` + call ``fn()`` under the permit."""
        with self.admit(timeout_s, version=version):
            return fn()
