"""Per-model SLOs: deadline-hit-rate objectives with multi-window burn
rates.

Reference: the multi-window, multi-burn-rate alerting policy of the SRE
workbook (ch. 5) and the Dapper/Canopy practice of judging a serving
fleet by its *objective*, not its mean. The serving stack records one
observation per completed request — did it finish OK, within its
deadline (and optional latency objective)? — and this module answers two
questions the raw counters cannot:

1. **How fast is the error budget burning?** ``burn_rate(window)`` =
   observed error rate / allowed error rate (``1 - objective``) over a
   sliding window. A burn rate of 1.0 spends the budget exactly on
   schedule; 14.4 exhausts a 30-day budget in 2 days.
2. **Should this replica stop taking traffic?** ``healthy()`` is False
   only when EVERY configured window burns past its threshold *and* the
   short window holds at least ``min_samples`` observations — the
   standard fast-burn page condition, conservative enough that a single
   unlucky request never flips ``/readyz`` (which
   ``serving.server.ModelServer`` gates on this, see
   ``DL4J_TPU_SLO_READYZ``).

Implementation: a ring of coarse time buckets (width = short window /
30) holding (good, total) pairs — O(1) record, O(#buckets) evaluation,
no per-request allocation beyond the bucket roll. Gauges exported per
model: ``dl4j_slo_burn_rate{model,window}``,
``dl4j_slo_hit_rate{model,window}``, and
``dl4j_slo_healthy{model}``.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.environment import environment
from ..common.locks import ordered_lock
from ..common.metrics import registry


class SLOTracker:
    """Sliding-window success-rate tracking for one served model."""

    def __init__(self, model: str, *,
                 objective: Optional[float] = None,
                 latency_objective_s: Optional[float] = "env",
                 windows: Optional[Sequence[Tuple[float, float]]] = None,
                 min_samples: int = 20,
                 clock=time.monotonic):
        env = environment()
        self.model = str(model)
        self.objective = (env.slo_objective() if objective is None
                          else float(objective))
        self.latency_objective_s = (env.slo_latency_s()
                                    if latency_objective_s == "env"
                                    else latency_objective_s)
        self.windows: Tuple[Tuple[float, float], ...] = tuple(
            sorted((float(w), float(b))
                   for w, b in (windows if windows is not None
                                else env.slo_windows())))
        if not self.windows:
            raise ValueError("need at least one (window_s, burn) pair")
        self.min_samples = max(int(min_samples), 1)
        self._clock = clock
        # bucket ring sized for the longest window at short-window/30
        # granularity — burn-rate evaluation walks <= maxlen buckets
        self.bucket_s = max(self.windows[0][0] / 30.0, 0.05)
        maxlen = int(self.windows[-1][0] / self.bucket_s) + 2
        self._buckets: deque = deque(maxlen=maxlen)  # [idx, good, total]
        self._lock = ordered_lock("slo")
        reg = registry()
        self._m_requests = reg.counter(
            "dl4j_slo_requests_total",
            "SLO-eligible serving requests by objective outcome",
            labels=("model", "good"))
        burn = reg.gauge(
            "dl4j_slo_burn_rate",
            "Error-budget burn rate (error rate / allowed rate) per window",
            labels=("model", "window"))
        hit = reg.gauge(
            "dl4j_slo_hit_rate",
            "Fraction of requests meeting the objective per window",
            labels=("model", "window"))
        self._m_burn = {w: burn.labels(model=self.model, window=int(w))
                        for w, _ in self.windows}
        self._m_hit = {w: hit.labels(model=self.model, window=int(w))
                       for w, _ in self.windows}
        self._m_healthy = reg.gauge(
            "dl4j_slo_healthy",
            "1 while the model's SLO is not fast-burning, else 0",
            labels=("model",)).labels(model=self.model)
        self._m_healthy.set(1)
        self._m_excluded = reg.counter(
            "dl4j_slo_excluded_total",
            "Requests excluded from the SLO as client faults, by reason "
            "(e.g. quarantined poison requests)",
            labels=("model", "reason"))

    # -- recording ---------------------------------------------------------
    def record(self, latency_s: float, ok: bool = True):
        """One completed request: ``ok=False`` for a deadline miss /
        server error; an ``ok`` request still misses the objective when
        a latency objective is set and ``latency_s`` exceeds it."""
        good = bool(ok) and (self.latency_objective_s is None
                             or latency_s <= self.latency_objective_s)
        idx = int(self._clock() // self.bucket_s)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == idx:
                slot = self._buckets[-1]
            else:
                slot = [idx, 0, 0]
                self._buckets.append(slot)
            slot[1] += 1 if good else 0
            slot[2] += 1
        self._m_requests.labels(model=self.model,
                                good="true" if good else "false").inc()
        self._refresh_gauges()
        return good

    def record_excluded(self, reason: str):
        """Count a request deliberately NOT fed into the objective (a
        quarantined poison request is the request's fault, not the
        replica's) so the exclusion itself stays observable — a replica
        quarantining half its traffic should look odd on a dashboard
        even while its SLO reads healthy."""
        self._m_excluded.labels(model=self.model,
                                reason=str(reason)).inc()

    # -- evaluation --------------------------------------------------------
    def _counts(self, window_s: float) -> Tuple[int, int]:
        """(good, total) over the trailing ``window_s`` seconds."""
        floor = int((self._clock() - window_s) // self.bucket_s)
        good = total = 0
        with self._lock:
            for idx, g, t in self._buckets:
                if idx > floor:
                    good += g
                    total += t
        return good, total

    def hit_rate(self, window_s: float) -> Optional[float]:
        good, total = self._counts(window_s)
        return good / total if total else None

    def burn_rate(self, window_s: float) -> float:
        """Error-budget burn rate over the window; 0.0 with no traffic
        (an idle model is not burning budget)."""
        good, total = self._counts(window_s)
        if total == 0:
            return 0.0
        error_rate = (total - good) / total
        budget = max(1.0 - self.objective, 1e-9)
        return error_rate / budget

    def healthy(self) -> bool:
        """False only when every window burns past its threshold and the
        shortest window saw at least ``min_samples`` requests."""
        short_total = self._counts(self.windows[0][0])[1]
        if short_total < self.min_samples:
            return True
        return not all(self.burn_rate(w) >= b for w, b in self.windows)

    def snapshot(self) -> Dict:
        """JSON-able state for /readyz, /debug, and the flight
        recorder."""
        windows: List[Dict] = []
        for w, b in self.windows:
            good, total = self._counts(w)
            windows.append({
                "window_s": w, "burn_threshold": b, "total": total,
                "good": good,
                "hit_rate": good / total if total else None,
                "burn_rate": self.burn_rate(w)})
        return {"model": self.model, "objective": self.objective,
                "latency_objective_s": self.latency_objective_s,
                "min_samples": self.min_samples,
                "healthy": self.healthy(), "windows": windows}

    def _refresh_gauges(self):
        for w, _ in self.windows:
            good, total = self._counts(w)
            self._m_burn[w].set(self.burn_rate(w))
            if total:
                self._m_hit[w].set(good / total)
        self._m_healthy.set(1 if self.healthy() else 0)
