"""Production model-serving subsystem.

The layer between the library-level ``runtime.inference.InferenceEngine``
and "heavy traffic from millions of users": a versioned multi-model
registry with warm-before-cutover hot swap and rollback
(``registry.ModelRegistry``), Clipper/Orca-style admission control with
deadlines and load shedding (``admission.AdmissionController``), a stdlib
HTTP front end with liveness/readiness probes and the shared ``/metrics``
exposition (``server.ModelServer``), and a SIGTERM graceful-drain
sequence that hands warmup manifests to the next replica
(``lifecycle.GracefulLifecycle``).

Minimal flow::

    from deeplearning4j_tpu.serving import (GracefulLifecycle,
                                            ModelRegistry, ModelServer)

    registry = ModelRegistry()
    registry.deploy("mnist", "v1", net, example=x)   # warms BEFORE serving
    server = ModelServer(registry)
    port = server.start()
    GracefulLifecycle(registry, server).install()    # SIGTERM drains
    ...
    registry.deploy("mnist", "v2", net2)  # warm-before-cutover hot swap
    registry.rollback("mnist")            # instant: v1 stayed warm

Generative models (the ``models.causal_lm.CausalLM`` protocol) deploy
the same way but behind a KV-cached, continuous-batching
``runtime.generation.DecodeEngine`` and serve via
``POST /v1/models/<name>/generate`` (optionally streaming tokens as
chunked ndjson); the SLO latency fed per request is time-to-first-token::

    registry.deploy("lm", "v1", causal_lm)           # warms prefill
    registry.generate("lm", [1, 5, 9], max_tokens=32)  # ladder + decode

Every request is trace-scoped (W3C ``traceparent`` in, ``X-Trace-Id``
out; spans across admission/coalesce/dispatch), per-model SLOs with
multi-window burn rates gate ``/readyz`` (``slo.SLOTracker``), and a
``/debug/*`` endpoint family (recent requests, trace fetch, profiler
capture, compile-cache inventory, device memory) plus a SIGTERM/SIGQUIT
flight-recorder dump make a misbehaving replica explainable.

Env knobs (``DL4J_TPU_SERVING_*``): ``MAX_CONCURRENT``, ``QUEUE_DEPTH``,
``HIGH_WATER``, ``TIMEOUT_S``, ``DRAIN_TIMEOUT_S``, ``RETAIN``,
``MANIFEST_DIR``; observability: ``DL4J_TPU_SLO_OBJECTIVE``,
``DL4J_TPU_SLO_LATENCY_MS``, ``DL4J_TPU_SLO_WINDOWS``,
``DL4J_TPU_SLO_READYZ``, ``DL4J_TPU_REQUEST_RING``,
``DL4J_TPU_DEBUG_ENDPOINTS``, ``DL4J_TPU_PROFILE_DIR``,
``DL4J_TPU_FLIGHT_RECORDER_DIR``.
"""
from ..runtime.inference import PoisonRequestError  # noqa: F401
from .admission import (AdmissionController, DeadlineExceededError,  # noqa: F401
                        ShedError)
from .lifecycle import GracefulLifecycle  # noqa: F401
from .registry import (READY, RETIRED, WARMING, ModelRegistry,  # noqa: F401
                       ModelVersion)
from .resilience import (BreakerOpenError, CircuitBreaker,  # noqa: F401
                         EngineWatchdog, HealthRegistry, health, watchdog)
from .server import ModelServer, RequestRing  # noqa: F401
from .slo import SLOTracker  # noqa: F401
