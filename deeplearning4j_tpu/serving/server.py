"""Model-serving HTTP front end (stdlib ThreadingHTTPServer).

Reference: the Vertx remote-router endpoints mirrored by `ui/server.py`,
grown into a production serving surface:

    POST /v1/models/<name>/predict            current version
    POST /v1/models/<name>:<version>/predict  pinned version
    GET  /v1/models                           registry listing
    GET  /healthz                             liveness (process is up)
    GET  /readyz                              readiness (all current
                                              versions warmed, not
                                              draining) — 503 otherwise
    GET  /metrics, /metrics.json              shared Prometheus/JSON
                                              exposition (PR 3 registry)

Request bodies are JSON (``{"inputs": ..., "timeout_s": ...}`` — a list
becomes one array, a dict maps input/placeholder names for graph/SameDiff
models) or a raw ``.npy`` payload (``Content-Type: application/x-npy``;
the response mirrors the format). Deadlines propagate: the JSON
``timeout_s`` (or ``X-Request-Timeout-S`` header) bounds admission wait
AND micro-batcher queueing; an expired request answers 504 without ever
occupying a batch slot. Overload answers 429 with a ``Retry-After`` hint
from the admission controller. Status mapping: 404 unknown model/version,
400 malformed input, 409 pinned to a retired version, 503 draining.
"""
from __future__ import annotations

import io
import json
import logging
import re
import threading
from typing import Dict, Optional
from urllib.parse import urlparse

import numpy as np

from ..common.httpserver import (JsonRequestHandler,
                                 QuietThreadingHTTPServer, metrics_payload)
from ..runtime.inference import EngineClosedError
from .admission import AdmissionController, DeadlineExceededError, ShedError
from .registry import ModelRegistry

log = logging.getLogger(__name__)

_PREDICT_RE = re.compile(r"^/v1/models/([^/:]+)(?::([^/]+))?/predict$")
_NPY_TYPES = ("application/x-npy", "application/octet-stream")


def _np_cast(a: np.ndarray) -> np.ndarray:
    """JSON numbers arrive as f64/i64; the frontends run f32/i32 (x64 is
    disabled)."""
    if a.dtype == np.float64:
        return a.astype(np.float32)
    if a.dtype == np.int64:
        return a.astype(np.int32)
    return a


def _parse_inputs(obj):
    if isinstance(obj, dict):
        return {k: _np_cast(np.asarray(v)) for k, v in obj.items()}
    return _np_cast(np.asarray(obj))


def _jsonable_outputs(out):
    def arr(x):
        return np.asarray(x.jax() if hasattr(x, "jax") else x).tolist()

    if isinstance(out, dict):
        return {k: arr(v) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return [arr(v) for v in out]
    return arr(out)


class ModelServer:
    """HTTP server over a ModelRegistry with per-model admission control.

    One ``AdmissionController`` per model, created on first use from the
    ``DL4J_TPU_SERVING_*`` env knobs (or the constructor overrides);
    ``set_admission()`` swaps in a custom-tuned controller."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_concurrent: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 high_water: Optional[int] = None):
        self.registry = registry if registry is not None else ModelRegistry()
        self.host = host
        self.port = port
        self.draining = False
        self._admission_kwargs = dict(max_concurrent=max_concurrent,
                                      queue_depth=queue_depth,
                                      high_water=high_water)
        self._admission: Dict[str, AdmissionController] = {}
        self._admission_lock = threading.Lock()
        self._httpd: Optional[QuietThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- admission plumbing -----------------------------------------------
    def admission_for(self, name: str) -> AdmissionController:
        ctrl = self._admission.get(name)
        if ctrl is None:
            with self._admission_lock:
                ctrl = self._admission.get(name)
                if ctrl is None:
                    ctrl = AdmissionController(name,
                                               **self._admission_kwargs)
                    self._admission[name] = ctrl
        return ctrl

    def set_admission(self, name: str, controller: AdmissionController):
        with self._admission_lock:
            self._admission[name] = controller
        return self

    # -- lifecycle --------------------------------------------------------
    def start(self) -> int:
        """Serve on a daemon thread; returns the bound port."""
        self._httpd = QuietThreadingHTTPServer((self.host, self.port),
                                               self._handler())
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-model-server",
                                        daemon=True)
        self._thread.start()
        log.info("model server on %s:%d", self.host, self.port)
        return self.port

    def begin_drain(self):
        """Flip readiness off and shed all new work (the first step of a
        graceful shutdown; the HTTP socket stays up so load balancers see
        the 503s and drain routing)."""
        self.draining = True
        with self._admission_lock:
            for ctrl in self._admission.values():
                ctrl.close()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        return self

    # -- handler ----------------------------------------------------------
    def _handler(self):
        server = self

        class Handler(JsonRequestHandler):
            def do_GET(self):
                path = urlparse(self.path).path
                if path == "/healthz":
                    self.send_payload(b"ok", "text/plain")
                elif path == "/readyz":
                    ready = not server.draining and server.registry.ready()
                    self.send_json(
                        {"ready": ready, "draining": server.draining,
                         "models": server.registry.models()},
                        200 if ready else 503)
                elif path == "/v1/models":
                    self.send_json({"models": server.registry.models()})
                elif path == "/metrics":
                    self.send_payload(*metrics_payload())
                elif path == "/metrics.json":
                    self.send_payload(*metrics_payload("json"))
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):
                m = _PREDICT_RE.match(urlparse(self.path).path)
                if not m:
                    self.send_json({"error": "not found"}, 404)
                    return
                name, version = m.group(1), m.group(2)
                if server.draining:
                    self.send_json(
                        {"error": "server is draining"}, 503,
                        headers=[("Retry-After", "1")])
                    return
                try:
                    self._predict(name, version)
                except KeyError as e:
                    self.send_json({"error": str(e.args[0])}, 404)
                except ShedError as e:
                    retry = max(1, int(np.ceil(e.retry_after_s)))
                    self.send_json(
                        {"error": str(e),
                         "retry_after_s": round(e.retry_after_s, 3)},
                        429, headers=[("Retry-After", retry)])
                except (DeadlineExceededError, TimeoutError) as e:
                    self.send_json({"error": f"deadline exceeded: {e}"},
                                   504)
                except EngineClosedError as e:
                    # a version pinned to a retired/drained engine: a
                    # routine routing miss, not a server fault
                    self.send_json({"error": str(e)}, 409)
                except (ValueError, TypeError) as e:
                    self.send_json({"error": f"bad request: {e}"}, 400)
                except Exception as e:  # the server must outlive any model
                    log.exception("predict failed for %s", name)
                    self.send_json(
                        {"error": f"{type(e).__name__}: {e}"}, 500)

            def _predict(self, name: str, version: Optional[str]):
                body = self.read_body()
                ctype = (self.headers.get("Content-Type") or
                         "application/json").split(";")[0].strip()
                timeout_s = None
                hdr = self.headers.get("X-Request-Timeout-S")
                if hdr:
                    timeout_s = float(hdr)
                if ctype in _NPY_TYPES:
                    request = _np_cast(
                        np.load(io.BytesIO(body), allow_pickle=False))
                    as_npy = True
                else:
                    doc = json.loads(body or b"{}")
                    if "inputs" not in doc:
                        raise ValueError('JSON body must carry "inputs"')
                    request = _parse_inputs(doc["inputs"])
                    if doc.get("timeout_s") is not None:
                        timeout_s = float(doc["timeout_s"])
                    as_npy = False
                # resolve first so unknown models 404 before admission
                mv = server.registry.get(name, version)
                ctrl = server.admission_for(name)
                with ctrl.admit(timeout_s if timeout_s is not None
                                else "default",
                                version=mv.version) as permit:
                    out = server.registry.predict(
                        name, request, version=version,
                        timeout_s=permit.remaining_s())
                    mv = server.registry.get(name, version)
                if as_npy:
                    first = out
                    if isinstance(out, dict):
                        first = next(iter(out.values()))
                    elif isinstance(out, (list, tuple)):
                        first = out[0]
                    buf = io.BytesIO()
                    np.save(buf, np.asarray(
                        first.jax() if hasattr(first, "jax") else first))
                    self.send_payload(
                        buf.getvalue(), "application/x-npy",
                        headers=[("X-Model-Version", mv.version)])
                else:
                    self.send_json({"model": name, "version": mv.version,
                                    "outputs": _jsonable_outputs(out)})

        return Handler
