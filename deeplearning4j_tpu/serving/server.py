"""Model-serving HTTP front end (stdlib ThreadingHTTPServer).

Reference: the Vertx remote-router endpoints mirrored by `ui/server.py`,
grown into a production serving surface:

    POST /v1/models/<name>/predict            current version
    POST /v1/models/<name>:<version>/predict  pinned version
    POST /v1/models/<name>/generate           autoregressive generation
                                              (KV-cached decode engine;
                                              optional chunked token
                                              streaming)
    GET  /v1/models                           registry listing
    GET  /healthz                             liveness (process is up)
    GET  /readyz                              readiness (all current
                                              versions warmed, not
                                              draining, SLOs not fast-
                                              burning) — 503 otherwise
    GET  /metrics, /metrics.json              shared Prometheus/JSON
                                              exposition (PR 3 registry)
    GET  /debug/requests                      recent-requests ring with
                                              per-request span trees
    GET  /debug/trace/<trace_id>              one trace's span tree
    GET  /debug/compile_cache                 executable inventory + XLA
                                              cost analysis
    GET  /debug/memory                        device memory stats
    POST /debug/profile?seconds=              on-demand jax.profiler
                                              capture

Request bodies are JSON (``{"inputs": ..., "timeout_s": ...}`` — a list
becomes one array, a dict maps input/placeholder names for graph/SameDiff
models) or a raw ``.npy`` payload (``Content-Type: application/x-npy``;
the response mirrors the format). Deadlines propagate: the JSON
``timeout_s`` (or ``X-Request-Timeout-S`` header) bounds admission wait
AND micro-batcher queueing; an expired request answers 504 without ever
occupying a batch slot. Overload answers 429 with a ``Retry-After`` hint
from the admission controller. Status mapping: 404 unknown model/version,
400 malformed input, 409 pinned to a retired version, 503 draining.

``/generate`` serves models deployed behind a ``DecodeEngine``
(``{"prompt": [ids...], "max_tokens", "temperature", "top_k",
"eos_token", "stream", "timeout_s"}``): requests ride the same admission
controller and trace context as predict; the per-request SLO latency fed
to the tracker is **time-to-first-token**, the generative latency
objective. With ``"stream": true`` the response is
``application/x-ndjson`` over chunked transfer encoding — one
``{"token": id}`` line per sampled token, then a final
``{"done": true, ...}`` summary line.

Every predict is *request-scoped traced* (Dapper-style): an inbound W3C
``traceparent`` header joins the caller's trace, otherwise a fresh
trace_id is minted; either way the response echoes ``X-Trace-Id`` and
the admission wait, micro-batch coalesce, and padded dispatch all record
spans under that trace — ``GET /debug/requests`` (or
``/debug/trace/<id>``) reconstructs the timeline, including for requests
that expired or were shed. Each completed request also feeds the
per-model SLO tracker (``serving/slo.py``); a fast-burning error budget
flips ``/readyz`` (``DL4J_TPU_SLO_READYZ``).
"""
from __future__ import annotations

import io
import json
import logging
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..common import faults
from ..common.environment import environment
from ..common.locks import ordered_lock
from ..common.httpserver import (CLIENT_DISCONNECTS, JsonRequestHandler,
                                 QuietThreadingHTTPServer, handle_debug_get,
                                 handle_debug_post, metrics_payload)
from ..common.tracing import (context_from_traceparent, pop_disposition,
                              span, span_tree, tracer, use_context)
from ..runtime.inference import EngineClosedError, PoisonRequestError
from . import resilience
from .admission import AdmissionController, DeadlineExceededError, ShedError
from .registry import ModelRegistry
from .resilience import BreakerOpenError
from .slo import SLOTracker

log = logging.getLogger(__name__)

_PREDICT_RE = re.compile(r"^/v1/models/([^/:]+)(?::([^/]+))?/predict$")
_GENERATE_RE = re.compile(r"^/v1/models/([^/:]+)(?::([^/]+))?/generate$")
_NPY_TYPES = ("application/x-npy", "application/octet-stream")

#: response status -> ring/SLO outcome label
_OUTCOMES = {200: "ok", 400: "bad_request", 404: "not_found",
             409: "retired", 422: "quarantined", 429: "shed",
             500: "error", 503: "draining", 504: "deadline"}

#: statuses that count against the serving SLO (client mistakes don't:
#: a quarantined poison request — 422 — is the request's own fault and
#: must not burn the replica's error budget; it is counted separately
#: via ``SLOTracker.record_excluded`` and the request ring disposition)
_SLO_STATUSES = (200, 429, 500, 503, 504)


def _np_cast(a: np.ndarray) -> np.ndarray:
    """JSON numbers arrive as f64/i64; the frontends run f32/i32 (x64 is
    disabled)."""
    if a.dtype == np.float64:
        return a.astype(np.float32)
    if a.dtype == np.int64:
        return a.astype(np.int32)
    return a


def _parse_inputs(obj):
    if isinstance(obj, dict):
        return {k: _np_cast(np.asarray(v)) for k, v in obj.items()}
    return _np_cast(np.asarray(obj))


def _jsonable_outputs(out):
    def arr(x):
        return np.asarray(x.jax() if hasattr(x, "jax") else x).tolist()

    if isinstance(out, dict):
        return {k: arr(v) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return [arr(v) for v in out]
    return arr(out)


class RequestRing:
    """Bounded ring of completed-request records (the flight recorder's
    and ``/debug/requests``'s source). Thread-safe via deque atomics."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = environment().request_ring_size()
        self._records: deque = deque(maxlen=max(int(capacity), 1))

    def add(self, record: dict):
        self._records.append(record)

    def records(self) -> List[dict]:
        return list(self._records)

    def find(self, trace_id: str) -> Optional[dict]:
        for rec in reversed(self._records):
            if rec.get("trace_id") == trace_id:
                return rec
        return None

    def __len__(self) -> int:
        return len(self._records)


class ModelServer:
    """HTTP server over a ModelRegistry with per-model admission control.

    One ``AdmissionController`` and one ``SLOTracker`` per model, created
    on first use from the ``DL4J_TPU_SERVING_*`` / ``DL4J_TPU_SLO_*`` env
    knobs (or the constructor overrides); ``set_admission()`` /
    ``set_slo()`` swap in custom-tuned instances."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_concurrent: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 high_water: Optional[int] = None,
                 request_ring: Optional[int] = None):
        self.registry = registry if registry is not None else ModelRegistry()
        self.host = host
        self.port = port
        self.draining = False
        self._admission_kwargs = dict(max_concurrent=max_concurrent,
                                      queue_depth=queue_depth,
                                      high_water=high_water)
        self._admission: Dict[str, AdmissionController] = {}
        self._admission_lock = ordered_lock("server.admission")
        self._slo: Dict[str, SLOTracker] = {}
        self._slo_lock = ordered_lock("server.slo")
        self.request_ring = RequestRing(request_ring)
        self._httpd: Optional[QuietThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- admission plumbing -----------------------------------------------
    def admission_for(self, name: str) -> AdmissionController:
        ctrl = self._admission.get(name)
        if ctrl is None:
            with self._admission_lock:
                ctrl = self._admission.get(name)
                if ctrl is None:
                    ctrl = AdmissionController(name,
                                               **self._admission_kwargs)
                    self._admission[name] = ctrl
        return ctrl

    def set_admission(self, name: str, controller: AdmissionController):
        with self._admission_lock:
            self._admission[name] = controller
        return self

    # -- SLO plumbing ------------------------------------------------------
    def slo_for(self, name: str) -> SLOTracker:
        slo = self._slo.get(name)
        if slo is None:
            with self._slo_lock:
                slo = self._slo.get(name)
                if slo is None:
                    slo = SLOTracker(name)
                    self._slo[name] = slo
        return slo

    def set_slo(self, name: str, tracker: SLOTracker):
        with self._slo_lock:
            self._slo[name] = tracker
        return self

    def slo_healthy(self) -> bool:
        """True while no served model's error budget is fast-burning."""
        with self._slo_lock:
            trackers = list(self._slo.values())
        return all(t.healthy() for t in trackers)

    def slo_snapshot(self) -> Dict[str, dict]:
        with self._slo_lock:
            trackers = dict(self._slo)
        return {name: t.snapshot() for name, t in sorted(trackers.items())}

    # -- request accounting ------------------------------------------------
    def _finish_request(self, name: str, version: Optional[str],
                        trace_id: str, status: int, duration_s: float,
                        timeout_s: Optional[float],
                        kind: str = "predict",
                        latency_s: Optional[float] = None,
                        disposition: Optional[str] = None,
                        precision: Optional[str] = None,
                        priority: Optional[int] = None,
                        fleet_replica: Optional[str] = None,
                        fleet_attempt: Optional[str] = None,
                        phases: Optional[dict] = None):
        """Ring + SLO bookkeeping for one completed request, whatever its
        outcome (the ring is the /debug/requests + flight-recorder
        source). ``latency_s`` overrides the SLO-fed latency — generate
        requests feed time-to-first-token, the generative latency
        objective, while ``duration_s`` in the ring stays wall time.
        ``disposition`` records what the resilience machinery did to the
        request (``quarantined|retried|breaker_open|engine_restart``);
        when the handler did not set one, the engine-recorded
        disposition for this trace id is consumed — so a post-mortem can
        tell shed load from faulted load by trace id.
        ``fleet_replica``/``fleet_attempt`` echo the front-door attempt
        that carried the request (the ``X-Fleet-Replica`` /
        ``X-Fleet-Attempt`` headers the fleet router stamps per
        attempt), so ``/debug/requests`` — and the flight recorder,
        which dumps these same ring records — shows which hedge/retry a
        replica actually served; ``phases`` is the engine's per-request
        latency decomposition (queue/prefill/decode seconds)."""
        if disposition is None:
            disposition = pop_disposition(trace_id)
        else:
            pop_disposition(trace_id)  # handler's verdict wins; drop ours
        self.request_ring.add({
            "trace_id": trace_id, "model": name, "version": version,
            "kind": kind, "status": status,
            "outcome": _OUTCOMES.get(status, str(status)),
            "disposition": disposition,
            "precision": precision,
            "priority": priority,
            "ts": time.time(), "duration_s": round(duration_s, 6),
            "timeout_s": timeout_s,
            "fleet_replica": fleet_replica,
            "fleet_attempt": fleet_attempt,
            "phases": phases})
        if status in _SLO_STATUSES:
            try:
                self.slo_for(name).record(
                    latency_s if latency_s is not None else duration_s,
                    ok=status == 200)
            except Exception:  # SLO bookkeeping never fails a response
                log.exception("SLO record failed for %s", name)

    def debug_requests(self, query: Dict[str, List[str]]) -> dict:
        """``GET /debug/requests``: newest-first records, each joined
        with its span tree from the trace ring (so a deadline-expired
        request's admission wait / queue / coalesce / dispatch timeline
        reads in one place)."""
        try:
            limit = int((query.get("n") or ["50"])[0])
        except ValueError:
            limit = 50
        model = (query.get("model") or [None])[0]
        trace_id = (query.get("trace_id") or [None])[0]
        trc = tracer()
        out = []
        for rec in reversed(self.request_ring.records()):
            if model and rec.get("model") != model:
                continue
            if trace_id and rec.get("trace_id") != trace_id:
                continue
            out.append({**rec,
                        "spans": span_tree(trc.events_for(
                            rec["trace_id"]))})
            if len(out) >= max(limit, 1):
                break
        return {"count": len(out), "ring_size": len(self.request_ring),
                "requests": out}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> int:
        """Serve on a daemon thread; returns the bound port."""
        self._httpd = QuietThreadingHTTPServer((self.host, self.port),
                                               self._handler())
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-model-server",
                                        daemon=True)
        self._thread.start()
        log.info("model server on %s:%d", self.host, self.port)
        return self.port

    def begin_drain(self):
        """Flip readiness off and shed all new work (the first step of a
        graceful shutdown; the HTTP socket stays up so load balancers see
        the 503s and drain routing)."""
        self.draining = True
        with self._admission_lock:
            for ctrl in self._admission.values():
                ctrl.close()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        return self

    # -- handler ----------------------------------------------------------
    def _handler(self):
        server = self

        class Handler(JsonRequestHandler):
            _trace_id: Optional[str] = None

            def send_payload(self, body, content_type="text/plain",
                             code=200, headers=()):
                self._last_status = code
                if self._trace_id:
                    headers = list(headers) + [("X-Trace-Id",
                                                self._trace_id)]
                super().send_payload(body, content_type, code, headers)

            def do_GET(self):
                self._trace_id = None  # keep-alive: no stale echo
                url = urlparse(self.path)
                path = url.path
                if path == "/healthz":
                    self.send_payload(b"ok", "text/plain")
                elif path == "/readyz":
                    warm = not server.draining and server.registry.ready()
                    slo_ok = server.slo_healthy()
                    health = resilience.health()
                    engines_ok = health.healthy()
                    ready = (warm and engines_ok
                             and (slo_ok
                                  or not environment().slo_gate_readyz()))
                    self.send_json(
                        {"ready": ready, "draining": server.draining,
                         "slo_healthy": slo_ok,
                         "engines_healthy": engines_ok,
                         "engine_health": health.snapshot(),
                         "slo": server.slo_snapshot(),
                         "models": server.registry.models()},
                        200 if ready else 503)
                elif path == "/v1/models":
                    self.send_json({"models": server.registry.models()})
                elif path == "/metrics":
                    self.send_payload(*metrics_payload())
                elif path == "/metrics.json":
                    self.send_payload(*metrics_payload("json"))
                elif path.startswith("/debug/"):
                    if not environment().debug_endpoints_enabled():
                        self.send_json(
                            {"error": "debug endpoints disabled "
                                      "(DL4J_TPU_DEBUG_ENDPOINTS=0)"}, 404)
                    elif path == "/debug/requests":
                        self.send_json(server.debug_requests(
                            parse_qs(url.query)))
                    elif path == "/debug/slo":
                        self.send_json({"healthy": server.slo_healthy(),
                                        "models": server.slo_snapshot()})
                    elif path == "/debug/resilience":
                        self.send_json({
                            "breakers":
                                server.registry.breaker_snapshot(),
                            "engine_health":
                                resilience.health().snapshot(),
                            "watchdog":
                                resilience.watchdog().watched(),
                            "faults": faults.stats()})
                    elif path == "/debug/decode":
                        self.send_json({
                            "decode":
                                server.registry.decode_snapshots()})
                    elif not handle_debug_get(self, path):
                        self.send_json({"error": "not found"}, 404)
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):
                url = urlparse(self.path)
                path = url.path
                if path.startswith("/debug/"):
                    if not environment().debug_endpoints_enabled() or \
                            not handle_debug_post(self, path,
                                                  parse_qs(url.query)):
                        self.send_json({"error": "not found"}, 404)
                    return
                kind = "predict"
                m = _PREDICT_RE.match(path)
                if m is None:
                    m = _GENERATE_RE.match(path)
                    kind = "generate"
                if m is None:
                    self.send_json({"error": "not found"}, 404)
                    return
                name, version = m.group(1), m.group(2)
                # join the caller's W3C trace or mint a fresh one; the
                # whole request — admission wait, prefill/decode or
                # coalesce/dispatch — records spans under it, and every
                # response (including errors) echoes X-Trace-Id
                ctx = context_from_traceparent(
                    self.headers.get("traceparent"))
                self._trace_id = ctx.trace_id
                self._last_status = 500
                self._served_version = version
                self._timeout_s = None
                self._latency_s = None
                self._disposition = None
                self._precision = None
                # the fleet front door's brownout class rides X-Priority;
                # recording it in the ring lets a post-mortem tell what a
                # shed would have cost (which priorities were in flight)
                self._priority = None
                raw_prio = self.headers.get("X-Priority")
                if raw_prio is not None:
                    try:
                        self._priority = min(max(int(raw_prio.strip()),
                                                 0), 9)
                    except ValueError:
                        pass
                # the fleet router stamps which attempt this is
                # (primary|retry|hedge|affinity_fallback) and its own
                # view of this replica's URL; echoing them into the
                # ring joins a replica's /debug/requests (and flight
                # recorder) back to the front-door attempt it served
                self._fleet_replica = self.headers.get("X-Fleet-Replica")
                self._fleet_attempt = self.headers.get("X-Fleet-Attempt")
                self._phases = None
                if server.draining:
                    self.send_json(
                        {"error": "server is draining"}, 503,
                        headers=[("Retry-After", "1")])
                    return
                t0 = time.perf_counter()
                try:
                    with use_context(ctx), \
                            span("serving/request", model=name,
                                 version=version or "", kind=kind):
                        self._dispatch_request(kind, name, version)
                finally:
                    server._finish_request(
                        name, self._served_version, ctx.trace_id,
                        self._last_status, time.perf_counter() - t0,
                        self._timeout_s, kind=kind,
                        latency_s=self._latency_s,
                        disposition=self._disposition,
                        precision=self._precision,
                        priority=self._priority,
                        fleet_replica=self._fleet_replica,
                        fleet_attempt=self._fleet_attempt,
                        phases=self._phases)

            def _dispatch_request(self, kind: str, name: str,
                                  version: Optional[str]):
                try:
                    if faults.active():
                        # handler-level injection site: an InjectedFault
                        # here maps to 500 and burns the SLO like any
                        # other server fault (that is the point)
                        faults.check("http.handler", model=name,
                                     kind=kind)
                    if kind == "generate":
                        self._generate(name, version)
                    else:
                        self._predict(name, version)
                except KeyError as e:
                    self.send_json({"error": str(e.args[0])}, 404)
                except ShedError as e:
                    retry = max(1, int(np.ceil(e.retry_after_s)))
                    self.send_json(
                        {"error": str(e),
                         "retry_after_s": round(e.retry_after_s, 3)},
                        429, headers=[("Retry-After", retry)])
                except BreakerOpenError as e:
                    # fail-fast: the version's breaker is open; hint the
                    # client off for the larger of the probe window and
                    # the admission backlog estimate
                    self._disposition = "breaker_open"
                    hint = e.retry_after_s
                    try:
                        hint = max(hint, server.admission_for(name)
                                   .retry_after_hint())
                    except Exception:
                        pass
                    self.send_json(
                        {"error": str(e),
                         "retry_after_s": round(hint, 3)},
                        503, headers=[("Retry-After",
                                       max(1, int(np.ceil(hint))))])
                except (DeadlineExceededError, TimeoutError) as e:
                    self.send_json({"error": f"deadline exceeded: {e}"},
                                   504)
                except PoisonRequestError as e:
                    # quarantined: failed its coalesced dispatch AND the
                    # one isolated retry — the fault follows the request,
                    # so answer 4xx with the trace id and keep serving
                    self._disposition = "quarantined"
                    try:
                        server.slo_for(name).record_excluded("quarantined")
                    except Exception:
                        pass
                    self.send_json(
                        {"error": str(e), "quarantined": True,
                         "trace_id": self._trace_id}, 422)
                except EngineClosedError as e:
                    # a version pinned to a retired/drained engine: a
                    # routine routing miss, not a server fault
                    self.send_json({"error": str(e)}, 409)
                except (ValueError, TypeError) as e:
                    self.send_json({"error": f"bad request: {e}"}, 400)
                except Exception as e:  # the server must outlive any model
                    log.exception("predict failed for %s", name)
                    self.send_json(
                        {"error": f"{type(e).__name__}: {e}"}, 500)

            def _predict(self, name: str, version: Optional[str]):
                body = self.read_body()
                ctype = (self.headers.get("Content-Type") or
                         "application/json").split(";")[0].strip()
                timeout_s = None
                hdr = self.headers.get("X-Request-Timeout-S")
                if hdr:
                    timeout_s = float(hdr)
                if ctype in _NPY_TYPES:
                    request = _np_cast(
                        np.load(io.BytesIO(body), allow_pickle=False))
                    as_npy = True
                else:
                    doc = json.loads(body or b"{}")
                    if "inputs" not in doc:
                        raise ValueError('JSON body must carry "inputs"')
                    request = _parse_inputs(doc["inputs"])
                    if doc.get("timeout_s") is not None:
                        timeout_s = float(doc["timeout_s"])
                    as_npy = False
                self._timeout_s = timeout_s
                # resolve first so unknown models 404 before admission
                mv = server.registry.get(name, version)
                self._served_version = mv.version
                self._precision = mv.precision
                ctrl = server.admission_for(name)
                with ctrl.admit(timeout_s if timeout_s is not None
                                else "default",
                                version=mv.version) as permit:
                    out = server.registry.predict(
                        name, request, version=version,
                        timeout_s=permit.remaining_s())
                    mv = server.registry.get(name, version)
                    self._served_version = mv.version
                    self._precision = mv.precision
                if as_npy:
                    first = out
                    if isinstance(out, dict):
                        first = next(iter(out.values()))
                    elif isinstance(out, (list, tuple)):
                        first = out[0]
                    buf = io.BytesIO()
                    np.save(buf, np.asarray(
                        first.jax() if hasattr(first, "jax") else first))
                    self.send_payload(
                        buf.getvalue(), "application/x-npy",
                        headers=[("X-Model-Version", mv.version)])
                else:
                    self.send_json({"model": name, "version": mv.version,
                                    "outputs": _jsonable_outputs(out)})

            # -- generation (KV-cached decode engine) ---------------------
            def _generate(self, name: str, version: Optional[str]):
                doc = json.loads(self.read_body() or b"{}")
                if "prompt" not in doc:
                    raise ValueError('JSON body must carry "prompt" '
                                     "(a list of token ids)")
                prompt = doc["prompt"]
                if not isinstance(prompt, (list, tuple)) or not all(
                        isinstance(t, int) for t in prompt):
                    raise ValueError('"prompt" must be a flat list of '
                                     "integer token ids")
                timeout_s = None
                hdr = self.headers.get("X-Request-Timeout-S")
                if hdr:
                    timeout_s = float(hdr)
                if doc.get("timeout_s") is not None:
                    timeout_s = float(doc["timeout_s"])
                self._timeout_s = timeout_s
                opts = {}
                if doc.get("max_tokens") is not None:
                    opts["max_tokens"] = int(doc["max_tokens"])
                if doc.get("temperature") is not None:
                    opts["temperature"] = float(doc["temperature"])
                if doc.get("top_k") is not None:
                    opts["top_k"] = int(doc["top_k"])
                if "eos_token" in doc:
                    opts["eos_token"] = doc["eos_token"]
                stream = bool(doc.get("stream"))
                # resolve first so unknown models 404 before admission
                mv = server.registry.get(name, version)
                self._served_version = mv.version
                self._precision = mv.precision
                ctrl = server.admission_for(name)
                with ctrl.admit(timeout_s if timeout_s is not None
                                else "default",
                                version=mv.version) as permit:
                    if stream:
                        self._stream_generate(name, version, prompt,
                                              opts, permit)
                        return
                    res = server.registry.generate(
                        name, prompt, version=version,
                        timeout_s=permit.remaining_s(), **opts)
                mv = server.registry.get(name, version)
                self._served_version = mv.version
                self._precision = mv.precision
                self._latency_s = res.get("ttft_s")
                self._phases = res.get("phases")
                self.send_json({"model": name, "version": mv.version,
                                **res})

            def _stream_generate(self, name, version, prompt, opts,
                                 permit):
                """Chunked token streaming: one ndjson line per sampled
                token from the decode loop, then a summary line. The
                engine's on_token callback feeds a queue the handler
                thread drains — sockets are written from one thread
                only."""
                import queue

                mv = server.registry.get(name, version)
                from ..runtime.generation import DecodeEngine
                if not isinstance(mv.engine, DecodeEngine):
                    raise TypeError(f"model '{name}' is not generative; "
                                    "use predict()")
                q: "queue.Queue" = queue.Queue()
                fut = mv.engine.generate(
                    prompt, timeout_s=permit.remaining_s(),
                    on_token=q.put, **opts)
                self.begin_chunked("application/x-ndjson")
                try:
                    while True:
                        try:
                            tok = q.get(timeout=0.05)
                        except queue.Empty:
                            if fut.done() and q.empty():
                                break
                            continue
                        self.write_chunk(json.dumps(
                            {"token": tok}).encode() + b"\n")
                    try:
                        res = fut.result()
                        tail = {"done": True, "model": name,
                                "version": mv.version, **res}
                        self._latency_s = res.get("ttft_s")
                        self._phases = res.get("phases")
                    except Exception as e:  # headers are out: in-band error
                        self._last_status = 500
                        tail = {"done": True,
                                "error": f"{type(e).__name__}: {e}"}
                    self.write_chunk(json.dumps(tail).encode() + b"\n")
                finally:
                    self.end_chunked()

            # chunked transfer-encoding plumbing (streaming responses)
            def begin_chunked(self, content_type, code=200, headers=()):
                self._last_status = code
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Transfer-Encoding", "chunked")
                if self._trace_id:
                    self.send_header("X-Trace-Id", self._trace_id)
                for k, v in headers:
                    self.send_header(k, str(v))
                self.end_headers()

            def write_chunk(self, body: bytes):
                try:
                    self.wfile.write(b"%X\r\n" % len(body) + body + b"\r\n")
                    self.wfile.flush()
                except CLIENT_DISCONNECTS:
                    self.close_connection = True

            def end_chunked(self):
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except CLIENT_DISCONNECTS:
                    self.close_connection = True

        return Handler
