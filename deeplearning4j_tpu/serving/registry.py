"""Versioned multi-model registry with warm-before-cutover hot swap.

Reference: the reference ecosystem's model-server layer (ParallelInference
behind a router) plus the Clipper model-container registry — reshaped for
the TPU cost model, where "loading a model" is cheap and *compiling* it is
the outage. Deploying a new version therefore warms it first:

1. ``deploy(name, version, model)`` wraps the model in an
   ``InferenceEngine`` and compiles its bucket ladder BEFORE any traffic
   sees it, replaying — in priority order — the explicit ``example``, the
   live traffic shapes of the outgoing version
   (``InferenceEngine.observed_entries()``), or the on-disk warmup
   manifest a previous replica saved (``runtime.compile_cache.
   serving_manifest_dir``). Every compile lands in the PR-4 persistent
   executable cache, so the same ladder warms in milliseconds on the next
   replica.
2. The registry then atomically repoints the model's current version.
   The outgoing engine drains its in-flight requests before release and
   is *parked* (drained, but retained warm) so that…
3. ``rollback(name)`` repoints to the previous retained version
   instantly — its executables never left the process. Retention is
   bounded by ``DL4J_TPU_SERVING_RETAIN``; evicted versions are closed
   for good.

``predict()`` routes a request to the current (or a pinned) version and
transparently retries a request that raced a cutover — the
zero-failed-in-flight contract of the hot swap.
"""
from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..common.environment import environment
from ..common.locks import ordered_rlock
from ..common.mesh import mesh_shape as _mesh_shape, spec_desc
from ..common.metrics import registry as metrics_registry
from ..common.tracing import span
from ..quant.calibrate import QuantSpec, calibrate as quant_calibrate
from ..quant.transforms import (param_bytes_of, precision_of_model,
                                quantize_model)
from ..quant.validate import validate as quant_validate
from ..runtime import compile_cache
from ..runtime.generation import DecodeEngine, is_generative_model
from ..runtime.inference import EngineClosedError, InferenceEngine
from . import resilience
from .resilience import CircuitBreaker

log = logging.getLogger(__name__)

#: ModelVersion lifecycle states
WARMING = "warming"   # deployed but not yet warmed: /readyz stays false
READY = "ready"       # warmed and serving (or parked warm for rollback)
RETIRED = "retired"   # drained after a cutover/rollback; warm, re-admittable


def _safe_name(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", name)


class ModelVersion:
    """One deployed (name, version) pair and its serving engine."""

    __slots__ = ("name", "version", "engine", "state", "deployed_at",
                 "precision", "param_bytes", "divergence", "mesh_shape",
                 "param_spec")

    def __init__(self, name: str, version: str, engine: InferenceEngine):
        self.name = name
        self.version = version
        self.engine = engine
        self.state = WARMING
        self.deployed_at = time.time()
        #: serving precision ("float32"/"bfloat16"/"int8"/"fp8") and param
        #: footprint, filled by deploy() for every version (quantized or
        #: not); divergence is the gate report of a quantized deploy
        self.precision: Optional[str] = None
        self.param_bytes: Optional[int] = None
        self.divergence: Optional[Dict[str, float]] = None
        #: sharded deploys only: {"data": d, "model": m} and the
        #: PartitionSpec description ("auto(model)", "P(None, 'model')", …)
        self.mesh_shape: Optional[Dict[str, int]] = None
        self.param_spec: Optional[str] = None

    def describe(self) -> Dict[str, Any]:
        d = {"version": self.version, "state": self.state,
             "deployed_at": self.deployed_at,
             "buckets": list(self.engine.ladder),
             "max_batch": self.engine.max_batch,
             "generative": isinstance(self.engine, DecodeEngine),
             "precision": self.precision,
             "param_bytes": self.param_bytes}
        if self.mesh_shape is not None:
            d["mesh_shape"] = dict(self.mesh_shape)
            d["param_spec"] = self.param_spec
        if self.divergence is not None:
            d["quant_divergence"] = self.divergence
        return d


class ModelRegistry:
    """Named, versioned models behind one object; thread-safe."""

    def __init__(self, *, retain: Optional[int] = None,
                 manifest_dir: Optional[str] = "auto",
                 breaker_threshold: Optional[int] = None,
                 breaker_probe_s: Optional[float] = None):
        self.retain = (environment().serving_retain()
                       if retain is None else int(retain))
        # "auto" = ride the executable cache volume; None disables disk
        # manifests entirely (hot-swap handoff still works in-process)
        if manifest_dir == "auto":
            # with a fleet store configured, sync down the fleet's
            # observed-traffic manifests first so deploy() warms the
            # shapes other replicas served, not just this machine's past
            try:
                compile_cache.pull_manifests()
            except Exception:
                log.exception("fleet manifest pull failed; using local "
                              "manifests only")
            self._manifest_dir = compile_cache.serving_manifest_dir()
        else:
            self._manifest_dir = manifest_dir
        self._lock = ordered_rlock("registry")
        self._versions: Dict[str, List[ModelVersion]] = {}
        self._current: Dict[str, ModelVersion] = {}
        self._draining = False
        # per-version circuit breakers (None knobs = env defaults) and
        # the one-shot auto-rollback guard per (model, version)
        self._breaker_threshold = breaker_threshold
        self._breaker_probe_s = breaker_probe_s
        self._breakers: Dict[tuple, CircuitBreaker] = {}
        self._auto_rolled: set = set()
        reg = metrics_registry()
        self._m_deploys = reg.counter(
            "dl4j_serving_deploys_total", "Model versions deployed",
            labels=("model",))
        self._m_rollbacks = reg.counter(
            "dl4j_serving_rollbacks_total", "Model rollbacks",
            labels=("model",))
        self._m_auto_rollbacks = reg.counter(
            "dl4j_auto_rollbacks_total",
            "Rollbacks triggered by a persistently open circuit breaker",
            labels=("model",))
        self._m_model_bytes = reg.gauge(
            "dl4j_model_bytes",
            "Parameter bytes at rest of a deployed model version",
            labels=("model", "version"))
        self._m_quant_deploys = reg.counter(
            "dl4j_quant_deploys_total",
            "Quantized deploys that passed the divergence gate",
            labels=("model", "mode"))

    # -- manifests --------------------------------------------------------
    def manifest_path(self, name: str) -> Optional[str]:
        """Per-model warmup-manifest file (shared across versions: the
        incoming version replays what the model — not the executable —
        was serving)."""
        if not self._manifest_dir:
            return None
        return os.path.join(self._manifest_dir,
                            f"{_safe_name(name)}.warmup.json")

    def save_manifests(self) -> List[str]:
        """Persist the current versions' observed traffic shapes so the
        next replica warms before taking traffic. Returns written paths."""
        written = []
        with self._lock:
            currents = list(self._current.values())
        for mv in currents:
            path = mv.engine.manifest_path
            if not path:
                continue
            try:
                written.append(mv.engine.save_manifest(path))
            except (OSError, ValueError) as e:
                log.warning("warmup manifest save for %s:%s failed (%s)",
                            mv.name, mv.version, e)
        return written

    # -- deployment -------------------------------------------------------
    def deploy(self, name: str, version: str, model, *,
               outputs: Optional[Sequence[Any]] = None,
               max_batch: Optional[int] = None,
               buckets: Optional[Sequence[int]] = None,
               max_delay_ms: float = 2.0,
               warm: bool = True,
               example=None,
               batch_sizes: Optional[Sequence[int]] = None,
               drain_timeout_s: Optional[float] = None,
               decode_slots: Optional[int] = None,
               decode_max_ctx: Optional[int] = None,
               decode_prompt_buckets: Optional[Sequence[int]] = None,
               decode_eos_token: Optional[int] = None,
               decode_kv_block_size: Optional[int] = None,
               decode_kv_blocks: Optional[int] = None,
               decode_prefill_batch: Optional[int] = None,
               decode_draft_model=None,
               decode_spec_k: Optional[int] = None,
               decode_prefix_cache: Optional[bool] = None,
               quantize=None,
               calibration_batch=None,
               quant_max_divergence: Optional[float] = None,
               quant_min_top1: Optional[float] = None,
               mesh=None,
               param_spec=None) -> ModelVersion:
        """Deploy ``model`` as ``name``:``version`` with warm-before-
        cutover; returns the new (current) ModelVersion.

        With ``warm=True`` (default) the incoming engine compiles its
        buckets before the swap, from the first available source:
        ``example`` (optionally narrowed by ``batch_sizes``) > the live
        observed shapes of the outgoing version > the model's on-disk
        warmup manifest. ``warm=False`` cuts over immediately in the
        ``warming`` state — ``/readyz`` stays false until ``warm()``
        runs. The outgoing version drains in-flight requests and is
        parked warm for rollback.

        A *generative* model (the ``models.causal_lm.CausalLM`` protocol:
        ``init_paged_kv_cache``/``paged_prefill``/``paged_decode``)
        deploys behind a ``DecodeEngine`` instead of an
        ``InferenceEngine`` — served via ``generate()`` /
        ``POST /v1/models/<name>/generate``; the ``decode_*`` knobs size
        its slot count, context window, prompt bucket ladder, and default
        EOS (env defaults otherwise). ``decode_kv_block_size`` /
        ``decode_kv_blocks`` size the paged KV pool,
        ``decode_prefill_batch`` caps how many same-bucket prompts share
        one prefill dispatch, ``decode_draft_model`` +
        ``decode_spec_k`` enable greedy speculative decoding, and
        ``decode_prefix_cache`` gates content-addressed KV-prefix reuse
        across requests/turns (``DL4J_TPU_PREFIX_CACHE``, on by
        default). Warmup
        compiles one prefill executable per (prompt bucket, batch rung)
        pair plus the decode-step executable (plus the speculative step
        when a draft is configured).

        ``quantize`` opts this deploy into post-training quantization
        (quant/): ``True``/``"int8"``/``"fp8"`` pick the storage mode, a
        :class:`~deeplearning4j_tpu.quant.QuantSpec` is used as-is,
        ``None`` defers to ``DL4J_TPU_QUANT`` (off by default), ``False``
        forces full precision. A quantized deploy REQUIRES a gate batch —
        ``calibration_batch`` or ``example`` — and runs the max-divergence
        gate (quant/validate.py) between warmup and cutover:
        ``QuantizationRejectedError`` aborts the swap with the incoming
        engine closed and the full-precision current version still live.
        ``quant_max_divergence``/``quant_min_top1`` override the env
        budgets for this deploy only.

        ``mesh`` deploys the version *sharded* over a device mesh built
        with :func:`~deeplearning4j_tpu.common.mesh.serving_mesh`:
        params partition over the ``model`` axis per ``param_spec`` (a
        single PartitionSpec, a pytree of specs matching the params, or
        None for automatic last-divisible-dim sharding), batches shard
        over the ``data`` axis, and a generative model's paged KV pool
        splits its heads over ``model``. Warmed executables land in the
        raw executable store with their shardings, so a sharded replica
        warm-restarts without recompiling."""
        name, version = str(name), str(version)
        with self._lock:
            if self._draining:
                raise RuntimeError("registry is draining; no new deploys")
            for mv in self._versions.get(name, ()):
                if mv.version == version:
                    raise ValueError(
                        f"model '{name}' version '{version}' is already "
                        "deployed (versions are immutable; bump the "
                        "version)")
            outgoing = self._current.get(name)
        # -- optional PTQ: quantize BEFORE the engine is built, fail closed
        # on a missing gate batch (nothing allocated yet)
        full_model, spec, mode = model, None, quantize
        if isinstance(mode, QuantSpec):
            spec, mode = mode, mode.mode
        if mode is None:
            mode = environment().quant_mode() or None
        if mode is True:
            mode = "int8"
        elif mode is False or mode == "":
            mode = None
        gate_batch = (calibration_batch if calibration_batch is not None
                      else example)
        if mode:
            if gate_batch is None:
                raise ValueError(
                    f"deploy of '{name}:{version}' with quantize={mode!r} "
                    "needs a calibration_batch (or example) to run the "
                    "divergence gate — refusing to serve an unvalidated "
                    "quantized model")
            if spec is None:
                spec = quant_calibrate(full_model, gate_batch, mode=mode)
            model = quantize_model(full_model, spec)
        if is_generative_model(model):
            engine = DecodeEngine(model, slots=decode_slots,
                                  max_ctx=decode_max_ctx,
                                  prompt_buckets=decode_prompt_buckets,
                                  eos_token=decode_eos_token,
                                  kv_block_size=decode_kv_block_size,
                                  kv_blocks=decode_kv_blocks,
                                  prefill_batch=decode_prefill_batch,
                                  draft_model=decode_draft_model,
                                  spec_k=decode_spec_k,
                                  prefix_cache=decode_prefix_cache,
                                  model_name=name,
                                  mesh=mesh, param_spec=param_spec)
        else:
            engine = InferenceEngine(model, max_batch=max_batch,
                                     buckets=buckets,
                                     max_delay_ms=max_delay_ms,
                                     outputs=outputs,
                                     manifest_path=self.manifest_path(name),
                                     mesh=mesh, param_spec=param_spec)
        mv = ModelVersion(name, version, engine)
        if mesh is not None:
            mv.mesh_shape = _mesh_shape(mesh)
            mv.param_spec = spec_desc(param_spec)
        mv.precision = precision_of_model(model)
        mv.param_bytes = param_bytes_of(model)
        if warm:
            try:
                self._warm_engine(engine, outgoing, example, batch_sizes)
            except BaseException:
                # a deploy that dies mid-warmup must not leak the incoming
                # engine's worker thread / decode slots — it never became
                # current, so nobody else will ever close it
                engine.close(0.0)
                raise
            mv.state = READY
        if mode:
            # the divergence gate runs AFTER warmup and BEFORE cutover: a
            # rejected twin aborts the swap (engine closed, nothing
            # registered) with the full-precision current version live
            try:
                mv.divergence = quant_validate(
                    full_model, model, gate_batch,
                    max_divergence=quant_max_divergence,
                    min_top1=quant_min_top1,
                    model_name=name, version=version)
            except BaseException:
                engine.close(0.0)
                raise
        # atomic cutover: one pointer swap under the lock
        with self._lock:
            if self._draining:
                engine.close(0.0)
                raise RuntimeError("registry is draining; no new deploys")
            self._versions.setdefault(name, []).append(mv)
            self._current[name] = mv
        self._m_deploys.labels(model=name).inc()
        if mv.param_bytes is not None:
            self._m_model_bytes.labels(
                model=name, version=version).set(mv.param_bytes)
        if mode:
            self._m_quant_deploys.labels(model=name, mode=mode).inc()
        self._watch(mv)
        # the outgoing engine finishes its in-flight work, then parks
        if outgoing is not None:
            outgoing.engine.drain(
                drain_timeout_s if drain_timeout_s is not None
                else environment().serving_drain_timeout_s())
            outgoing.state = RETIRED
            self._unwatch(outgoing)
        self._prune(name)
        log.info("deployed %s:%s (%s)%s", name, version, mv.state,
                 f", replacing {outgoing.version}" if outgoing else "")
        return mv

    def _warm_engine(self, engine, outgoing: Optional[ModelVersion],
                     example, batch_sizes) -> List[int]:
        if isinstance(engine, DecodeEngine):
            # generative warmup is fully shape-determined: prefill bucket
            # ladder + the one decode step; nothing to replay from traffic
            return engine.warmup()
        if example is not None:
            return engine.warmup(example, batch_sizes=batch_sizes)
        if outgoing is not None:
            entries = outgoing.engine.observed_entries()
            if entries:
                return engine.warmup(entries=entries)
        return engine.warmup()  # on-disk manifest of a previous replica

    def warm(self, name: str, example=None,
             batch_sizes: Optional[Sequence[int]] = None) -> List[int]:
        """Warm the *current* version of ``name`` (the deferred half of a
        ``deploy(warm=False)``) and flip it ready."""
        mv = self.get(name)
        buckets = self._warm_engine(mv.engine, None, example, batch_sizes)
        mv.state = READY
        return buckets

    # -- resolution -------------------------------------------------------
    def get(self, name: str, version: Optional[str] = None) -> ModelVersion:
        """The current ModelVersion of ``name``, or a pinned version.
        Raises KeyError when unknown."""
        with self._lock:
            if version is None:
                mv = self._current.get(name)
                if mv is None:
                    raise KeyError(f"no model '{name}' deployed")
                return mv
            for mv in self._versions.get(name, ()):
                if mv.version == str(version):
                    return mv
        raise KeyError(f"model '{name}' has no version '{version}'")

    def models(self) -> Dict[str, Dict[str, Any]]:
        """Listing for ``GET /v1/models``."""
        with self._lock:
            return {name: {
                "current": self._current[name].version
                if name in self._current else None,
                "versions": [mv.describe() for mv in versions],
            } for name, versions in sorted(self._versions.items())}

    def decode_snapshots(self) -> List[Dict[str, Any]]:
        """Live decode-engine state for ``GET /debug/decode`` and the
        flight recorder: one entry per generative model, the current
        version's slot map, block tables, pool occupancy, queue depth,
        and speculative acceptance (``DecodeEngine.debug_snapshot()``)."""
        with self._lock:
            currents = sorted(self._current.items())
        out = []
        for name, mv in currents:
            snap_fn = getattr(mv.engine, "debug_snapshot", None)
            if callable(snap_fn):
                snap = snap_fn()
                snap["model"] = name
                snap["version"] = mv.version
                out.append(snap)
        return out

    def ready(self) -> bool:
        """Readiness: not draining, and every deployed model's current
        version is warmed. (An empty registry is ready — liveness is
        /healthz's job.)"""
        with self._lock:
            return not self._draining and all(
                mv.state == READY for mv in self._current.values())

    # -- dispatch watchdog -------------------------------------------------
    @staticmethod
    def _watch(mv: ModelVersion):
        """Register the (now current) version's engine with the dispatch
        watchdog: a dispatch stuck past deadline × factor marks it
        unhealthy and flips /readyz. No-op when the watchdog is disabled
        (DL4J_TPU_WATCHDOG_FACTOR <= 0)."""
        budget = resilience.watchdog_budget_s()
        if budget is not None:
            resilience.watchdog().register(f"{mv.name}:{mv.version}",
                                           mv.engine, budget)

    @staticmethod
    def _unwatch(mv: ModelVersion):
        resilience.watchdog().unregister(f"{mv.name}:{mv.version}")

    # -- circuit breakers -------------------------------------------------
    def breaker_for(self, name: str, version: str) -> CircuitBreaker:
        """The (lazily created) circuit breaker of one model version."""
        key = (str(name), str(version))
        br = self._breakers.get(key)
        if br is None:
            with self._lock:
                br = self._breakers.get(key)
                if br is None:
                    br = CircuitBreaker(
                        key[0], key[1],
                        threshold=self._breaker_threshold,
                        probe_s=self._breaker_probe_s)
                    self._breakers[key] = br
        return br

    def breaker_snapshot(self) -> Dict[str, dict]:
        """Every breaker's state, for /readyz, /debug and the flight
        recorder."""
        with self._lock:
            breakers = dict(self._breakers)
        return {f"{n}:{v}": br.snapshot()
                for (n, v), br in sorted(breakers.items())}

    #: dispatch outcomes that must NOT count as breaker failures: drain
    #: races (the swap retry handles them), deadline/shed pressure (load,
    #: not fault), quarantined poison (the request's own fault), and
    #: client-side input errors
    _BREAKER_EXEMPT = (EngineClosedError, TimeoutError, KeyError, TypeError,
                       ValueError)

    def _dispatch_guarded(self, mv: ModelVersion, fn):
        """One breaker-accounted dispatch attempt against ``mv``. A
        quarantined poison request counts as a failure too — a *flood*
        of consecutive quarantines with no success in between is a sick
        executable, and failing fast beats grinding through isolated
        retries — but any success in between resets the count, so one
        poison rider never opens a healthy version's breaker."""
        br = self.breaker_for(mv.name, mv.version)
        br.preflight()
        try:
            out = fn()
        except self._BREAKER_EXEMPT:
            raise
        except Exception:
            if br.record_failure():
                self._maybe_auto_rollback(mv.name, br)
            raise
        br.record_success()
        return out

    def _maybe_auto_rollback(self, name: str, br: CircuitBreaker):
        """Env-gated last resort: a breaker that re-opens
        ``auto_rollback_opens`` times in a row while a warm parked
        previous version exists repoints to that version — degraded
        service beats no service. Fires at most once per (model,
        version)."""
        env = environment()
        if not env.auto_rollback():
            return
        if br.consecutive_opens < env.auto_rollback_opens():
            return
        key = (name, br.version)
        with self._lock:
            if key in self._auto_rolled:
                return
            versions = self._versions.get(name, [])
            cur = self._current.get(name)
            if cur is None or cur.version != br.version:
                return  # an older version's breaker; nothing to do
            idx = versions.index(cur)
            target = versions[idx - 1] if idx > 0 else None
            if target is None or target.engine.closed:
                return  # no warm parked version to fall back to
            self._auto_rolled.add(key)
        log.error("auto-rollback: %s:%s breaker persistently open "
                  "(%d consecutive opens); rolling back", name,
                  br.version, br.consecutive_opens)
        try:
            self.rollback(name)
            self._m_auto_rollbacks.labels(model=name).inc()
        except Exception:
            log.exception("auto-rollback of %s failed", name)

    # -- prediction -------------------------------------------------------
    def predict(self, name: str, request,
                version: Optional[str] = None,
                timeout_s: Optional[float] = None):
        """Route one request through the micro-batcher of the resolved
        version. A request that races a hot swap (the engine drains
        between resolution and dispatch) is transparently retried against
        the replacement — in-flight traffic never fails on a deploy or
        rollback. TimeoutError propagates when ``timeout_s`` expires
        before dispatch. Runs in a ``serving/predict`` span of the
        caller's trace (the engine's queue/dispatch spans nest under
        it). Each attempt is accounted against the version's circuit
        breaker: an open breaker fails fast with ``BreakerOpenError``
        (503 + Retry-After at the HTTP layer)."""
        with span("serving/predict", model=name,
                  version=str(version) if version is not None else ""):
            last_exc: Optional[Exception] = None
            for _ in range(4):
                mv = self.get(name, version)
                if isinstance(mv.engine, DecodeEngine):
                    raise TypeError(
                        f"model '{name}' is generative; use generate() "
                        "(POST /v1/models/<name>/generate)")

                def attempt(mv=mv):
                    try:
                        return mv.engine.submit(
                            request, timeout_s=timeout_s).result()
                    except ValueError:
                        # batch larger than max_batch: the chunked sync
                        # path (re-raises genuine bad-request errors)
                        return mv.engine.infer(request)

                try:
                    return self._dispatch_guarded(mv, attempt)
                except EngineClosedError as e:
                    last_exc = e
                    if version is not None:
                        raise  # pinned to a retired/closed version
                    continue  # current swapped mid-flight; re-resolve
            raise last_exc  # registry is shutting down (drain_all)

    # -- generation -------------------------------------------------------
    def generate(self, name: str, prompt,
                 version: Optional[str] = None,
                 timeout_s: Optional[float] = None, **opts):
        """Route one generation request to the resolved version's
        ``DecodeEngine`` and block for the result dict. Same hot-swap
        contract as ``predict()``: a request that races a cutover is
        transparently retried against the replacement. ``timeout_s``
        bounds the wait for a decode slot; ``opts`` pass through to
        ``DecodeEngine.generate`` (max_tokens, temperature, top_k,
        eos_token, on_token)."""
        with span("serving/generate", model=name,
                  version=str(version) if version is not None else ""):
            last_exc: Optional[Exception] = None
            for _ in range(4):
                mv = self.get(name, version)
                if not isinstance(mv.engine, DecodeEngine):
                    raise TypeError(
                        f"model '{name}' is not generative; use predict()")
                try:
                    return self._dispatch_guarded(
                        mv, lambda mv=mv: mv.engine.generate(
                            prompt, timeout_s=timeout_s, **opts).result())
                except EngineClosedError as e:
                    last_exc = e
                    if version is not None:
                        raise  # pinned to a retired/closed version
                    continue  # current swapped mid-flight; re-resolve
            raise last_exc

    # -- rollback / retention ---------------------------------------------
    def rollback(self, name: str,
                 drain_timeout_s: Optional[float] = None) -> ModelVersion:
        """Repoint ``name`` to the previous retained version (its engine
        re-admits instantly — executables never left the process). The
        rolled-away-from version drains and is parked."""
        with self._lock:
            versions = self._versions.get(name)
            if not versions:
                raise KeyError(f"no model '{name}' deployed")
            cur = self._current[name]
            idx = versions.index(cur)
            if idx == 0:
                raise RuntimeError(
                    f"model '{name}' has no retained version to roll "
                    f"back to (current: {cur.version})")
            target = versions[idx - 1]
            target.engine.start()  # reverse the park-drain
            target.state = READY
            self._current[name] = target
        self._watch(target)
        cur.engine.drain(drain_timeout_s if drain_timeout_s is not None
                         else environment().serving_drain_timeout_s())
        cur.state = RETIRED
        self._unwatch(cur)
        self._m_rollbacks.labels(model=name).inc()
        log.info("rolled back %s: %s -> %s", name, cur.version,
                 target.version)
        return target

    def _prune(self, name: str):
        """Close and drop the oldest non-current versions beyond the
        retention cap."""
        to_close: List[ModelVersion] = []
        with self._lock:
            versions = self._versions.get(name, [])
            cur = self._current.get(name)
            others = [mv for mv in versions if mv is not cur]
            excess = len(others) - self.retain
            if excess > 0:
                for mv in others[:excess]:
                    versions.remove(mv)
                    to_close.append(mv)
        for mv in to_close:
            mv.engine.close(environment().serving_drain_timeout_s())
            log.info("evicted %s:%s beyond retain=%d", name, mv.version,
                     self.retain)

    def undeploy(self, name: str,
                 drain_timeout_s: Optional[float] = None):
        """Drain and permanently close every version of ``name``."""
        with self._lock:
            versions = self._versions.pop(name, [])
            self._current.pop(name, None)
        t = (drain_timeout_s if drain_timeout_s is not None
             else environment().serving_drain_timeout_s())
        for mv in versions:
            mv.engine.close(t)
            mv.state = RETIRED
            self._unwatch(mv)
        return self

    # -- graceful drain ---------------------------------------------------
    def drain_all(self, timeout_s: Optional[float] = None,
                  save_manifests: bool = True) -> bool:
        """SIGTERM path: stop serving, flush every engine's micro-batcher,
        and (by default) save the warmup manifests the next replica warms
        from. Idempotent. Returns True when everything drained in time."""
        t = (timeout_s if timeout_s is not None
             else environment().serving_drain_timeout_s())
        with self._lock:
            self._draining = True
            versions = [mv for vs in self._versions.values() for mv in vs]
        if save_manifests:
            self.save_manifests()
        ok = True
        for mv in versions:
            ok = mv.engine.close(t) and ok
            mv.state = RETIRED
            self._unwatch(mv)
        return ok
