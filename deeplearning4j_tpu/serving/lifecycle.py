"""Graceful serving lifecycle: SIGTERM drain and replica handoff.

Reference: the Kubernetes termination contract every production serving
deployment runs under — on SIGTERM a replica must (1) fail its readiness
probe so the load balancer stops routing to it, (2) refuse new work with
backpressure the client understands, (3) finish what it already accepted,
and (4) leave enough state behind that its replacement starts warm. Here:

1. ``ModelServer.begin_drain()`` — ``/readyz`` answers 503, predicts
   answer 503/429, admission controllers shed their waiters.
2. ``ModelRegistry.drain_all()`` — every engine's micro-batcher flushes
   its queued requests, in-flight dispatches finish, late submits fail
   fast with ``EngineClosedError``.
3. ``save_manifests()`` — the observed-traffic warmup manifests land in
   ``runtime.compile_cache.serving_manifest_dir()``; paired with the
   persistent executable cache, the next replica (or the next version of
   a rolling deploy) warms the same bucket ladder before taking traffic.
4. The HTTP socket closes last, after the work is done.

``GracefulLifecycle.install()`` wires this to SIGTERM (handler chains to
any previously installed one); ``drain()`` can also be called directly —
e.g. from a preStop hook or a test.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Callable, Iterable, Optional

from ..common.environment import environment
from .registry import ModelRegistry
from .server import ModelServer

log = logging.getLogger(__name__)


class GracefulLifecycle:
    """Owns the drain sequence for one (registry, server) pair."""

    def __init__(self, registry: ModelRegistry,
                 server: Optional[ModelServer] = None,
                 drain_timeout_s: Optional[float] = None,
                 on_drained: Optional[Callable[[], None]] = None):
        self.registry = registry
        self.server = server
        self.drain_timeout_s = (drain_timeout_s
                                if drain_timeout_s is not None
                                else environment().serving_drain_timeout_s())
        self.on_drained = on_drained
        self._lock = threading.Lock()
        self._drain_started = False
        self._drained = threading.Event()
        self._previous: dict = {}

    # -- signal wiring ----------------------------------------------------
    def install(self, signals: Iterable[int] = (signal.SIGTERM,)):
        """Install the drain handler (main thread only — a CPython
        constraint of ``signal.signal``). The previous handler is chained
        after ours and restored by ``uninstall()``."""
        for sig in signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        return self

    def _handle(self, signum, frame):
        log.info("signal %d: starting graceful drain", signum)
        # the drain blocks on in-flight work; never do that in a signal
        # handler — hand it to a thread and return immediately
        threading.Thread(target=self.drain, name="dl4j-tpu-drain",
                         daemon=True).start()
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    # -- the drain sequence -----------------------------------------------
    @property
    def drained(self) -> bool:
        return self._drained.is_set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def drain(self) -> bool:
        """Run the full drain sequence (idempotent: concurrent calls wait
        for the first). Returns True when everything flushed in time."""
        with self._lock:
            if self._drain_started:
                return self._drained.wait(self.drain_timeout_s + 5)
            self._drain_started = True
        try:
            if self.server is not None:
                self.server.begin_drain()  # readyz -> 503, shed new work
            ok = self.registry.drain_all(timeout_s=self.drain_timeout_s,
                                         save_manifests=True)
            if self.server is not None:
                self.server.stop()  # socket closes after the work is done
            if self.on_drained is not None:
                try:
                    self.on_drained()
                except Exception:
                    log.exception("on_drained callback failed")
            log.info("graceful drain complete (flushed=%s)", ok)
            return ok
        finally:
            self._drained.set()
