"""Graceful serving lifecycle: SIGTERM drain and replica handoff.

Reference: the Kubernetes termination contract every production serving
deployment runs under — on SIGTERM a replica must (1) fail its readiness
probe so the load balancer stops routing to it, (2) refuse new work with
backpressure the client understands, (3) finish what it already accepted,
and (4) leave enough state behind that its replacement starts warm. Here:

1. ``ModelServer.begin_drain()`` — ``/readyz`` answers 503, predicts
   answer 503/429, admission controllers shed their waiters.
2. ``ModelRegistry.drain_all()`` — every engine's micro-batcher flushes
   its queued requests, in-flight dispatches finish, late submits fail
   fast with ``EngineClosedError``.
3. ``save_manifests()`` — the observed-traffic warmup manifests land in
   ``runtime.compile_cache.serving_manifest_dir()``; paired with the
   persistent executable cache, the next replica (or the next version of
   a rolling deploy) warms the same bucket ladder before taking traffic.
4. The HTTP socket closes last, after the work is done.

On the way down (and on demand at SIGQUIT, which does *not* drain) the
lifecycle writes a **flight recorder**: one JSON file carrying the
recent-requests ring, the buffered trace events, the per-model SLO
state, and the metrics snapshot — the post-mortem a dead replica can no
longer serve from ``/debug/requests``. Dumps land in
``DL4J_TPU_FLIGHT_RECORDER_DIR`` (default ``<cache_dir>/flight``) and
are written atomically.

``GracefulLifecycle.install()`` wires this to SIGTERM (handler chains to
any previously installed one); ``drain()`` can also be called directly —
e.g. from a preStop hook or a test.

With a fleet-shared artifact store configured (``DL4J_TPU_REMOTE_CACHE``)
the contract extends across replicas: ``drain()`` additionally pushes the
local executables + manifests to the shared store
(``compile_cache.push_to_remote``), and :func:`restore_on_boot` pulls
them down on the way up — call it before deploying models, i.e. before
``/readyz`` can flip, so the load balancer never routes traffic to a
replica that would compile instead of serve.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Callable, Iterable, Optional

from ..common import faults
from ..common.environment import environment
from ..common.locks import ordered_lock
from ..common.metrics import registry as metrics_registry
from ..common.tracing import tracer
from ..runtime import compile_cache
from . import resilience
from .registry import ModelRegistry
from .server import ModelServer

log = logging.getLogger(__name__)


def restore_on_boot() -> dict:
    """Pull the fleet's executables + warmup manifests from the shared
    artifact store into the local cache (no-op without
    ``DL4J_TPU_REMOTE_CACHE``). Call before ``registry.deploy`` /
    ``ModelServer`` start so every bucket warms from a store hit and
    ``/readyz`` only ever flips on a replica that won't compile under
    live traffic. Returns ``{"executables": n, "manifests": m}``."""
    try:
        return compile_cache.pull_from_remote()
    except Exception:
        log.exception("artifact-store pull on boot failed; continuing "
                      "with a cold cache")
        return {"executables": 0, "manifests": 0}


class GracefulLifecycle:
    """Owns the drain sequence for one (registry, server) pair."""

    def __init__(self, registry: ModelRegistry,
                 server: Optional[ModelServer] = None,
                 drain_timeout_s: Optional[float] = None,
                 on_drained: Optional[Callable[[], None]] = None):
        self.registry = registry
        self.server = server
        self.drain_timeout_s = (drain_timeout_s
                                if drain_timeout_s is not None
                                else environment().serving_drain_timeout_s())
        self.on_drained = on_drained
        self._lock = ordered_lock("lifecycle")
        self._drain_started = False
        self._drained = threading.Event()
        self._previous: dict = {}

    # -- signal wiring ----------------------------------------------------
    def install(self, signals: Iterable[int] = (signal.SIGTERM,),
                dump_signals: Iterable[int] = (
                    (signal.SIGQUIT,) if hasattr(signal, "SIGQUIT")
                    else ())):
        """Install the drain handler on ``signals`` and a dump-only
        handler on ``dump_signals`` (SIGQUIT = "show me what you were
        doing" without shutting down). Main thread only — a CPython
        constraint of ``signal.signal``. Previous handlers are chained
        after ours and restored by ``uninstall()``."""
        for sig in signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        for sig in dump_signals:
            self._previous[sig] = signal.signal(sig, self._handle_dump)
        return self

    def uninstall(self):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        return self

    def _handle(self, signum, frame):
        log.info("signal %d: starting graceful drain", signum)
        # the drain blocks on in-flight work; never do that in a signal
        # handler — hand it to a thread and return immediately
        threading.Thread(target=self.drain, name="dl4j-tpu-drain",
                         daemon=True).start()
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    def _handle_dump(self, signum, frame):
        log.info("signal %d: dumping flight recorder", signum)
        threading.Thread(target=self.dump_flight_recorder,
                         name="dl4j-tpu-flight-dump", daemon=True).start()
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    # -- flight recorder ---------------------------------------------------
    def dump_flight_recorder(self, path: Optional[str] = None
                             ) -> Optional[str]:
        """Write the in-memory observability state — recent-requests
        ring, buffered trace events, SLO snapshots, metrics — as one JSON
        file (atomic: tmp + rename). ``path`` overrides the default
        ``<flight_recorder_dir>/flight-<utc>-<pid>.json``; returns the
        written path, or None when the recorder is disabled (no dir
        resolvable) or the write failed — a dump must never break the
        drain."""
        try:
            if path is None:
                d = environment().flight_recorder_dir()
                if not d:
                    return None
                path = os.path.join(
                    d, time.strftime("flight-%Y%m%d-%H%M%S",
                                     time.gmtime())
                    + f"-{os.getpid()}.json")
            server = self.server
            doc = {
                "dumped_at": time.time(),
                "pid": os.getpid(),
                "draining": self._drain_started,
                "requests": (server.request_ring.records()
                             if server is not None else []),
                "slo": (server.slo_snapshot()
                        if server is not None else {}),
                # resilience state: which breakers were open, which
                # engines were flagged unhealthy, and what faults were
                # armed — the ring's per-request dispositions only make
                # sense next to these
                "breakers": self.registry.breaker_snapshot(),
                "engine_health": resilience.health().snapshot(),
                "faults": faults.stats(),
                # generative decode state: slot map, block tables, pool
                # occupancy, speculative acceptance (same join as
                # /debug/decode)
                "decode": self.registry.decode_snapshots(),
                "trace_events": tracer().events(),
                "metrics": metrics_registry().snapshot(),
            }
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
            log.info("flight recorder written to %s (%d requests, %d "
                     "trace events)", path, len(doc["requests"]),
                     len(doc["trace_events"]))
            return path
        except Exception:
            log.exception("flight recorder dump failed")
            return None

    # -- the drain sequence -----------------------------------------------
    @property
    def drained(self) -> bool:
        return self._drained.is_set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def drain(self) -> bool:
        """Run the full drain sequence (idempotent: concurrent calls wait
        for the first). Returns True when everything flushed in time."""
        with self._lock:
            if self._drain_started:
                return self._drained.wait(self.drain_timeout_s + 5)
            self._drain_started = True
        try:
            if self.server is not None:
                self.server.begin_drain()  # readyz -> 503, shed new work
            # snapshot the in-memory observability state before engines
            # flush — the post-mortem of whatever this replica was doing
            self.dump_flight_recorder()
            ok = self.registry.drain_all(timeout_s=self.drain_timeout_s,
                                         save_manifests=True)
            # publish this replica's compiles + manifests to the shared
            # artifact store (no-op without DL4J_TPU_REMOTE_CACHE) so its
            # replacement boots warm instead of recompiling under load
            try:
                compile_cache.push_to_remote()
            except Exception:
                log.exception("artifact-store push on drain failed")
            if self.server is not None:
                self.server.stop()  # socket closes after the work is done
            if self.on_drained is not None:
                try:
                    self.on_drained()
                except Exception:
                    log.exception("on_drained callback failed")
            log.info("graceful drain complete (flushed=%s)", ok)
            return ok
        finally:
            self._drained.set()
