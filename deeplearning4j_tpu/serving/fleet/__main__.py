"""Run the fleet front door: ``python -m deeplearning4j_tpu.serving.fleet
--replicas http://h1:8000,http://h2:8000``.

The router process needs no accelerator and no model — it proxies to the
serving replicas and keeps only routing state. Replicas can also be
passed as repeated ``--replicas`` flags; membership can grow at runtime
by restarting with the longer list (or programmatically via
``FleetRouter.add_replica``).
"""
from __future__ import annotations

import argparse
import logging
import sys
import time

from .router import FleetRouter, FleetServer


def _parse_replicas(values) -> list:
    urls = []
    for v in values or ():
        urls.extend(u.strip() for u in v.split(",") if u.strip())
    return urls


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.serving.fleet",
        description="Front-of-fleet replica router for model serving")
    ap.add_argument("--replicas", action="append", required=True,
                    metavar="URL[,URL...]",
                    help="serving replica base URLs (repeatable or "
                         "comma-separated)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--poll-s", type=float, default=None,
                    help="replica poll cadence (DL4J_TPU_FLEET_POLL_S)")
    ap.add_argument("--retries", type=int, default=None,
                    help="failover retries (DL4J_TPU_FLEET_RETRIES)")
    ap.add_argument("--timeout-s", type=float, default=None,
                    help="per-attempt timeout (DL4J_TPU_FLEET_TIMEOUT_S)")
    ap.add_argument("--retry-budget", type=float, default=None,
                    help="failover+hedge token ratio "
                         "(DL4J_TPU_FLEET_RETRY_BUDGET)")
    ap.add_argument("--hedge-pctl", type=float, default=None,
                    help="hedge-delay latency percentile, <=0 disables "
                         "(DL4J_TPU_FLEET_HEDGE_PCTL)")
    ap.add_argument("--brownout-frac", type=float, default=None,
                    help="ready fraction below which the front door "
                         "sheds low-priority traffic "
                         "(DL4J_TPU_FLEET_BROWNOUT_FRAC)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    urls = _parse_replicas(args.replicas)
    if not urls:
        ap.error("--replicas needs at least one URL")
    router = FleetRouter(urls, poll_s=args.poll_s, retries=args.retries,
                         timeout_s=args.timeout_s,
                         retry_budget=args.retry_budget,
                         hedge_pctl=args.hedge_pctl,
                         brownout_frac=args.brownout_frac)
    server = FleetServer(router, host=args.host, port=args.port)
    port = server.start()
    print(f"fleet router on http://{args.host}:{port} "
          f"fronting {len(urls)} replicas", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
