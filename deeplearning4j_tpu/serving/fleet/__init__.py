"""Sharded serving fleet: scale-up over a mesh, scale-out over replicas.

The two halves of serving beyond one device:

**Scale-up** (tensor parallelism) lives in the engines, not here:
``registry.deploy(..., mesh=serving_mesh(), param_spec=...)`` shards a
version's params over the mesh's ``model`` axis (batches over ``data``,
a generative model's paged KV pool over its heads) and the raw
executable store round-trips the sharded executables, so a sharded
replica warm-restarts without recompiling. See
:mod:`deeplearning4j_tpu.common.mesh` (``serving_mesh``,
``param_shardings``) and the ``mesh``/``param_spec`` kwargs on
``InferenceEngine`` / ``DecodeEngine`` / ``ModelRegistry.deploy``.

**Scale-out** (replica routing) is this package:
:class:`~.router.FleetRouter` fronts N ``ModelServer`` replicas by URL
with least-loaded dispatch (admission EWMA x backlog, polled from each
replica's ``/metrics.json``), readyz-aware membership, and the
tail-tolerance layer: budgeted failover + hedged requests drawing from
one fleet-wide :class:`~.router.RetryBudget`, outlier ejection over
actual dispatch outcomes with probe re-admission, brownout
shedding by ``X-Priority`` when ready capacity drops, and
consistent-hash session affinity (``X-Session-Id`` / prompt-prefix
fingerprint) that pins a chat session's turns to the replica whose
decode engine holds its KV blocks in the radix prefix cache.
:class:`~.router.FleetServer` is the HTTP front door;
``python -m deeplearning4j_tpu.serving.fleet --replicas ...`` runs it
standalone. A joining replica pre-bakes the fleet's bucket ladder from
the shared warmup manifests before its ``/readyz`` flips, so elastic
scale-out never routes traffic onto a cold compile.

Minimal flow::

    from deeplearning4j_tpu.common.mesh import serving_mesh
    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
    from deeplearning4j_tpu.serving.fleet import FleetRouter, FleetServer

    # each replica process: sharded deploy + HTTP server
    registry = ModelRegistry()
    registry.deploy("mnist", "v1", net, example=x, mesh=serving_mesh())
    port = ModelServer(registry).start()

    # the front door (its own process, no JAX needed)
    router = FleetRouter([f"http://127.0.0.1:{port}", ...])
    front = FleetServer(router)
    front.start()                      # clients talk to this one URL

**Observability plane**: the front door is also the fleet's one pane of
glass. Every dispatch attempt (primary / retry / hedge /
affinity_fallback) records a ``fleet/attempt`` span under the inbound
trace context and forwards ``traceparent`` with the attempt's span id as
parent, so the fleet's ``GET /debug/trace/<id>`` stitches the front-door
attempts with each involved replica's server-side tree into one
cross-process trace — a hedged request renders as a single trace with
both attempts and the winner's full admission/dispatch subtree.
:class:`~.aggregator.FleetAggregator` rides the existing poll loop,
folding each replica's ``/metrics.json`` into a bounded time-series ring
with per-type merge semantics (counters summed with restart-reset
detection, gauges last-value-per-replica, histograms bucket-wise summed
so merged percentiles are exact); the fleet serves ``GET /metrics`` +
``/metrics.json`` (per-replica series labeled ``replica`` plus merged
series) and ``GET /fleet/signals``, the documented autoscaler feed.

Env knobs: ``DL4J_TPU_FLEET_POLL_S`` (replica poll cadence),
``DL4J_TPU_FLEET_RETRIES`` (failover attempts),
``DL4J_TPU_FLEET_TIMEOUT_S`` (per-attempt timeout),
``DL4J_TPU_FLEET_RETRY_BUDGET`` (failover+hedge token ratio),
``DL4J_TPU_FLEET_HEDGE_PCTL`` (hedge-delay latency percentile),
``DL4J_TPU_FLEET_BROWNOUT_FRAC`` (ready fraction below which the front
door sheds), ``DL4J_TPU_FLEET_DEFAULT_PRIORITY`` (priority assumed
without an ``X-Priority`` header), ``DL4J_TPU_FLEET_AGG_RETENTION_S`` /
``DL4J_TPU_FLEET_AGG_MAX_SAMPLES`` (signal-ring retention). Telemetry:
``dl4j_fleet_replicas{model}``,
``dl4j_router_dispatch_total{replica,outcome}``,
``dl4j_fleet_hedges_total{model,outcome}``,
``dl4j_fleet_ejections_total{replica,reason}``,
``dl4j_fleet_shed_total{model,priority}`` and friends (see
:mod:`.router`).
"""
from .aggregator import (FleetAggregator, histogram_quantile,  # noqa: F401
                         render_prometheus_text)
from .router import (FleetRouter, FleetServer, MidStreamError,  # noqa: F401
                     NoReplicaError, Replica, RetryBudget,
                     prompt_fingerprint)
