"""Front-of-fleet replica router: scale-out over N ``ModelServer``\\ s.

One :class:`FleetRouter` fronts N independent serving replicas (each a
``serving.ModelServer`` — typically one process per TPU slice / host),
addressed by base URL. The router is deliberately *stateless* about
models: replicas own deployment, warmup, admission, and SLOs; the router
only decides **which** replica answers a request and retries replica-
level failures somewhere else.

Routing policy — least loaded, admission-aware:

- A background poller refreshes every replica's ``/readyz`` (is it
  allowed to take traffic at all?) and ``/metrics.json`` (the admission
  controller's live gauges: ``dl4j_serving_ewma_service_seconds``,
  ``dl4j_serving_queue_depth``, ``dl4j_serving_active``,
  ``dl4j_serving_waiters``) every ``DL4J_TPU_FLEET_POLL_S`` seconds.
- A request for model M goes to the READY replica with the lowest
  expected drain time: ``(waiters + router-side in-flight) x EWMA
  service seconds``. Router-side in-flight counts dispatches the poller
  has not seen yet, so a burst does not pile onto one replica between
  polls.
- Replica-level failures — connection refused/reset, timeout, HTTP 503
  — fail over: up to ``DL4J_TPU_FLEET_RETRIES`` (default 1) retries on a
  *different* replica, the failed one marked not-ready until a poll
  succeeds again. Request-level outcomes (2xx/4xx/429) are the
  replica's answer and are returned as-is — a shed (429) on the least
  loaded replica means the fleet is saturated, and retrying it
  elsewhere would only amplify the overload.

Scale-out elasticity rides the warmup manifests of the serving layer: a
joining replica pointed at the shared manifest directory
(``DL4J_TPU_SERVING_MANIFEST_DIR`` / the executable-cache volume)
pre-bakes the fleet's observed bucket ladder during ``deploy()`` —
its ``/readyz`` stays false until the ladder is compiled, so
``add_replica()`` can be called *before* warmup finishes and the router
will not route to it until it is actually ready. With a fleet-shared
artifact store (``DL4J_TPU_REMOTE_CACHE``) the joiner *downloads* that
ladder instead of compiling it: ``lifecycle.restore_on_boot()`` pulls
the fleet's manifests + executables before deploy, so every warmup
bucket is a store hit and cold-join time-to-ready is bounded by
artifact download, not XLA.

Poll scheduling is jittered: each replica is polled on its own
deterministic phase within ``DL4J_TPU_FLEET_POLL_S`` (see
``poll_offset``) so N replicas don't all get probed on the same tick.

Telemetry: ``dl4j_fleet_replicas{model}`` (ready replicas currently
serving each model) and ``dl4j_router_dispatch_total{replica,outcome}``
with outcome ``ok`` (replica answered), ``failover`` (replica-level
failure, retried elsewhere), ``failed`` (failure with no retry budget
left), ``no_replica`` (nothing ready).
"""
from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.error
import urllib.request
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ...common.environment import environment
from ...common.locks import ordered_lock
from ...common.metrics import registry as metrics_registry

log = logging.getLogger(__name__)

#: admission gauges polled off every replica's /metrics.json; missing
#: series (a replica that has not served yet) default to 0.0
_POLLED_GAUGES = ("dl4j_serving_ewma_service_seconds",
                  "dl4j_serving_queue_depth",
                  "dl4j_serving_active",
                  "dl4j_serving_waiters")


class NoReplicaError(RuntimeError):
    """No ready replica could take the request (none ready, or every
    attempt hit a replica-level failure with the retry budget spent)."""


class Replica:
    """One fleet member: its URL and the last polled view of it."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.ready = False
        self.models: List[str] = []          # models the replica serves
        #: per-model admission view: model -> {ewma_s, queue_depth,
        #: active, waiters}
        self.load: Dict[str, Dict[str, float]] = {}
        self.inflight = 0                    # router-side, un-polled yet
        self.dispatched = 0                  # lifetime routed attempts
        self.last_poll_s: Optional[float] = None
        self.consecutive_failures = 0

    def score(self, model: str) -> float:
        """Expected drain time of one more request on this replica:
        (admission backlog + router-side in-flight) x EWMA service
        seconds. Lower is better. A replica with no admission history
        yet (a fresh joiner) takes only the 1e-4 floor — routing to it
        is how the fleet learns its real EWMA."""
        view = self.load.get(model, {})
        ewma = float(view.get("ewma_s") or 0.0)
        backlog = float(view.get("waiters") or 0.0) + self.inflight
        return (backlog + 1.0) * max(ewma, 1e-4)

    def snapshot(self) -> Dict[str, Any]:
        return {"url": self.url, "ready": self.ready,
                "models": list(self.models),
                "load": {m: dict(v) for m, v in sorted(self.load.items())},
                "inflight": self.inflight,
                "dispatched": self.dispatched,
                "last_poll_s": self.last_poll_s,
                "consecutive_failures": self.consecutive_failures}


def _parse_metrics_json(doc: dict) -> Dict[str, Dict[str, float]]:
    """``/metrics.json`` -> model -> admission view. Tolerates missing
    families (a replica that has not admitted a request yet)."""
    out: Dict[str, Dict[str, float]] = {}
    short = {"dl4j_serving_ewma_service_seconds": "ewma_s",
             "dl4j_serving_queue_depth": "queue_depth",
             "dl4j_serving_active": "active",
             "dl4j_serving_waiters": "waiters"}
    for fam in _POLLED_GAUGES:
        for series in (doc.get(fam) or {}).get("series", ()):
            model = (series.get("labels") or {}).get("model")
            if model is None:
                continue
            try:
                value = float(series.get("value") or 0.0)
            except (TypeError, ValueError):
                value = 0.0
            out.setdefault(model, {})[short[fam]] = value
    return out


class FleetRouter:
    """Least-loaded, readyz-aware request router over serving replicas.

    ``replicas`` are base URLs (``http://host:port``). Poll cadence,
    failover retry budget, and per-attempt timeout default to the
    ``DL4J_TPU_FLEET_*`` env knobs. ``start_polling()`` runs the
    background refresh; tests can drive ``poll_once()`` directly."""

    def __init__(self, replicas: Sequence[str] = (), *,
                 poll_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 timeout_s: Optional[float] = None):
        env = environment()
        self.poll_s = env.fleet_poll_s() if poll_s is None else float(poll_s)
        self.retries = env.fleet_retries() if retries is None \
            else max(int(retries), 0)
        self.timeout_s = env.fleet_timeout_s() if timeout_s is None \
            else float(timeout_s)
        self._lock = ordered_lock("fleet.router")
        self._replicas: Dict[str, Replica] = {}
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = metrics_registry()
        self._m_replicas = reg.gauge(
            "dl4j_fleet_replicas",
            "Ready replicas currently serving each model",
            labels=("model",))
        self._m_dispatch = reg.counter(
            "dl4j_router_dispatch_total",
            "Routed dispatch attempts by replica and outcome "
            "(ok|failover|failed|no_replica)",
            labels=("replica", "outcome"))
        for url in replicas:
            self.add_replica(url, poll=False)

    # -- membership -------------------------------------------------------
    def add_replica(self, url: str, *, poll: bool = True) -> Replica:
        """Register one replica. It takes traffic only once a poll sees
        its ``/readyz`` true — safe to call while the replica is still
        warming its bucket ladder from the shared manifest."""
        rep = Replica(url)
        with self._lock:
            existing = self._replicas.get(rep.url)
            if existing is not None:
                return existing
            self._replicas[rep.url] = rep
        if poll:
            self._poll_replica(rep)
            self._update_fleet_gauge()
        return rep

    def remove_replica(self, url: str) -> bool:
        with self._lock:
            gone = self._replicas.pop(url.rstrip("/"), None) is not None
        if gone:
            self._update_fleet_gauge()
        return gone

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def snapshot(self) -> Dict[str, Any]:
        """``/fleet`` debug view: every replica's polled state."""
        return {"poll_s": self.poll_s, "retries": self.retries,
                "replicas": [r.snapshot() for r in self.replicas()]}

    # -- polling ----------------------------------------------------------
    def _fetch_json(self, url: str, timeout: float):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")

    def _poll_replica(self, rep: Replica):
        timeout = min(self.timeout_s, max(self.poll_s * 2, 1.0))
        try:
            try:
                status, ready_doc = self._fetch_json(
                    rep.url + "/readyz", timeout)
            except urllib.error.HTTPError as e:
                # /readyz answers 503 with the same JSON body when unready
                status, ready_doc = e.code, json.loads(e.read() or b"{}")
            _, metrics_doc = self._fetch_json(
                rep.url + "/metrics.json", timeout)
        except (OSError, ValueError) as e:
            with self._lock:
                rep.ready = False
                rep.consecutive_failures += 1
                rep.last_poll_s = time.time()
            log.debug("poll of %s failed: %r", rep.url, e)
            return
        with self._lock:
            rep.ready = status == 200 and bool(ready_doc.get("ready"))
            rep.models = sorted((ready_doc.get("models") or {}).keys())
            rep.load = _parse_metrics_json(metrics_doc)
            rep.consecutive_failures = 0
            rep.last_poll_s = time.time()

    def poll_once(self):
        """One synchronous refresh of every replica (tests; the poll
        thread spreads the same work across the period instead)."""
        for rep in self.replicas():
            self._poll_replica(rep)
        self._update_fleet_gauge()

    def poll_offset(self, url: str) -> float:
        """Deterministic per-replica phase within the poll period,
        ``[0, poll_s)``: each replica's first scheduled poll is delayed
        by this much so N replicas spread over the window instead of
        being probed in one thundering-herd tick (and, fleet-wide, N
        routers hash the same replica to the same phase rather than all
        re-synchronizing on their own start times). Hash, not index, so
        an offset never changes as membership churns."""
        return (zlib.crc32(url.rstrip("/").encode("utf-8")) % 9973) \
            / 9973.0 * self.poll_s

    def _update_fleet_gauge(self):
        counts: Dict[str, int] = {}
        with self._lock:
            reps = list(self._replicas.values())
            for rep in reps:
                if not rep.ready:
                    continue
                for model in rep.models:
                    counts[model] = counts.get(model, 0) + 1
            known = set()
            for rep in reps:
                known.update(rep.models)
        for model in known:
            self._m_replicas.labels(model=model).set(counts.get(model, 0))

    def start_polling(self) -> "FleetRouter":
        if self._poll_thread is not None:
            return self
        self._stop.clear()

        def loop():
            # each replica keeps its own next-poll deadline, first seen
            # at now + poll_offset(url): distinct phases per replica,
            # full poll_s cadence each thereafter
            due: Dict[str, float] = {}
            while not self._stop.is_set():
                now = time.monotonic()
                polled = False
                for rep in self.replicas():
                    when = due.get(rep.url)
                    if when is None:
                        when = now + self.poll_offset(rep.url)
                        due[rep.url] = when
                    if when > now:
                        continue
                    try:
                        self._poll_replica(rep)
                    except Exception:
                        log.exception("fleet poll of %s failed", rep.url)
                    due[rep.url] = now + self.poll_s
                    polled = True
                if polled:
                    self._update_fleet_gauge()
                with self._lock:
                    live = set(self._replicas)
                for url in list(due):
                    if url not in live:
                        del due[url]
                now = time.monotonic()
                next_due = min(due.values(), default=now + self.poll_s)
                self._stop.wait(max(min(next_due - now, self.poll_s), 0.01))

        self._poll_thread = threading.Thread(
            target=loop, name="dl4j-tpu-fleet-poll", daemon=True)
        self._poll_thread.start()
        return self

    def stop_polling(self):
        self._stop.set()
        t = self._poll_thread
        if t is not None:
            t.join(timeout=max(self.poll_s * 2, 2.0))
            self._poll_thread = None

    # -- routing ----------------------------------------------------------
    def _candidates(self, model: Optional[str]) -> List[Replica]:
        """READY replicas (serving ``model``, when known), best score
        first."""
        with self._lock:
            reps = [r for r in self._replicas.values() if r.ready]
        if model is not None:
            serving = [r for r in reps if model in r.models]
            # a replica whose model list is unknown yet (no successful
            # poll since deploy) still counts — the attempt will 404
            # and surface the truth
            reps = serving or reps
        if model is not None:
            # dispatched count breaks score ties: equally loaded
            # replicas round-robin instead of piling onto the first
            reps.sort(key=lambda r: (r.score(model), r.dispatched, r.url))
        return reps

    def route(self, method: str, path: str, body: Optional[bytes] = None,
              headers: Sequence[Tuple[str, str]] = (),
              model: Optional[str] = None,
              timeout_s: Optional[float] = None
              ) -> Tuple[int, Dict[str, str], bytes, str]:
        """Route one HTTP request to the best replica, failing over on
        replica-level errors. Returns ``(status, headers, body,
        replica_url)``. Raises :class:`NoReplicaError` when no replica
        could take it."""
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        tried: List[str] = []
        attempts = self.retries + 1
        last_err: Optional[BaseException] = None
        for _ in range(attempts):
            rep = next((r for r in self._candidates(model)
                        if r.url not in tried), None)
            if rep is None:
                break
            tried.append(rep.url)
            with self._lock:
                rep.inflight += 1
                rep.dispatched += 1
            try:
                req = urllib.request.Request(
                    rep.url + path, data=body, method=method,
                    headers=dict(headers))
                try:
                    with urllib.request.urlopen(req, timeout=timeout) as r:
                        status, hdrs, payload = (r.status, dict(r.headers),
                                                 r.read())
                except urllib.error.HTTPError as e:
                    status, hdrs, payload = e.code, dict(e.headers), e.read()
            except (OSError, urllib.error.URLError) as e:
                # connection refused/reset, DNS, timeout: replica-level
                last_err = e
                self._mark_failed(rep, "connect")
                continue
            finally:
                with self._lock:
                    rep.inflight = max(rep.inflight - 1, 0)
            if status == 503:
                # replica-level: draining / breaker / not ready — take it
                # out of rotation and try the next one
                last_err = None
                self._mark_failed(rep, "503")
                continue
            self._m_dispatch.labels(replica=rep.url, outcome="ok").inc()
            return status, hdrs, payload, rep.url
        if tried:
            self._m_dispatch.labels(replica=tried[-1],
                                    outcome="failed").inc()
            raise NoReplicaError(
                f"all routed attempts failed (tried {tried})"
                + (f": {last_err!r}" if last_err else ""))
        self._m_dispatch.labels(replica="", outcome="no_replica").inc()
        raise NoReplicaError(
            "no ready replica" + (f" for model '{model}'" if model else ""))

    def _mark_failed(self, rep: Replica, why: str):
        with self._lock:
            rep.ready = False
            rep.consecutive_failures += 1
        self._m_dispatch.labels(replica=rep.url, outcome="failover").inc()
        log.warning("replica %s failed (%s); failing over", rep.url, why)
        self._update_fleet_gauge()

    # -- convenience client API -------------------------------------------
    def predict(self, model: str, inputs, *,
                timeout_s: Optional[float] = None) -> dict:
        """JSON predict against the least-loaded replica; returns the
        parsed response body. Non-2xx answers raise RuntimeError with
        the replica's error payload."""
        body = json.dumps({"inputs": inputs if isinstance(inputs, (dict,
                           list)) else inputs.tolist()}).encode()
        status, _, payload, url = self.route(
            "POST", f"/v1/models/{model}/predict", body,
            headers=[("Content-Type", "application/json")],
            model=model, timeout_s=timeout_s)
        doc = json.loads(payload or b"{}")
        if status != 200:
            raise RuntimeError(
                f"predict on {url} answered {status}: {doc.get('error')}")
        return doc

    def generate(self, model: str, prompt: Sequence[int], *,
                 timeout_s: Optional[float] = None, **opts) -> dict:
        body = json.dumps({"prompt": list(prompt), **opts}).encode()
        status, _, payload, url = self.route(
            "POST", f"/v1/models/{model}/generate", body,
            headers=[("Content-Type", "application/json")],
            model=model, timeout_s=timeout_s)
        doc = json.loads(payload or b"{}")
        if status != 200:
            raise RuntimeError(
                f"generate on {url} answered {status}: {doc.get('error')}")
        return doc


_MODEL_PATH_RE = re.compile(r"^/v1/models/([^/:]+)(?::[^/]+)?/")

#: request headers the front door forwards to the replica (trace context
#: and deadlines must survive the hop; hop-by-hop headers must not)
_FORWARDED_HEADERS = ("content-type", "traceparent", "x-request-timeout-s")


class FleetServer:
    """HTTP front door over a :class:`FleetRouter`: the one URL clients
    talk to. ``POST /v1/models/...`` proxies to the least-loaded ready
    replica (with failover); ``GET /v1/models`` answers from the best
    replica; ``/readyz`` is the *fleet's* readiness (any replica ready);
    ``/fleet`` is the router's polled membership view; ``/metrics`` is
    the router process's own registry (dispatch counters + fleet
    gauges)."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        from ...common.httpserver import QuietThreadingHTTPServer
        self._httpd = QuietThreadingHTTPServer((self.host, self.port),
                                               self._handler())
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-fleet-router",
                                        daemon=True)
        self._thread.start()
        self.router.start_polling()
        log.info("fleet router on %s:%d fronting %d replicas",
                 self.host, self.port, len(self.router.replicas()))
        return self.port

    def stop(self):
        self.router.stop_polling()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        return self

    def _handler(self):
        from ...common.httpserver import JsonRequestHandler, metrics_payload
        router = self.router

        class Handler(JsonRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    self.send_payload(b"ok", "text/plain")
                elif path == "/readyz":
                    reps = router.replicas()
                    ready = any(r.ready for r in reps)
                    self.send_json(
                        {"ready": ready,
                         "replicas": [{"url": r.url, "ready": r.ready}
                                      for r in reps]},
                        200 if ready else 503)
                elif path == "/fleet":
                    self.send_json(router.snapshot())
                elif path == "/metrics":
                    self.send_payload(*metrics_payload())
                elif path == "/metrics.json":
                    self.send_payload(*metrics_payload("json"))
                elif path == "/v1/models":
                    self._proxy("GET", None)
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                m = _MODEL_PATH_RE.match(path)
                if m is None:
                    self.send_json({"error": "not found"}, 404)
                    return
                self._proxy("POST", m.group(1))

            def _proxy(self, method: str, model: Optional[str]):
                body = self.read_body() if method == "POST" else None
                fwd = [(k, v) for k, v in self.headers.items()
                       if k.lower() in _FORWARDED_HEADERS]
                try:
                    status, hdrs, payload, url = router.route(
                        method, self.path, body, headers=fwd, model=model)
                except NoReplicaError as e:
                    self.send_json({"error": str(e)}, 503,
                                   headers=[("Retry-After", "1")])
                    return
                passthrough = [(k, v) for k, v in hdrs.items()
                               if k.lower() in ("x-trace-id",
                                                "x-model-version",
                                                "retry-after")]
                passthrough.append(("X-Fleet-Replica", url))
                self.send_payload(
                    payload,
                    hdrs.get("Content-Type", "application/json"),
                    status, headers=passthrough)

        return Handler
