"""Front-of-fleet replica router: tail-tolerant scale-out over N
``ModelServer``\\ s.

One :class:`FleetRouter` fronts N independent serving replicas (each a
``serving.ModelServer`` — typically one process per TPU slice / host),
addressed by base URL. The router is deliberately *stateless* about
models: replicas own deployment, warmup, admission, and SLOs; the router
only decides **which** replica answers a request and what happens when a
replica-level failure or a slow tail threatens it.

Routing policy — least loaded, admission-aware:

- A background poller refreshes every replica's ``/readyz`` (is it
  allowed to take traffic at all?) and ``/metrics.json`` (the admission
  controller's live gauges: ``dl4j_serving_ewma_service_seconds``,
  ``dl4j_serving_queue_depth``, ``dl4j_serving_active``,
  ``dl4j_serving_waiters``) every ``DL4J_TPU_FLEET_POLL_S`` seconds.
  Malformed poll payloads degrade that replica to a *neutral* score and
  count a ``poll_error`` — junk JSON must never wedge scoring.
- A request for model M goes to the READY replica with the lowest
  expected drain time: ``(waiters + router-side in-flight) x EWMA
  service seconds``. Router-side in-flight counts dispatches the poller
  has not seen yet, so a burst does not pile onto one replica between
  polls.

Tail tolerance (Dean & Barroso, *The Tail at Scale*; the same shapes
Envoy ships as retry budgets + outlier detection):

- **Retry budget** (:class:`RetryBudget`): one fleet-wide token bucket
  (``DL4J_TPU_FLEET_RETRY_BUDGET``, default 0.2) that every failover
  AND every hedge draws from. Tokens accrue per primary dispatch, so
  extra attempts are bounded to ~20% of recent offered load (plus a
  small burst) — a sick fleet degrades to pass-through instead of
  amplifying its own overload with a retry storm. Budget exhausted ⇒
  dispatch count == request count.
- **Hedged requests**: an *idempotent* request (predict; never
  generate) still unanswered past the per-model hedge delay — the
  ``DL4J_TPU_FLEET_HEDGE_PCTL`` percentile of the router's own observed
  dispatch latencies — gets a second, budgeted attempt on a different
  replica. First non-503 answer wins; the loser is abandoned and
  counted (``outcome="abandoned"``).
- **Outlier ejection**: per-replica error-rate + latency-z-score over
  *actual dispatch outcomes* (``serving.resilience.DispatchStats``),
  not just ``/readyz`` polls — a zombie that polls healthy but fails
  traffic is caught here. An ejected replica leaves rotation with
  exponential backoff and re-admits via a single probe request; a
  max-ejection fraction stops the router from ejecting itself to zero,
  and when nothing scores as routable the router *panics* open (routes
  to any known non-ejected replica) rather than failing the request.
- **Failover** still retries replica-level failures — connection
  refused/reset, timeout, HTTP 503 — on a *different* replica, up to
  ``DL4J_TPU_FLEET_RETRIES`` (budget permitting); the failed replica is
  marked not-ready until a poll succeeds. Request-level outcomes
  (2xx/4xx/429) are the replica's answer and are returned as-is. A 503
  that cannot be retried is *passed through* with its ``Retry-After``
  intact instead of being flattened into :class:`NoReplicaError`.
- **Non-retryable mid-stream failures**: once a non-idempotent request
  (generate) has started consuming its response body, a connection
  reset surfaces as :class:`MidStreamError` carrying the trace id —
  never a silent duplicate generation.

Brownout (:class:`FleetServer`): when the fleet's ready fraction falls
below ``DL4J_TPU_FLEET_BROWNOUT_FRAC``, the front door sheds
lowest-priority traffic first (``X-Priority`` header 0–9, default
``DL4J_TPU_FLEET_DEFAULT_PRIORITY``) with 503 + ``Retry-After``, and
tightens forwarded deadlines in proportion to the capacity deficit.

Session affinity (prefix-cache locality): a request carrying a session
key — the ``X-Session-Id`` header, or for generates without one a
fingerprint of the prompt's leading tokens — is pinned to the replica
that owns the key on a consistent-hash ring (``affinity_vnodes``
virtual nodes per replica, so membership churn only remaps ~1/N of
sessions). Follow-up turns of a chat session therefore land on the
replica whose decode engine already holds the session's KV blocks in
its radix prefix cache (``runtime.generation``). Affinity is strictly
an *optimization*: when the ring owner is ejected, not ready, no
longer serving the model, or the fleet is browned out, the request
degrades to the normal least-loaded pick
(``dl4j_fleet_affinity_total{outcome="fallback"}``), and a failed
affine attempt fails over to least-loaded exactly like any other.
Generates are never hedged (they are non-idempotent), so an affine
generate never races a cold replica against the warm one.

Fault sites for drills (``common.faults``): ``fleet.dispatch`` (ctx
``url``/``model``/``phase``: ``connect`` = connection failure or slow
replica, ``body`` = truncated response / mid-stream reset) and
``fleet.poll`` (ctx ``url``).

Scale-out elasticity rides the warmup manifests of the serving layer: a
joining replica pointed at the shared manifest directory
(``DL4J_TPU_SERVING_MANIFEST_DIR`` / the executable-cache volume)
pre-bakes the fleet's observed bucket ladder during ``deploy()`` —
its ``/readyz`` stays false until the ladder is compiled, so
``add_replica()`` can be called *before* warmup finishes and the router
will not route to it until it is actually ready. With a fleet-shared
artifact store (``DL4J_TPU_REMOTE_CACHE``) the joiner *downloads* that
ladder instead of compiling it (``lifecycle.restore_on_boot()``).

Poll scheduling is jittered: each replica is polled on its own
deterministic phase within ``DL4J_TPU_FLEET_POLL_S`` (see
``poll_offset``) so N replicas don't all get probed on the same tick.

Telemetry: ``dl4j_fleet_replicas{model}``,
``dl4j_router_dispatch_total{replica,outcome}`` with outcome
``ok|failover|failed|passthrough|abandoned|no_replica``,
``dl4j_fleet_hedges_total{model,outcome}`` (``launched|won|suppressed``),
``dl4j_fleet_retry_tokens``, ``dl4j_fleet_budget_denials_total{reason}``,
``dl4j_fleet_ejections_total{replica,reason}``,
``dl4j_fleet_readmissions_total{replica}``, ``dl4j_fleet_ejected``,
``dl4j_fleet_poll_errors_total{replica,reason}``,
``dl4j_fleet_shed_total{model,priority}``, ``dl4j_fleet_brownout``,
``dl4j_fleet_ready_fraction``,
``dl4j_fleet_affinity_total{outcome}`` (``hit|fallback``).
"""
from __future__ import annotations

import json
import logging
import math
import queue
import re
import threading
import time
import bisect
import hashlib
import urllib.error
import urllib.request
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ...common import faults
from ...common.environment import environment
from ...common.locks import ordered_lock
from ...common.metrics import registry as metrics_registry
from ...common.tracing import (TraceContext, context_from_traceparent,
                               format_traceparent, new_span_id, span_tree,
                               tracer)
from ..resilience import DispatchStats, latency_zscore
from .aggregator import FleetAggregator

log = logging.getLogger(__name__)

#: admission gauges polled off every replica's /metrics.json; missing
#: series (a replica that has not served yet) default to 0.0
_POLLED_GAUGES = ("dl4j_serving_ewma_service_seconds",
                  "dl4j_serving_queue_depth",
                  "dl4j_serving_active",
                  "dl4j_serving_waiters")


class NoReplicaError(RuntimeError):
    """No ready replica could take the request (none ready, or every
    attempt hit a replica-level failure with the retry budget spent)."""


class MidStreamError(RuntimeError):
    """A non-idempotent request (generate) lost its connection AFTER the
    response body started streaming. Retrying would silently run the
    generation twice, so the failure surfaces instead, carrying the
    replica's trace id for correlation."""

    def __init__(self, replica_url: str, trace_id: Optional[str] = None,
                 cause: Optional[BaseException] = None):
        super().__init__(
            f"mid-stream failure from {replica_url}"
            + (f" (trace {trace_id})" if trace_id else "")
            + (f": {cause!r}" if cause else "")
            + "; not retried — the generation may have run")
        self.replica_url = replica_url
        self.trace_id = trace_id
        self.cause = cause


class RetryBudget:
    """Fleet-wide token bucket that every extra dispatch — failover
    retry or hedge — must draw from. Tokens accrue at ``ratio`` per
    *primary* dispatch up to a small ``burst`` cap, so extra attempts
    are bounded to ``ratio`` of recent offered load: under a fleet-wide
    failure the router degrades to pass-through instead of amplifying
    the overload. ``ratio`` 0 disables every extra dispatch. Not
    self-locking — the owning router serializes access."""

    def __init__(self, ratio: float, burst: Optional[float] = None):
        self.ratio = min(max(float(ratio), 0.0), 1.0)
        if burst is None:
            burst = max(1.0, self.ratio * 50.0)
        self.burst = float(burst) if self.ratio > 0 else 0.0
        self.tokens = self.burst

    def record_dispatch(self):
        self.tokens = min(self.tokens + self.ratio, self.burst)

    def try_spend(self) -> bool:
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def snapshot(self) -> Dict[str, float]:
        return {"ratio": self.ratio, "burst": self.burst,
                "tokens": round(self.tokens, 3)}


class Replica:
    """One fleet member: its URL, the last polled view of it, and its
    rolling dispatch-outcome window (the ejection evidence)."""

    def __init__(self, url: str, stats_window: int = 20):
        self.url = url.rstrip("/")
        self.ready = False
        self.models: List[str] = []          # models the replica serves
        #: per-model admission view: model -> {ewma_s, queue_depth,
        #: active, waiters}
        self.load: Dict[str, Dict[str, float]] = {}
        self.inflight = 0                    # router-side, un-polled yet
        self.dispatched = 0                  # lifetime routed attempts
        self.last_poll_s: Optional[float] = None
        self.consecutive_failures = 0
        # outlier-ejection state
        self.stats = DispatchStats(stats_window)
        self.ejected = False
        self.ejected_until = 0.0             # monotonic; probation opens
        self.eject_backoff_s = 0.0           # current backoff (0 = base)
        self.ejections = 0                   # lifetime ejection count
        self.probe_inflight = False          # the single re-admit probe

    def score(self, model: str) -> float:
        """Expected drain time of one more request on this replica:
        (admission backlog + router-side in-flight) x EWMA service
        seconds. Lower is better. A replica with no admission history
        yet (a fresh joiner) takes only the 1e-4 floor — routing to it
        is how the fleet learns its real EWMA."""
        view = self.load.get(model, {})
        ewma = float(view.get("ewma_s") or 0.0)
        backlog = float(view.get("waiters") or 0.0) + self.inflight
        return (backlog + 1.0) * max(ewma, 1e-4)

    def snapshot(self) -> Dict[str, Any]:
        return {"url": self.url, "ready": self.ready,
                "models": list(self.models),
                "load": {m: dict(v) for m, v in sorted(self.load.items())},
                "inflight": self.inflight,
                "dispatched": self.dispatched,
                "last_poll_s": self.last_poll_s,
                "consecutive_failures": self.consecutive_failures,
                "ejected": self.ejected,
                "ejections": self.ejections,
                "outcomes": self.stats.snapshot()}


def _parse_metrics_json(doc) -> Tuple[Dict[str, Dict[str, float]], int]:
    """``/metrics.json`` -> (model -> admission view, malformed-entry
    count). Tolerates missing families (a replica that has not admitted
    a request yet) and degrades junk entries — non-dict series,
    non-dict labels, unparseable or non-finite values — to neutral 0.0
    while counting them, so a garbage payload can never wedge scoring.
    A payload that is not a JSON object at all raises ValueError."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"/metrics.json answered non-object JSON "
            f"({type(doc).__name__})")
    out: Dict[str, Dict[str, float]] = {}
    malformed = 0
    short = {"dl4j_serving_ewma_service_seconds": "ewma_s",
             "dl4j_serving_queue_depth": "queue_depth",
             "dl4j_serving_active": "active",
             "dl4j_serving_waiters": "waiters"}
    for fam in _POLLED_GAUGES:
        entry = doc.get(fam)
        if entry is None:
            continue
        if not isinstance(entry, dict):
            malformed += 1
            continue
        series_list = entry.get("series", ())
        if not isinstance(series_list, (list, tuple)):
            malformed += 1
            continue
        for series in series_list:
            if not isinstance(series, dict):
                malformed += 1
                continue
            labels = series.get("labels")
            if not isinstance(labels, dict):
                malformed += 1
                continue
            model = labels.get("model")
            if model is None:
                continue
            try:
                value = float(series.get("value") or 0.0)
            except (TypeError, ValueError):
                malformed += 1
                value = 0.0
            if not math.isfinite(value):
                malformed += 1
                value = 0.0
            out.setdefault(str(model), {})[short[fam]] = value
    return out, malformed


class FleetRouter:
    """Least-loaded, readyz-aware, tail-tolerant request router over
    serving replicas.

    ``replicas`` are base URLs (``http://host:port``). Poll cadence,
    failover retries, per-attempt timeout, retry-budget ratio, hedge
    percentile, and brownout fraction default to the
    ``DL4J_TPU_FLEET_*`` env knobs; the ejection thresholds and
    ``affinity_vnodes`` (virtual nodes per replica on the session ring)
    are constructor-only (they are operator tuning, not deployment
    config).
    ``start_polling()`` runs the background refresh; tests can drive
    ``poll_once()`` directly."""

    def __init__(self, replicas: Sequence[str] = (), *,
                 poll_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 retry_budget: Optional[float] = None,
                 retry_burst: Optional[float] = None,
                 hedge_pctl: Optional[float] = None,
                 hedge_min_samples: int = 8,
                 brownout_frac: Optional[float] = None,
                 eject_window: int = 20,
                 eject_min_samples: int = 8,
                 eject_error_rate: float = 0.5,
                 eject_latency_z: float = 3.0,
                 eject_backoff_s: float = 5.0,
                 eject_max_backoff_s: float = 60.0,
                 eject_max_frac: float = 0.5,
                 affinity_vnodes: int = 64):
        env = environment()
        self.poll_s = env.fleet_poll_s() if poll_s is None else float(poll_s)
        self.retries = env.fleet_retries() if retries is None \
            else max(int(retries), 0)
        self.timeout_s = env.fleet_timeout_s() if timeout_s is None \
            else float(timeout_s)
        self.hedge_pctl = env.fleet_hedge_pctl() if hedge_pctl is None \
            else min(float(hedge_pctl), 100.0)
        self.hedge_min_samples = max(int(hedge_min_samples), 2)
        self.brownout_frac = env.fleet_brownout_frac() \
            if brownout_frac is None else min(max(float(brownout_frac),
                                                  0.0), 1.0)
        self.default_priority = env.fleet_default_priority()
        self.eject_window = max(int(eject_window), 1)
        self.eject_min_samples = max(int(eject_min_samples), 1)
        self.eject_error_rate = float(eject_error_rate)
        self.eject_latency_z = float(eject_latency_z)
        self.eject_backoff_s = max(float(eject_backoff_s), 0.01)
        self.eject_max_backoff_s = max(float(eject_max_backoff_s),
                                       self.eject_backoff_s)
        self.eject_max_frac = min(max(float(eject_max_frac), 0.0), 1.0)
        self._budget = RetryBudget(
            env.fleet_retry_budget() if retry_budget is None
            else retry_budget, retry_burst)
        #: fleet metrics aggregation rides the poll loop: every
        #: /metrics.json the poller fetches is folded into this
        self.aggregator = FleetAggregator()
        self._lock = ordered_lock("fleet.router")
        self._replicas: Dict[str, Replica] = {}
        self.affinity_vnodes = max(int(affinity_vnodes), 1)
        #: consistent-hash ring for session affinity: sorted
        #: ``(hash, url)`` vnode entries, rebuilt on membership change
        self._ring: List[Tuple[int, str]] = []
        #: per-model recent winner latencies (the hedge-delay basis)
        self._latencies: Dict[str, "list[float]"] = {}
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = metrics_registry()
        self._m_replicas = reg.gauge(
            "dl4j_fleet_replicas",
            "Ready replicas currently serving each model",
            labels=("model",))
        self._m_dispatch = reg.counter(
            "dl4j_router_dispatch_total",
            "Routed dispatch attempts by replica and outcome (ok|"
            "failover|failed|passthrough|abandoned|no_replica)",
            labels=("replica", "outcome"))
        self._m_hedges = reg.counter(
            "dl4j_fleet_hedges_total",
            "Hedged second attempts by outcome "
            "(launched|won|suppressed)",
            labels=("model", "outcome"))
        self._m_tokens = reg.gauge(
            "dl4j_fleet_retry_tokens",
            "Retry-budget tokens currently available to failovers "
            "and hedges")
        self._m_denials = reg.counter(
            "dl4j_fleet_budget_denials_total",
            "Extra dispatches refused by the retry budget (reason "
            "retry|hedge)",
            labels=("reason",))
        self._m_ejections = reg.counter(
            "dl4j_fleet_ejections_total",
            "Replica ejections by reason "
            "(error_rate|latency|probe_failed)",
            labels=("replica", "reason"))
        self._m_readmissions = reg.counter(
            "dl4j_fleet_readmissions_total",
            "Replicas re-admitted after a successful probe request",
            labels=("replica",))
        self._m_ejected = reg.gauge(
            "dl4j_fleet_ejected",
            "Replicas currently ejected from rotation")
        self._m_poll_errors = reg.counter(
            "dl4j_fleet_poll_errors_total",
            "Replica polls that failed or carried malformed payloads "
            "(reason unreachable|malformed)",
            labels=("replica", "reason"))
        self._m_shed = reg.counter(
            "dl4j_fleet_shed_total",
            "Requests shed by the brownout front door, by priority",
            labels=("model", "priority"))
        self._m_brownout = reg.gauge(
            "dl4j_fleet_brownout",
            "1 while the fleet front door is in brownout")
        self._m_ready_frac = reg.gauge(
            "dl4j_fleet_ready_fraction",
            "Fraction of known replicas ready and not ejected")
        self._m_affinity = reg.counter(
            "dl4j_fleet_affinity_total",
            "Session-affine routing decisions: hit = dispatched to the "
            "ring owner, fallback = owner unusable, degraded to "
            "least-loaded",
            labels=("outcome",))
        self._m_tokens.set(self._budget.tokens)
        for url in replicas:
            self.add_replica(url, poll=False)

    # -- membership -------------------------------------------------------
    def add_replica(self, url: str, *, poll: bool = True) -> Replica:
        """Register one replica. It takes traffic only once a poll sees
        its ``/readyz`` true — safe to call while the replica is still
        warming its bucket ladder from the shared manifest."""
        rep = Replica(url, stats_window=self.eject_window)
        with self._lock:
            existing = self._replicas.get(rep.url)
            if existing is not None:
                return existing
            self._replicas[rep.url] = rep
            self._rebuild_ring_locked()
        if poll:
            self._poll_replica(rep)
            self._update_fleet_gauge()
        return rep

    def remove_replica(self, url: str) -> bool:
        with self._lock:
            gone = self._replicas.pop(url.rstrip("/"), None) is not None
            if gone:
                self._rebuild_ring_locked()
        if gone:
            self.aggregator.forget(url)
            self._update_fleet_gauge()
        return gone

    def _rebuild_ring_locked(self):
        """Recompute the consistent-hash ring from current membership.
        Caller holds the lock. ``affinity_vnodes`` virtual nodes per
        replica keep the key space evenly spread and bound remap churn
        on membership change to ~1/N of sessions."""
        ring: List[Tuple[int, str]] = []
        for url in self._replicas:
            for v in range(self.affinity_vnodes):
                ring.append((zlib.crc32(f"{url}#{v}".encode()), url))
        ring.sort()
        self._ring = ring

    @staticmethod
    def session_hash(session_key: str) -> int:
        return zlib.crc32(session_key.encode())

    def affine_url(self, session_key: str) -> Optional[str]:
        """The ring owner for ``session_key`` — health-blind; routing
        applies the usability checks on top. Exposed for tests and the
        ``/fleet`` debug view."""
        h = self.session_hash(session_key)
        with self._lock:
            ring = self._ring
            if not ring:
                return None
            idx = bisect.bisect_left(ring, (h, ""))
            return ring[idx % len(ring)][1]

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def snapshot(self) -> Dict[str, Any]:
        """``/fleet`` debug view: every replica's polled state plus the
        budget and brownout posture."""
        with self._lock:
            budget = self._budget.snapshot()
        return {"poll_s": self.poll_s, "retries": self.retries,
                "budget": budget,
                "brownout": self.brownout_state(),
                "affinity": {"vnodes": self.affinity_vnodes,
                             "ring_size": len(self._ring)},
                "replicas": [r.snapshot() for r in self.replicas()]}

    # -- polling ----------------------------------------------------------
    def _fetch_json(self, url: str, timeout: float):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")

    def _poll_replica(self, rep: Replica):
        timeout = min(self.timeout_s, max(self.poll_s * 2, 1.0))
        try:
            if faults.active():
                faults.check("fleet.poll", url=rep.url)
            try:
                status, ready_doc = self._fetch_json(
                    rep.url + "/readyz", timeout)
            except urllib.error.HTTPError as e:
                # /readyz answers 503 with the same JSON body when unready
                status, ready_doc = e.code, json.loads(e.read() or b"{}")
            _, metrics_doc = self._fetch_json(
                rep.url + "/metrics.json", timeout)
            if not isinstance(ready_doc, dict):
                raise ValueError(
                    f"/readyz answered non-object JSON "
                    f"({type(ready_doc).__name__})")
        except (OSError, ValueError, faults.InjectedFault) as e:
            with self._lock:
                rep.ready = False
                rep.consecutive_failures += 1
                rep.last_poll_s = time.time()
            self._m_poll_errors.labels(replica=rep.url,
                                       reason="unreachable").inc()
            log.debug("poll of %s failed: %r", rep.url, e)
            return
        # the replica is reachable and its readiness is known; a junk
        # /metrics.json only costs it its load view (neutral score),
        # never its place in rotation
        try:
            load, malformed = _parse_metrics_json(metrics_doc)
        except ValueError as e:
            load, malformed = {}, 1
            log.debug("junk /metrics.json from %s: %r", rep.url, e)
        self.aggregator.ingest(rep.url, metrics_doc)
        if malformed:
            self._m_poll_errors.labels(replica=rep.url,
                                       reason="malformed").inc()
        models = ready_doc.get("models")
        with self._lock:
            rep.ready = status == 200 and bool(ready_doc.get("ready"))
            rep.models = sorted(models.keys()) \
                if isinstance(models, dict) else []
            rep.load = load
            rep.consecutive_failures = 0
            rep.last_poll_s = time.time()

    def poll_once(self):
        """One synchronous refresh of every replica (tests; the poll
        thread spreads the same work across the period instead)."""
        for rep in self.replicas():
            self._poll_replica(rep)
        self._update_fleet_gauge()

    def poll_offset(self, url: str) -> float:
        """Deterministic per-replica phase within the poll period,
        ``[0, poll_s)``: each replica's first scheduled poll is delayed
        by this much so N replicas spread over the window instead of
        being probed in one thundering-herd tick (and, fleet-wide, N
        routers hash the same replica to the same phase rather than all
        re-synchronizing on their own start times). Hash, not index, so
        an offset never changes as membership churns."""
        return (zlib.crc32(url.rstrip("/").encode("utf-8")) % 9973) \
            / 9973.0 * self.poll_s

    def _update_fleet_gauge(self):
        counts: Dict[str, int] = {}
        with self._lock:
            reps = list(self._replicas.values())
            ejected = sum(1 for r in reps if r.ejected)
            for rep in reps:
                if not rep.ready or rep.ejected:
                    continue
                for model in rep.models:
                    counts[model] = counts.get(model, 0) + 1
            known = set()
            for rep in reps:
                known.update(rep.models)
        for model in known:
            self._m_replicas.labels(model=model).set(counts.get(model, 0))
        self._m_ejected.set(ejected)

    def start_polling(self) -> "FleetRouter":
        if self._poll_thread is not None:
            return self
        self._stop.clear()

        def loop():
            # each replica keeps its own next-poll deadline, first seen
            # at now + poll_offset(url): distinct phases per replica,
            # full poll_s cadence each thereafter
            due: Dict[str, float] = {}
            while not self._stop.is_set():
                now = time.monotonic()
                polled = False
                for rep in self.replicas():
                    when = due.get(rep.url)
                    if when is None:
                        when = now + self.poll_offset(rep.url)
                        due[rep.url] = when
                    if when > now:
                        continue
                    try:
                        self._poll_replica(rep)
                    except Exception:
                        log.exception("fleet poll of %s failed", rep.url)
                    due[rep.url] = now + self.poll_s
                    polled = True
                if polled:
                    self._update_fleet_gauge()
                with self._lock:
                    live = set(self._replicas)
                for url in list(due):
                    if url not in live:
                        del due[url]
                now = time.monotonic()
                next_due = min(due.values(), default=now + self.poll_s)
                self._stop.wait(max(min(next_due - now, self.poll_s), 0.01))

        self._poll_thread = threading.Thread(
            target=loop, name="dl4j-tpu-fleet-poll", daemon=True)
        self._poll_thread.start()
        return self

    def stop_polling(self):
        self._stop.set()
        t = self._poll_thread
        if t is not None:
            t.join(timeout=max(self.poll_s * 2, 2.0))
            self._poll_thread = None

    # -- hedge-delay basis ------------------------------------------------
    def _note_latency(self, model: str, latency_s: float):
        with self._lock:
            samples = self._latencies.setdefault(model, [])
            samples.append(latency_s)
            if len(samples) > 64:
                del samples[:len(samples) - 64]

    def _hedge_delay(self, model: Optional[str]) -> Optional[float]:
        """The per-model hedge delay: the ``hedge_pctl`` percentile of
        observed winner latencies. None (no hedging) until enough
        samples exist or when hedging is disabled."""
        if model is None or self.hedge_pctl <= 0:
            return None
        with self._lock:
            samples = sorted(self._latencies.get(model, ()))
        if len(samples) < self.hedge_min_samples:
            return None
        idx = min(len(samples) - 1,
                  max(0, math.ceil(self.hedge_pctl / 100.0
                                   * len(samples)) - 1))
        return max(samples[idx], 0.001)

    # -- outlier ejection -------------------------------------------------
    def _settle_attempt(self, rep: Replica, *, ok: bool,
                        latency_s: Optional[float], probe: bool):
        """Book one finished dispatch attempt against the replica's
        rolling outcome window; resolve a probe; evaluate ejection.
        Metric writes happen after the lock drops."""
        events: List[Tuple[str, str]] = []
        with self._lock:
            rep.inflight = max(rep.inflight - 1, 0)
            rep.stats.record(ok, latency_s)
            if probe:
                rep.probe_inflight = False
                if ok:
                    rep.ejected = False
                    rep.eject_backoff_s = 0.0
                    rep.stats.reset()
                    events.append(("readmitted", ""))
                else:
                    self._eject_locked(rep, "probe_failed")
                    events.append(("ejected", "probe_failed"))
            elif not rep.ejected:
                reason = self._eject_reason_locked(rep)
                if reason is not None:
                    self._eject_locked(rep, reason)
                    events.append(("ejected", reason))
        for what, reason in events:
            if what == "readmitted":
                self._m_readmissions.labels(replica=rep.url).inc()
                log.info("replica %s re-admitted after probe", rep.url)
            else:
                self._m_ejections.labels(replica=rep.url,
                                         reason=reason).inc()
                log.warning("replica %s ejected (%s), backoff %.2fs",
                            rep.url, reason, rep.eject_backoff_s)
        if events:
            self._update_fleet_gauge()

    def _eject_reason_locked(self, rep: Replica) -> Optional[str]:
        """Why ``rep`` should be ejected right now, or None. Caller
        holds the lock. Honors the max-ejection fraction: the router
        must never eject itself to zero."""
        if len(rep.stats) < self.eject_min_samples:
            return None
        reason = None
        if rep.stats.error_rate() >= self.eject_error_rate:
            reason = "error_rate"
        else:
            mean = rep.stats.mean_latency_s()
            if mean is not None:
                peers = [r.stats.mean_latency_s()
                         for r in self._replicas.values()
                         if r is not rep and not r.ejected
                         and len(r.stats) >= self.eject_min_samples]
                if latency_zscore(mean, peers) >= self.eject_latency_z:
                    reason = "latency"
        if reason is None:
            return None
        total = len(self._replicas)
        already = sum(1 for r in self._replicas.values() if r.ejected)
        if total and (already + 1) / total > self.eject_max_frac:
            log.warning("replica %s looks like an outlier (%s) but the "
                        "max-ejection fraction %.2f is spent",
                        rep.url, reason, self.eject_max_frac)
            return None
        return reason

    def _eject_locked(self, rep: Replica, reason: str):
        rep.ejected = True
        rep.ejections += 1
        rep.eject_backoff_s = min(
            self.eject_backoff_s if rep.eject_backoff_s <= 0
            else rep.eject_backoff_s * 2.0,
            self.eject_max_backoff_s)
        rep.ejected_until = time.monotonic() + rep.eject_backoff_s

    # -- routing ----------------------------------------------------------
    def _candidates(self, model: Optional[str]) -> List[Replica]:
        """READY, non-ejected replicas (serving ``model``, when known),
        best score first."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.ready and not r.ejected]
        if model is not None:
            serving = [r for r in reps if model in r.models]
            # a replica whose model list is unknown yet (no successful
            # poll since deploy) still counts — the attempt will 404
            # and surface the truth
            reps = serving or reps
        if model is not None:
            # dispatched count breaks score ties: equally loaded
            # replicas round-robin instead of piling onto the first
            reps.sort(key=lambda r: (r.score(model), r.dispatched, r.url))
        return reps

    def _affine_replica(self, model: Optional[str],
                        session_key: str) -> Optional[Replica]:
        """The ring owner for ``session_key`` iff it is usable right
        now: ready, not ejected, serving ``model`` (an unknown model
        list still counts, mirroring ``_candidates``), and the fleet
        not browned out — a browned-out fleet routes for capacity, not
        cache locality. None means: degrade to least-loaded."""
        if self.brownout_state()["active"]:
            return None
        url = self.affine_url(session_key)
        if url is None:
            return None
        with self._lock:
            rep = self._replicas.get(url)
            if rep is None or not rep.ready or rep.ejected:
                return None
            if model is not None and rep.models and model not in rep.models:
                return None
            return rep

    def _pick(self, model: Optional[str], exclude: Sequence[str],
              strict: bool = False) -> Tuple[Optional[Replica], bool]:
        """Next replica for an attempt, ``(replica, is_probe)``. An
        ejected replica whose backoff expired gets exactly one probe
        request — this one. When nothing scores as routable, panic
        open: any known non-ejected replica beats failing the request
        outright (the attempt will surface the truth), and — unless
        ``strict`` — a failover may even re-try an already-tried
        replica as a last resort (a transient connect fault draws
        independently on the second attempt). Hedges are ``strict``:
        a hedge on the same replica measures nothing."""
        now = time.monotonic()
        with self._lock:
            probe = next(
                (r for r in self._replicas.values()
                 if r.ejected and not r.probe_inflight
                 and now >= r.ejected_until and r.url not in exclude),
                None)
            if probe is not None:
                probe.probe_inflight = True
                return probe, True
        rep = next((r for r in self._candidates(model)
                    if r.url not in exclude), None)
        if rep is not None:
            return rep, False
        with self._lock:
            panic = [r for r in self._replicas.values()
                     if not r.ejected and r.url not in exclude]
            if not panic and not strict:
                panic = [r for r in self._replicas.values()
                         if not r.ejected]
        panic.sort(key=lambda r: (r.consecutive_failures, r.dispatched,
                                  r.url))
        return (panic[0] if panic else None), False

    def _do_http(self, rep: Replica, method: str, path: str,
                 body: Optional[bytes], headers: Sequence[Tuple[str, str]],
                 timeout: float, model: Optional[str]):
        """One HTTP attempt. Returns ``(kind, payload)``:
        ``("response", (status, hdrs, body))``, ``("conn_error", exc)``
        (nothing consumed — retryable), or
        ``("mid_stream", (hdrs, exc))`` (response body partially
        consumed — retryable only for idempotent requests)."""
        resp = None
        try:
            if faults.active():
                faults.check("fleet.dispatch", url=rep.url, model=model,
                             phase="connect")
            req = urllib.request.Request(
                rep.url + path, data=body, method=method,
                headers=dict(headers))
            try:
                resp = urllib.request.urlopen(req, timeout=timeout)
                status = resp.status
            except urllib.error.HTTPError as e:
                resp, status = e, e.code
            hdrs = dict(resp.headers)
        except (OSError, urllib.error.URLError, faults.InjectedFault) as e:
            return "conn_error", e
        try:
            if faults.active():
                faults.check("fleet.dispatch", url=rep.url, model=model,
                             phase="body")
            payload = resp.read()
        except (OSError, faults.InjectedFault) as e:
            return "mid_stream", (hdrs, e)
        finally:
            try:
                resp.close()
            except Exception:
                pass
        return "response", (status, hdrs, payload)

    def _attempt(self, rep: Replica, method: str, path: str,
                 body: Optional[bytes], headers: Sequence[Tuple[str, str]],
                 timeout: float, model: Optional[str], meta: Dict[str, Any],
                 resq: "queue.Queue", race: Dict[str, bool],
                 race_lock: threading.Lock):
        kind, res = self._do_http(rep, method, path, body, headers,
                                  timeout, model)
        with race_lock:
            if not race["done"]:
                resq.put((rep, kind, res, meta))
                return
        # the race already settled while this attempt was in flight:
        # the loser accounts for itself
        self._account_abandoned(rep, kind, res, meta)

    def _record_attempt(self, rep: Replica, meta: Dict[str, Any],
                        outcome: str):
        """Record this attempt's ``fleet/attempt`` span cross-thread
        into the front door's trace ring, under the request's
        :class:`TraceContext` — with the SAME span id the attempt
        announced downstream in ``traceparent``, so the replica's
        server-side subtree parents under the exact attempt that
        reached it when :meth:`stitched_trace` joins the two rings.
        Runs on whatever thread settles the attempt (the route loop for
        the winner, the attempt worker itself for an abandoned hedge
        loser) — ``record(context=)`` is the cross-thread-safe path."""
        ctx = meta.get("ctx")
        if ctx is None:
            return
        tracer().record("fleet/attempt", meta.get("pt0", 0.0),
                        time.perf_counter(), context=ctx,
                        span_id=meta.get("span_id"), replica=rep.url,
                        kind=meta.get("kind", ""), outcome=outcome)

    def _account_abandoned(self, rep: Replica, kind: str, res,
                           meta: Dict[str, Any]):
        latency = time.monotonic() - meta["t0"]
        ok = kind == "response" and res[0] != 503
        self._settle_attempt(rep, ok=ok,
                             latency_s=latency if ok else None,
                             probe=meta["probe"])
        if not ok:
            why = "503" if kind == "response" else kind
            self._note_replica_failure(rep, why)
        self._record_attempt(rep, meta, "abandoned")
        self._m_dispatch.labels(replica=rep.url, outcome="abandoned").inc()

    def _note_replica_failure(self, rep: Replica, why: str):
        with self._lock:
            rep.ready = False
            rep.consecutive_failures += 1
        log.warning("replica %s failed (%s)", rep.url, why)
        self._update_fleet_gauge()

    def route(self, method: str, path: str, body: Optional[bytes] = None,
              headers: Sequence[Tuple[str, str]] = (),
              model: Optional[str] = None,
              timeout_s: Optional[float] = None,
              idempotent: Optional[bool] = None,
              session_key: Optional[str] = None
              ) -> Tuple[int, Dict[str, str], bytes, str]:
        """Route one HTTP request to the best replica with budgeted
        failover and (for idempotent requests) a budgeted hedge.
        Returns ``(status, headers, body, replica_url)``. A 503 that
        cannot be retried is returned as-is (``Retry-After``
        preserved); :class:`NoReplicaError` is raised only when no
        replica produced an HTTP answer at all; a mid-stream failure on
        a non-idempotent request raises :class:`MidStreamError` instead
        of retrying. ``idempotent`` defaults from the path: generate is
        not, everything else is. ``session_key`` requests prefix-cache
        affinity: the first attempt goes to the key's consistent-hash
        ring owner when that replica is usable
        (``dl4j_fleet_affinity_total{outcome="hit"}``), else — or on
        failover after the affine attempt fails — the normal
        least-loaded pick applies (``outcome="fallback"``)."""
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        if idempotent is None:
            idempotent = not path.split("?", 1)[0].endswith("/generate")
        # the request's trace context: the client's traceparent when one
        # arrived, else a fresh root minted here — either way every
        # attempt records a fleet/attempt span under it, and forwards
        # its OWN span id downstream so the replica's subtree nests
        ctx = context_from_traceparent(
            next((v for k, v in headers
                  if str(k).lower() == "traceparent"), None))
        base_headers = [(k, v) for k, v in headers
                        if str(k).lower() not in ("traceparent",
                                                  "x-fleet-replica",
                                                  "x-fleet-attempt")]
        resq: "queue.Queue" = queue.Queue()
        race = {"done": False}
        race_lock = threading.Lock()
        tried: List[str] = []
        inflight = 0
        failovers = 0
        hedged = False
        hedge_blocked = not idempotent
        first_kind = "primary"
        last_503: Optional[Tuple[int, Dict[str, str], bytes, str]] = None
        last_err: Optional[BaseException] = None

        def start(rep: Replica, probe: bool, hedge: bool):
            nonlocal inflight
            kind = "hedge" if hedge else ("retry" if tried else first_kind)
            tried.append(rep.url)
            with self._lock:
                rep.inflight += 1
                rep.dispatched += 1
            sid = new_span_id()
            hdrs = list(base_headers)
            hdrs.append(("traceparent", format_traceparent(
                TraceContext(ctx.trace_id, sid))))
            hdrs.append(("X-Fleet-Replica", rep.url))
            hdrs.append(("X-Fleet-Attempt", kind))
            meta = {"probe": probe, "hedge": hedge, "t0": time.monotonic(),
                    "pt0": time.perf_counter(), "ctx": ctx,
                    "span_id": sid, "kind": kind}
            threading.Thread(
                target=self._attempt,
                args=(rep, method, path, body, hdrs, timeout, model,
                      meta, resq, race, race_lock),
                name="dl4j-tpu-fleet-attempt", daemon=True).start()
            inflight += 1

        def finish():
            with race_lock:
                race["done"] = True
            # drain results that were queued before the race settled
            while True:
                try:
                    orep, okind, ores, ometa = resq.get_nowait()
                except queue.Empty:
                    return
                self._account_abandoned(orep, okind, ores, ometa)

        rep, probe = None, False
        if session_key is not None:
            rep = self._affine_replica(model, session_key)
            self._m_affinity.labels(
                outcome="hit" if rep is not None else "fallback").inc()
            if rep is None:
                first_kind = "affinity_fallback"
        if rep is None:
            rep, probe = self._pick(model, tried)
        if rep is None:
            self._m_dispatch.labels(replica="", outcome="no_replica").inc()
            raise NoReplicaError(
                "no ready replica"
                + (f" for model '{model}'" if model else ""))
        with self._lock:
            self._budget.record_dispatch()
            tokens = self._budget.tokens
        self._m_tokens.set(tokens)
        start(rep, probe, hedge=False)
        hedge_delay = self._hedge_delay(model) if idempotent else None
        hedge_at = None if hedge_delay is None \
            else time.monotonic() + hedge_delay

        while inflight:
            wait = None
            if hedge_at is not None and not hedged and not hedge_blocked \
                    and inflight == 1:
                wait = max(hedge_at - time.monotonic(), 0.0)
            try:
                rep, kind, res, meta = resq.get(timeout=wait)
            except queue.Empty:
                # hedge timer fired with the primary still unanswered
                cand, cprobe = self._pick(model, tried, strict=True)
                if cand is None:
                    hedge_blocked = True
                    continue
                with self._lock:
                    granted = self._budget.try_spend()
                    tokens = self._budget.tokens
                    if not granted and cprobe:
                        cand.probe_inflight = False  # return the slot
                self._m_tokens.set(tokens)
                if not granted:
                    hedge_blocked = True
                    self._m_denials.labels(reason="hedge").inc()
                    self._m_hedges.labels(model=model or "",
                                          outcome="suppressed").inc()
                    continue
                hedged = True
                self._m_hedges.labels(model=model or "",
                                      outcome="launched").inc()
                start(cand, cprobe, hedge=True)
                continue
            inflight -= 1
            latency = time.monotonic() - meta["t0"]

            if kind == "response":
                status, hdrs, payload = res
                if status != 503:
                    # the replica's answer — the race winner
                    self._settle_attempt(
                        rep, ok=True,
                        latency_s=latency if status < 300 else None,
                        probe=meta["probe"])
                    if status < 300 and model is not None:
                        self._note_latency(model, latency)
                    self._record_attempt(rep, meta, "ok")
                    finish()
                    self._m_dispatch.labels(replica=rep.url,
                                            outcome="ok").inc()
                    if meta["hedge"]:
                        self._m_hedges.labels(model=model or "",
                                              outcome="won").inc()
                    return status, hdrs, payload, rep.url
                # 503 is replica-level (draining / breaker / unready):
                # keep its Retry-After in hand for pass-through
                last_503 = (status, hdrs, payload, rep.url)
                last_err = None
                self._settle_attempt(rep, ok=False, latency_s=None,
                                     probe=meta["probe"])
                self._note_replica_failure(rep, "503")
                self._record_attempt(rep, meta, "503")
            elif kind == "mid_stream":
                hdrs, err = res
                self._settle_attempt(rep, ok=False, latency_s=None,
                                     probe=meta["probe"])
                self._note_replica_failure(rep, "mid_stream")
                self._record_attempt(rep, meta, "mid_stream")
                if not idempotent:
                    # the response body started; a retry could run the
                    # generation twice — surface instead
                    finish()
                    self._m_dispatch.labels(replica=rep.url,
                                            outcome="failed").inc()
                    raise MidStreamError(
                        rep.url,
                        trace_id=hdrs.get("X-Trace-Id")
                        or hdrs.get("x-trace-id"),
                        cause=err)
                last_err = err
            else:  # conn_error: nothing reached the replica's handler
                last_err = res
                self._settle_attempt(rep, ok=False, latency_s=None,
                                     probe=meta["probe"])
                self._note_replica_failure(rep, "connect")
                self._record_attempt(rep, meta, "conn_error")

            # a sibling attempt may still win the race
            if inflight:
                self._m_dispatch.labels(replica=rep.url,
                                        outcome="failover").inc()
                continue
            # failover, budget and candidates permitting
            if failovers < self.retries:
                cand, cprobe = self._pick(model, tried)
                if cand is not None:
                    with self._lock:
                        granted = self._budget.try_spend()
                        tokens = self._budget.tokens
                        if not granted and cprobe:
                            cand.probe_inflight = False  # return the slot
                    self._m_tokens.set(tokens)
                    if granted:
                        failovers += 1
                        self._m_dispatch.labels(replica=rep.url,
                                                outcome="failover").inc()
                        start(cand, cprobe, hedge=False)
                        continue
                    self._m_denials.labels(reason="retry").inc()
            # terminal: no retry possible for this failed attempt
            finish()
            if last_503 is not None:
                # degrade to pass-through: the replica's own 503 (with
                # its Retry-After) beats a synthesized error
                self._m_dispatch.labels(
                    replica=rep.url,
                    outcome="passthrough" if kind == "response"
                    else "failed").inc()
                return last_503
            self._m_dispatch.labels(replica=rep.url,
                                    outcome="failed").inc()
            raise NoReplicaError(
                f"all routed attempts failed (tried {tried})"
                + (f": {last_err!r}" if last_err else ""))
        raise NoReplicaError(  # pragma: no cover — loop always resolves
            f"all routed attempts failed (tried {tried})")

    # -- brownout ---------------------------------------------------------
    def brownout_state(self) -> Dict[str, Any]:
        """The front door's degradation posture. Brownout turns on when
        the fraction of known replicas that are ready and not ejected
        drops below ``brownout_frac``; the priority cutoff and the
        forwarded-deadline scale both deepen with the capacity
        deficit."""
        with self._lock:
            reps = list(self._replicas.values())
        known = len(reps)
        ready = sum(1 for r in reps if r.ready and not r.ejected)
        frac = (ready / known) if known else 0.0
        limit = self.brownout_frac
        active = limit > 0 and frac < limit
        if active:
            ratio = frac / limit                      # [0, 1)
            cutoff = min(math.ceil(10.0 * (1.0 - ratio)), 10)
            timeout_scale = max(ratio, 0.25)
        else:
            cutoff = 0
            timeout_scale = 1.0
        self._m_brownout.set(1.0 if active else 0.0)
        self._m_ready_frac.set(frac)
        return {"active": active, "ready_fraction": round(frac, 4),
                "cutoff": cutoff, "timeout_scale": round(timeout_scale, 4),
                "retry_after_s": max(int(math.ceil(self.poll_s)), 1),
                "default_priority": self.default_priority}

    def count_shed(self, model: Optional[str], priority: int):
        self._m_shed.labels(model=model or "",
                            priority=str(priority)).inc()

    # -- cross-replica trace stitching ------------------------------------
    def stitched_trace(self, trace_id: str) -> Dict[str, Any]:
        """One cross-process span tree for ``trace_id``: the front
        door's own ``fleet/attempt`` spans plus every involved
        replica's ``/debug/trace/<id>`` events, nested by span ids.
        Each attempt forwarded its OWN span id downstream in
        ``traceparent``, so a replica's server-side
        ``serving/request`` → admission → dispatch subtree hangs under
        the exact attempt that reached it — a hedged request renders as
        ONE trace with both attempts and the winner's full subtree.
        Replicas named by local attempt spans are asked first; with no
        local evidence (ring rolled over, or another front door served
        the request) every known replica is asked. An unreachable
        replica just contributes nothing — stitching is best-effort."""
        trc = tracer()
        local = [e for e in trc.events_for(trace_id)
                 if isinstance(e.get("args"), dict)]
        urls = sorted({e["args"].get("replica") for e in local
                       if e.get("name") == "fleet/attempt"
                       and e["args"].get("replica")})
        if not urls:
            urls = sorted(r.url for r in self.replicas())
        events = list(local)
        stitched_from: List[str] = []
        timeout = min(self.timeout_s, max(self.poll_s * 2, 1.0))
        for url in urls:
            try:
                _, doc = self._fetch_json(
                    url + "/debug/trace/" + trace_id, timeout)
            except (OSError, ValueError):
                continue
            remote = doc.get("events") if isinstance(doc, dict) else None
            if isinstance(remote, list) and remote:
                stitched_from.append(url)
                events.extend(e for e in remote if isinstance(e, dict))
        # dedup by span id: an in-process fleet (tests, benches) shares
        # one tracer ring, so the "remote" fetch returns spans the local
        # scan already collected — one node per span keeps the tree sane
        seen: set = set()
        deduped = []
        for e in events:
            sid = e.get("args", {}).get("span_id") \
                if isinstance(e.get("args"), dict) else None
            if sid is not None:
                if sid in seen:
                    continue
                seen.add(sid)
            deduped.append(e)
        events = deduped
        return {"trace_id": trace_id, "count": len(events),
                "replicas": stitched_from, "tree": span_tree(events),
                "events": events}

    # -- autoscaler signal feed -------------------------------------------
    def fleet_signals(self) -> Dict[str, Any]:
        """``GET /fleet/signals``: the aggregator's latest per-replica
        autoscaling signals joined with the router's own membership
        view (ready/ejected/inflight) and brownout posture, plus the
        fleet rollup — the documented feed for ROADMAP item 3's
        SLO-driven autoscaler."""
        with self._lock:
            state = {r.url: {"ready": r.ready, "ejected": r.ejected,
                             "inflight": r.inflight,
                             "models": list(r.models)}
                     for r in self._replicas.values()}
        return self.aggregator.signals(replica_state=state,
                                       brownout=self.brownout_state())

    # -- convenience client API -------------------------------------------
    def predict(self, model: str, inputs, *,
                timeout_s: Optional[float] = None,
                session_key: Optional[str] = None) -> dict:
        """JSON predict against the least-loaded replica; returns the
        parsed response body. Non-2xx answers raise RuntimeError with
        the replica's error payload."""
        body = json.dumps({"inputs": inputs if isinstance(inputs, (dict,
                           list)) else inputs.tolist()}).encode()
        status, _, payload, url = self.route(
            "POST", f"/v1/models/{model}/predict", body,
            headers=[("Content-Type", "application/json")],
            model=model, timeout_s=timeout_s, idempotent=True,
            session_key=session_key)
        doc = json.loads(payload or b"{}")
        if status != 200:
            raise RuntimeError(
                f"predict on {url} answered {status}: {doc.get('error')}")
        return doc

    def generate(self, model: str, prompt: Sequence[int], *,
                 timeout_s: Optional[float] = None,
                 session_key: Optional[str] = None, **opts) -> dict:
        """Generate with optional session affinity: pass the same
        ``session_key`` on every turn of a chat session and follow-up
        turns land on the replica whose prefix cache holds the
        session's KV blocks. Omitted, the key defaults to a
        fingerprint of the prompt's leading tokens, which pins shared
        system-prompt storms the same way."""
        if session_key is None:
            session_key = prompt_fingerprint(model, prompt)
        body = json.dumps({"prompt": list(prompt), **opts}).encode()
        status, _, payload, url = self.route(
            "POST", f"/v1/models/{model}/generate", body,
            headers=[("Content-Type", "application/json")],
            model=model, timeout_s=timeout_s, idempotent=False,
            session_key=session_key)
        doc = json.loads(payload or b"{}")
        if status != 200:
            raise RuntimeError(
                f"generate on {url} answered {status}: {doc.get('error')}")
        return doc


_MODEL_PATH_RE = re.compile(r"^/v1/models/([^/:]+)(?::[^/]+)?/")

#: how many leading prompt tokens the fallback session fingerprint
#: covers — enough to separate distinct system prompts, short enough
#: that every turn of a growing session keeps hashing the same head
_FINGERPRINT_TOKENS = 32

#: request headers the front door forwards to the replica (trace context,
#: deadlines, priority, and the session key must survive the hop;
#: hop-by-hop headers must not)
_FORWARDED_HEADERS = ("content-type", "traceparent", "x-request-timeout-s",
                      "x-priority", "x-session-id")


def prompt_fingerprint(model: Optional[str],
                       prompt: Sequence[int]) -> str:
    """Session key derived from a prompt's leading tokens: requests
    sharing a system prompt (or earlier turns of the same session)
    hash identically and therefore pin to the same replica."""
    head = ",".join(str(int(t)) for t in list(prompt)[:_FINGERPRINT_TOKENS])
    digest = hashlib.sha1(f"{model or ''}|{head}".encode()).hexdigest()
    return f"pfx:{digest}"


def _parse_priority(raw: Optional[str], default: int) -> int:
    if raw is None:
        return default
    try:
        return min(max(int(str(raw).strip()), 0), 9)
    except ValueError:
        return default


class FleetServer:
    """HTTP front door over a :class:`FleetRouter`: the one URL clients
    talk to. ``POST /v1/models/...`` proxies to the least-loaded ready
    replica (with budgeted failover + hedging); ``GET /v1/models``
    answers from the best replica; ``/readyz`` is the *fleet's*
    readiness (any replica ready) plus its brownout posture; ``/fleet``
    is the router's polled membership + budget view; ``/metrics`` +
    ``/metrics.json`` serve the router process's own registry (dispatch
    counters + fleet gauges) COMBINED with the aggregated replica
    registries — per-replica series carry a ``replica`` label, merged
    series none (see :mod:`.aggregator`); ``/fleet/signals`` is the
    distilled autoscaler feed; ``/debug/trace/<id>`` (debug-gated like
    every ``/debug/*``) answers the cross-replica stitched span tree.

    During brownout the front door sheds POSTs whose ``X-Priority``
    (0–9, default ``DL4J_TPU_FLEET_DEFAULT_PRIORITY``) falls below the
    capacity-scaled cutoff — 503 with ``Retry-After`` and
    ``X-Fleet-Brownout: 1`` — and tightens the forwarded
    ``X-Request-Timeout-S`` so queued work inside the degraded fleet
    gives up sooner.

    Clients that want prefix-cache locality send ``X-Session-Id`` (any
    stable opaque string per chat session); generates without one are
    keyed by a fingerprint of the prompt's leading tokens. Either way
    the request pins to the session's ring owner when that replica is
    healthy — see :class:`FleetRouter` session affinity."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        from ...common.httpserver import QuietThreadingHTTPServer
        self._httpd = QuietThreadingHTTPServer((self.host, self.port),
                                               self._handler())
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-fleet-router",
                                        daemon=True)
        self._thread.start()
        self.router.start_polling()
        log.info("fleet router on %s:%d fronting %d replicas",
                 self.host, self.port, len(self.router.replicas()))
        return self.port

    def stop(self):
        self.router.stop_polling()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        return self

    def _handler(self):
        from ...common.httpserver import JsonRequestHandler, debug_enabled
        from ...common.metrics import touch_runtime_info
        from .aggregator import render_prometheus_text
        router = self.router

        class Handler(JsonRequestHandler):
            def _fleet_exposition(self):
                """Front-door registry + aggregated replica registries
                in one /metrics.json-shaped document."""
                return router.aggregator.merged_with(
                    touch_runtime_info().snapshot())

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    self.send_payload(b"ok", "text/plain")
                elif path == "/readyz":
                    reps = router.replicas()
                    ready = any(r.ready for r in reps)
                    self.send_json(
                        {"ready": ready,
                         "brownout": router.brownout_state(),
                         "replicas": [{"url": r.url, "ready": r.ready,
                                       "ejected": r.ejected}
                                      for r in reps]},
                        200 if ready else 503)
                elif path == "/fleet":
                    self.send_json(router.snapshot())
                elif path == "/fleet/signals":
                    self.send_json(router.fleet_signals())
                elif path == "/metrics":
                    self.send_payload(
                        render_prometheus_text(
                            self._fleet_exposition()).encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/metrics.json":
                    self.send_payload(
                        json.dumps(self._fleet_exposition()).encode(),
                        "application/json")
                elif path.startswith("/debug/trace/") and debug_enabled():
                    self.send_json(router.stitched_trace(
                        path[len("/debug/trace/"):].strip("/")))
                elif path == "/v1/models":
                    self._proxy("GET", None)
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                m = _MODEL_PATH_RE.match(path)
                if m is None:
                    self.send_json({"error": "not found"}, 404)
                    return
                self._proxy("POST", m.group(1))

            def _proxy(self, method: str, model: Optional[str]):
                body = self.read_body() if method == "POST" else None
                fwd = [(k, v) for k, v in self.headers.items()
                       if k.lower() in _FORWARDED_HEADERS]
                brown = router.brownout_state()
                if method == "POST" and brown["active"]:
                    prio = _parse_priority(self.headers.get("X-Priority"),
                                           brown["default_priority"])
                    if prio < brown["cutoff"]:
                        router.count_shed(model, prio)
                        self.send_json(
                            {"error": "brownout: fleet capacity at "
                             f"{brown['ready_fraction']:.0%}, shedding "
                             f"priority < {brown['cutoff']}",
                             "priority": prio},
                            503,
                            headers=[("Retry-After",
                                      str(brown["retry_after_s"])),
                                     ("X-Fleet-Brownout", "1")])
                        return
                    # tighten the forwarded deadline: a browned-out
                    # fleet must not queue work it cannot finish
                    base = None
                    for k, v in fwd:
                        if k.lower() == "x-request-timeout-s":
                            try:
                                base = float(v)
                            except ValueError:
                                base = None
                    if base is None:
                        base = environment().serving_default_timeout_s() \
                            or router.timeout_s
                    tightened = max(base * brown["timeout_scale"], 0.1)
                    fwd = [(k, v) for k, v in fwd
                           if k.lower() != "x-request-timeout-s"]
                    fwd.append(("X-Request-Timeout-S",
                                f"{tightened:.3f}"))
                path = self.path.split("?", 1)[0]
                idempotent = not path.endswith("/generate")
                session_key = self.headers.get("X-Session-Id")
                if session_key is None and not idempotent and body:
                    # no explicit session: fingerprint the prompt head
                    # so shared-prefix storms still pin to one replica
                    try:
                        doc = json.loads(body)
                        session_key = prompt_fingerprint(
                            model, doc.get("prompt") or ())
                    except (ValueError, TypeError):
                        session_key = None
                try:
                    status, hdrs, payload, url = router.route(
                        method, self.path, body, headers=fwd, model=model,
                        idempotent=idempotent, session_key=session_key)
                except MidStreamError as e:
                    hh = [("X-Trace-Id", e.trace_id)] if e.trace_id else []
                    self.send_json(
                        {"error": str(e), "trace_id": e.trace_id,
                         "replica": e.replica_url},
                        502, headers=hh)
                    return
                except NoReplicaError as e:
                    self.send_json({"error": str(e)}, 503,
                                   headers=[("Retry-After", "1")])
                    return
                passthrough = [(k, v) for k, v in hdrs.items()
                               if k.lower() in ("x-trace-id",
                                                "x-model-version",
                                                "retry-after")]
                passthrough.append(("X-Fleet-Replica", url))
                self.send_payload(
                    payload,
                    hdrs.get("Content-Type", "application/json"),
                    status, headers=passthrough)

        return Handler
