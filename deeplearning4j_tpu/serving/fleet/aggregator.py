"""Fleet metrics aggregation: one exposition over N replica registries.

:class:`FleetAggregator` rides the :class:`~.router.FleetRouter` poll
loop — every ``/metrics.json`` scrape the poller already performs is
handed to :meth:`FleetAggregator.ingest` — and maintains a fleet-wide
view with *correct per-type merge semantics*:

- **counters** are summed across replicas with per-replica **reset
  detection**: each (family, label-set, replica) series tracks the last
  raw value and accumulates deltas, so a replica restart (its counters
  snap back to ~0) contributes its post-restart counts instead of
  stepping the fleet sum backward. The fleet-level rate of a counter is
  therefore monotone non-decreasing through any single-replica restart.
- **gauges** keep the last scraped value per replica; the merged series
  is the sum across replicas — exact for the capacity gauges the
  autoscaler reads (waiters, queue depth, active, free KV blocks), and
  documented as "sum" for everything else.
- **histograms** are merged **bucket-wise**: every engine in the repo
  observes into the same exponential bucket scheme
  (``common.metrics.exponential_buckets``), so summing per-bucket counts
  across replicas and interpolating quantiles inside the merged buckets
  yields *exactly* the percentiles of the pooled observations — not a
  re-estimate over pre-digested p50/p99s (averaging percentiles is the
  classic aggregation bug this module exists to avoid). Bucket counts
  get the same reset detection as counters, keyed on the series'
  monotone total count.

Exposition (served by ``FleetServer`` ``GET /metrics`` +
``/metrics.json``): per family, every per-replica series carries a
``replica="<url>"`` label and the merged series carries none, so one
scrape answers both "which replica?" and "the fleet as a whole".

A bounded in-memory **signal ring** (``DL4J_TPU_FLEET_AGG_RETENTION_S``
seconds / ``DL4J_TPU_FLEET_AGG_MAX_SAMPLES`` samples) keeps a short
time-series of each replica's autoscaling signals — admission waiters,
service EWMA, SLO burn/healthy, free KV blocks — and
:meth:`FleetAggregator.signals` joins the latest sample per replica with
the router's membership/brownout posture into the ``GET /fleet/signals``
JSON that ROADMAP item 3's SLO-driven autoscaler consumes.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ...common.environment import environment
from ...common.metrics import _fmt

#: gauge families distilled into the per-replica autoscaler signal view
_SIGNAL_GAUGES = {
    "dl4j_serving_waiters": ("admission", "waiters"),
    "dl4j_serving_ewma_service_seconds": ("admission", "ewma_s"),
    "dl4j_serving_queue_depth": ("admission", "queue_depth"),
    "dl4j_serving_active": ("admission", "active"),
    "dl4j_kv_blocks_free": ("kv", "blocks_free"),
    "dl4j_slo_healthy": ("slo", "healthy"),
}

#: signal fields whose fleet rollup is a plain SUM across replicas (the
#: capacity view); everything else rolls up as documented in signals()
_SUMMED_SIGNALS = ("waiters", "queue_depth", "active", "blocks_free")


def histogram_quantile(bounds: Tuple[float, ...], counts: List[float],
                       q: float) -> Optional[float]:
    """q-quantile by linear interpolation inside the buckets — the same
    rule as ``_HistogramChild.quantile`` and PromQL's
    ``histogram_quantile`` — over an explicit (bounds, counts) pair so
    fleet-merged bucket vectors use identical math to a single child.
    ``counts`` is per-bucket (NOT cumulative), last slot = +Inf
    overflow. None for an empty histogram (strict-JSON safe)."""
    total = sum(counts)
    if total <= 0 or not bounds:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev_cum = cum
        cum += c
        if cum >= rank and c > 0:
            if i >= len(bounds):  # +Inf bucket clamps to the top bound
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * (rank - prev_cum) / c
    return bounds[-1]


def _label_suffix(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{labels[k]}"' for k in sorted(labels)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus_text(snap: Dict[str, dict]) -> str:
    """Prometheus text exposition (0.0.4) from a ``/metrics.json``-shaped
    snapshot — works for both a local ``MetricsRegistry.snapshot()`` and
    the aggregator's merged view (their series now both carry raw
    ``bounds``/``bucket_counts`` for histograms), so the fleet front
    door can serve one combined ``/metrics`` text."""
    lines: List[str] = []
    for name in sorted(snap):
        fam = snap[name]
        if not isinstance(fam, dict):
            continue
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam.get('type', 'untyped')}")
        for series in fam.get("series", ()):
            labels = series.get("labels") or {}
            if "bucket_counts" in series:
                bounds = series.get("bounds") or ()
                counts = series["bucket_counts"]
                cum = 0.0
                for bound, c in zip(bounds, counts):
                    cum += c
                    le = _label_suffix(labels, f'le="{_fmt(bound)}"')
                    lines.append(f"{name}_bucket{le} {_fmt(cum)}")
                le = _label_suffix(labels, 'le="+Inf"')
                lines.append(f"{name}_bucket{le} "
                             f"{_fmt(series.get('count', 0))}")
                ls = _label_suffix(labels)
                lines.append(f"{name}_sum{ls} "
                             f"{_fmt(series.get('sum', 0.0))}")
                lines.append(f"{name}_count{ls} "
                             f"{_fmt(series.get('count', 0))}")
            else:
                ls = _label_suffix(labels)
                lines.append(f"{name}{ls} "
                             f"{_fmt(series.get('value', 0.0))}")
    return "\n".join(lines) + ("\n" if lines else "")


class _HistState:
    """Per (family, label-set, replica) histogram accumulator with reset
    detection keyed on the series' monotone total count."""
    __slots__ = ("bounds", "last_counts", "adj_counts", "last_count",
                 "adj_count", "last_sum", "adj_sum")

    def __init__(self, bounds: Tuple[float, ...]):
        n = len(bounds) + 1
        self.bounds = bounds
        self.last_counts = [0.0] * n
        self.adj_counts = [0.0] * n
        self.last_count = 0.0
        self.adj_count = 0.0
        self.last_sum = 0.0
        self.adj_sum = 0.0

    def update(self, counts: List[float], count: float, total_sum: float):
        if count < self.last_count:  # replica restarted: fresh baseline
            self.last_counts = [0.0] * len(self.last_counts)
            self.last_count = 0.0
            self.last_sum = 0.0
        for i, c in enumerate(counts[:len(self.adj_counts)]):
            self.adj_counts[i] += max(c - self.last_counts[i], 0.0)
            self.last_counts[i] = c
        self.adj_count += max(count - self.last_count, 0.0)
        self.last_count = count
        self.adj_sum += max(total_sum - self.last_sum, 0.0)
        self.last_sum = total_sum


class FleetAggregator:
    """Scrape sink + merged exposition for a fleet of replicas. All
    state is in-process and bounded; ``ingest`` is defensive — a junk
    payload (wrong types, non-finite values) skips the junk entries and
    never raises into the poll loop."""

    def __init__(self, retention_s: Optional[float] = None,
                 max_samples: Optional[int] = None):
        env = environment()
        self.retention_s = env.fleet_agg_retention_s() \
            if retention_s is None else max(float(retention_s), 1.0)
        self.max_samples = env.fleet_agg_max_samples() \
            if max_samples is None else max(int(max_samples), 1)
        self._lock = threading.Lock()
        #: family name -> {"type", "help"}
        self._families: Dict[str, Dict[str, str]] = {}
        #: (name, labelkey) -> replica -> [last_raw, adjusted]
        self._counters: Dict[Tuple[str, Tuple], Dict[str, List[float]]] = {}
        #: (name, labelkey) -> adjusted totals of forgotten replicas —
        #: keeps the merged counter monotone across membership changes
        self._retired: Dict[Tuple[str, Tuple], float] = {}
        #: (name, labelkey) -> replica -> last value
        self._gauges: Dict[Tuple[str, Tuple], Dict[str, float]] = {}
        #: (name, labelkey) -> replica -> _HistState
        self._hists: Dict[Tuple[str, Tuple], Dict[str, _HistState]] = {}
        #: (ts, replica, signal view) ring — the autoscaler's short
        #: history; bounded by retention_s AND max_samples
        self._ring: "deque[Tuple[float, str, dict]]" = deque()
        self._scrapes = 0

    # -- ingest -----------------------------------------------------------
    def ingest(self, replica: str, doc: Any):
        """Fold one replica's ``/metrics.json`` into the fleet view."""
        if not isinstance(doc, dict):
            return
        replica = str(replica).rstrip("/")
        now = time.time()
        with self._lock:
            self._scrapes += 1
            for name, fam in doc.items():
                if not isinstance(fam, dict):
                    continue
                kind = fam.get("type")
                series = fam.get("series")
                if kind not in ("counter", "gauge", "histogram") \
                        or not isinstance(series, (list, tuple)):
                    continue
                self._families.setdefault(
                    name, {"type": kind, "help": str(fam.get("help", ""))})
                for entry in series:
                    if not isinstance(entry, dict):
                        continue
                    labels = entry.get("labels")
                    if not isinstance(labels, dict):
                        continue
                    key = (name, tuple(sorted(
                        (str(k), str(v)) for k, v in labels.items())))
                    if kind == "histogram":
                        self._ingest_hist(key, replica, entry)
                    elif kind == "counter":
                        v = _finite(entry.get("value"))
                        if v is None:
                            continue
                        st = self._counters.setdefault(key, {}).get(replica)
                        if st is None:
                            self._counters[key][replica] = [v, v]
                        else:
                            st[1] += v - st[0] if v >= st[0] else v
                            st[0] = v
                    else:
                        v = _finite(entry.get("value"))
                        if v is not None:
                            self._gauges.setdefault(key, {})[replica] = v
            self._ring.append((now, replica,
                               self._signal_view_locked(replica)))
            horizon = now - self.retention_s
            while self._ring and (len(self._ring) > self.max_samples
                                  or self._ring[0][0] < horizon):
                self._ring.popleft()

    def _ingest_hist(self, key, replica: str, entry: dict):
        bounds = entry.get("bounds")
        counts = entry.get("bucket_counts")
        if not isinstance(bounds, (list, tuple)) \
                or not isinstance(counts, (list, tuple)) \
                or len(counts) != len(bounds) + 1:
            return
        try:
            bounds = tuple(float(b) for b in bounds)
            counts = [float(c) for c in counts]
            count = float(entry.get("count") or 0.0)
            total_sum = float(entry.get("sum") or 0.0)
        except (TypeError, ValueError):
            return
        per_rep = self._hists.setdefault(key, {})
        st = per_rep.get(replica)
        if st is None or st.bounds != bounds:
            st = per_rep[replica] = _HistState(bounds)
        st.update(counts, count, total_sum)

    def forget(self, replica: str):
        """Drop a removed replica's per-series state (its already-merged
        counter history stays in the adjusted sums — a retired replica's
        past traffic really happened)."""
        replica = str(replica).rstrip("/")
        with self._lock:
            for key, per_rep in self._counters.items():
                st = per_rep.pop(replica, None)
                if st is not None:
                    self._retired[key] = self._retired.get(key, 0.0) \
                        + st[1]
            for table in (self._gauges, self._hists):
                for per_rep in table.values():
                    per_rep.pop(replica, None)

    # -- merged exposition ------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """``/metrics.json``-shaped fleet view: per family, one series
        per (label-set, replica) carrying a ``replica`` label, plus one
        merged series per label-set carrying none."""
        out: Dict[str, dict] = {}
        with self._lock:
            fams = dict(self._families)
            counters = {k: {r: st[1] for r, st in v.items()}
                        for k, v in self._counters.items()}
            retired = dict(self._retired)
            for key in retired:
                counters.setdefault(key, {})
            gauges = {k: dict(v) for k, v in self._gauges.items()}
            hists = {k: {r: (st.bounds, list(st.adj_counts), st.adj_count,
                             st.adj_sum) for r, st in v.items()}
                     for k, v in self._hists.items()}
        for name in sorted(fams):
            kind = fams[name]["type"]
            series: List[dict] = []
            if kind == "histogram":
                keys = sorted(k for k in hists if k[0] == name)
                for key in keys:
                    merged: Dict[Tuple[float, ...], list] = {}
                    for rep in sorted(hists[key]):
                        bounds, counts, count, s = hists[key][rep]
                        series.append(self._hist_entry(
                            dict(key[1]), bounds, counts, count, s,
                            replica=rep))
                        m = merged.setdefault(
                            bounds, [[0.0] * len(counts), 0.0, 0.0])
                        for i, c in enumerate(counts):
                            m[0][i] += c
                        m[1] += count
                        m[2] += s
                    for bounds in sorted(merged):
                        counts, count, s = merged[bounds]
                        series.append(self._hist_entry(
                            dict(key[1]), bounds, counts, count, s))
            else:
                table = counters if kind == "counter" else gauges
                keys = sorted(k for k in table if k[0] == name)
                for key in keys:
                    for rep in sorted(table[key]):
                        series.append(
                            {"labels": {**dict(key[1]), "replica": rep},
                             "value": table[key][rep]})
                    # merged counters fold in forgotten replicas'
                    # adjusted totals: the fleet sum stays monotone
                    # across membership changes
                    merged_v = sum(table[key].values())
                    if kind == "counter":
                        merged_v += retired.get(key, 0.0)
                    series.append({"labels": dict(key[1]),
                                   "value": merged_v})
            out[name] = {"type": kind, "help": fams[name]["help"],
                         "series": series}
        return out

    @staticmethod
    def _hist_entry(labels: Dict[str, str], bounds, counts, count, s,
                    replica: Optional[str] = None) -> dict:
        if replica is not None:
            labels = {**labels, "replica": replica}
        return {"labels": labels, "count": count, "sum": s,
                "bounds": list(bounds), "bucket_counts": list(counts),
                "p50": histogram_quantile(bounds, counts, 0.50),
                "p90": histogram_quantile(bounds, counts, 0.90),
                "p99": histogram_quantile(bounds, counts, 0.99)}

    def merged_with(self, local: Dict[str, dict]) -> Dict[str, dict]:
        """The combined fleet exposition: the front door's own registry
        snapshot with every aggregated family folded in (on a name
        collision the aggregated series — replica-labeled + merged —
        append to the local family's series list)."""
        out = {name: {"type": fam.get("type"), "help": fam.get("help"),
                      "series": list(fam.get("series", ()))}
               for name, fam in local.items()}
        for name, fam in self.snapshot().items():
            if name in out:
                out[name]["series"].extend(fam["series"])
            else:
                out[name] = fam
        return out

    # -- autoscaler signals -----------------------------------------------
    def _signal_view_locked(self, replica: str) -> Dict[str, Any]:
        """Distill the replica's latest gauges into the autoscaler's
        signal schema. Caller holds the lock."""
        view: Dict[str, Any] = {"admission": {}, "slo": {}, "kv": {}}
        for (name, labelkey), per_rep in self._gauges.items():
            spec = _SIGNAL_GAUGES.get(name)
            if spec is None or replica not in per_rep:
                continue
            group, field = spec
            labels = dict(labelkey)
            model = labels.get("model")
            if model is None:
                continue
            slot = view[group].setdefault(model, {})
            value = per_rep[replica]
            slot[field] = bool(value) if field == "healthy" else value
        for (name, labelkey), per_rep in self._gauges.items():
            if name != "dl4j_slo_burn_rate" or replica not in per_rep:
                continue
            labels = dict(labelkey)
            model, window = labels.get("model"), labels.get("window")
            if model is None or window is None:
                continue
            view["slo"].setdefault(model, {}).setdefault(
                "burn", {})[window] = per_rep[replica]
        return view

    def signals(self, replica_state: Optional[Dict[str, dict]] = None,
                brownout: Optional[dict] = None) -> Dict[str, Any]:
        """The ``GET /fleet/signals`` document: per replica the latest
        distilled signal view (admission waiters/EWMA/queue/active, SLO
        burn rates + healthy, free KV blocks) joined with the router's
        membership state, plus a fleet rollup — membership counts
        (``replicas``/``ready``) ride on top, capacity fields
        (waiters, queue_depth, active, blocks_free) are exact sums,
        ``ewma_s`` is the mean across reporting replicas, SLO burn is
        the worst (max) replica and ``healthy`` is the AND. The ring
        depth/retention ride along so an autoscaler can tell how much
        history backs the numbers."""
        with self._lock:
            latest: Dict[str, Tuple[float, dict]] = {}
            for ts, rep, view in self._ring:
                latest[rep] = (ts, view)
            ring_len = len(self._ring)
            scrapes = self._scrapes
        replicas: Dict[str, dict] = {}
        for rep, (ts, view) in sorted(latest.items()):
            entry = {"ts": ts, **view}
            if replica_state and rep in replica_state:
                entry.update(replica_state[rep])
            replicas[rep] = entry
        for rep, state in sorted((replica_state or {}).items()):
            replicas.setdefault(rep, {"ts": None, "admission": {},
                                      "slo": {}, "kv": {}, **state})
        rollup: Dict[str, Any] = {
            "replicas": len(replicas),
            "ready": sum(1 for e in replicas.values() if e.get("ready")),
            "admission": {}, "slo": {}, "kv": {}}
        ewma_n: Dict[str, int] = {}
        for entry in replicas.values():
            for model, adm in entry.get("admission", {}).items():
                slot = rollup["admission"].setdefault(model, {})
                for field in _SUMMED_SIGNALS:
                    if field in adm:
                        slot[field] = slot.get(field, 0.0) + adm[field]
                if "ewma_s" in adm:
                    slot["ewma_s"] = slot.get("ewma_s", 0.0) + adm["ewma_s"]
                    ewma_n[model] = ewma_n.get(model, 0) + 1
            for model, kv in entry.get("kv", {}).items():
                slot = rollup["kv"].setdefault(model, {})
                for field in _SUMMED_SIGNALS:
                    if field in kv:
                        slot[field] = slot.get(field, 0.0) + kv[field]
            for model, slo in entry.get("slo", {}).items():
                slot = rollup["slo"].setdefault(
                    model, {"healthy": True, "burn": {}})
                if slo.get("healthy") is False:
                    slot["healthy"] = False
                for window, rate in slo.get("burn", {}).items():
                    slot["burn"][window] = max(
                        slot["burn"].get(window, 0.0), rate)
        for model, n in ewma_n.items():
            rollup["admission"][model]["ewma_s"] /= n
        doc = {"ts": time.time(), "replicas": replicas, "fleet": rollup,
               "ring": {"samples": ring_len, "scrapes": scrapes,
                        "retention_s": self.retention_s,
                        "max_samples": self.max_samples}}
        if brownout is not None:
            doc["brownout"] = brownout
        return doc

    def history(self, replica: Optional[str] = None) -> List[dict]:
        """The retained signal ring, oldest first (debug/tests)."""
        with self._lock:
            return [{"ts": ts, "replica": rep, "signals": view}
                    for ts, rep, view in self._ring
                    if replica is None or rep == str(replica).rstrip("/")]


def _finite(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f and f not in (float("inf"), float("-inf")) else None
