"""Self-healing serving: circuit breakers, engine health, dispatch
watchdog.

Reference: the Clipper (NSDI '17) practice of isolating a misbehaving
model container behind a fallback, and the Clockwork (OSDI '20) rule
that predictable serving requires actively refusing work that cannot be
served well. The engine-level half of the story lives in the engines
themselves (supervised worker restart + poison-request quarantine in
``runtime/inference.py`` / ``runtime/generation.py``, backed by
``common.faults``); this module is the *serving-layer* half:

- :class:`CircuitBreaker` — per model *version*. Consecutive dispatch
  failures open it; open fails fast (:class:`BreakerOpenError` → HTTP
  503 + ``Retry-After``) instead of queueing doomed work behind a sick
  executable; after ``DL4J_TPU_BREAKER_PROBE_S`` one half-open probe is
  let through — success re-closes, failure re-opens. A breaker that
  re-opens ``DL4J_TPU_AUTO_ROLLBACK_OPENS`` times in a row is
  *persistently* open: with ``DL4J_TPU_AUTO_ROLLBACK=1`` and a warm
  parked previous version, ``ModelRegistry`` rolls back to it —
  degraded service beats no service.
- :class:`HealthRegistry` (module singleton :func:`health`) — the
  aggregated engine-health signal ``/readyz`` gates on, fed by the
  watchdog and by engine-supervisor permadeath.
- :class:`EngineWatchdog` (module singleton :func:`watchdog`) — polls
  registered engines' in-flight dispatch age; a dispatch stuck past
  ``deadline × DL4J_TPU_WATCHDOG_FACTOR`` (or a worker thread whose
  restart budget is exhausted) marks the engine unhealthy so the load
  balancer stops routing here; recovery clears the mark.

Metrics: ``dl4j_breaker_state{model,version}`` (0 closed / 1 half-open /
2 open), ``dl4j_breaker_transitions_total{model,state}``,
``dl4j_engine_healthy{engine}``, ``dl4j_auto_rollbacks_total{model}``
(in the registry).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from ..common.environment import environment
from ..common.locks import ordered_lock
from ..common.metrics import registry as metrics_registry

log = logging.getLogger(__name__)

#: breaker states (also the gauge values)
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}


class BreakerOpenError(RuntimeError):
    """Fail-fast refusal: the model version's breaker is open. Carries
    the time until the next half-open probe as ``retry_after_s`` (the
    HTTP layer merges it with the admission EWMA hint)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.001)


class CircuitBreaker:
    """Consecutive-failure breaker for one (model, version) pair."""

    def __init__(self, model: str, version: str, *,
                 threshold: Optional[int] = None,
                 probe_s: Optional[float] = None,
                 clock=time.monotonic):
        env = environment()
        self.model = str(model)
        self.version = str(version)
        self.threshold = (env.breaker_threshold() if threshold is None
                          else max(int(threshold), 1))
        self.probe_s = (env.breaker_probe_s() if probe_s is None
                        else float(probe_s))
        self._clock = clock
        self._lock = ordered_lock("breaker")
        self._state = CLOSED
        self._failures = 0          # consecutive, reset on success
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        self.consecutive_opens = 0  # opens without a success between
        reg = metrics_registry()
        self._m_state = reg.gauge(
            "dl4j_breaker_state",
            "Circuit-breaker state per served model version "
            "(0 closed, 1 half-open, 2 open)",
            labels=("model", "version")).labels(model=self.model,
                                                version=self.version)
        self._m_state.set(CLOSED)
        self._m_transitions = reg.counter(
            "dl4j_breaker_transitions_total",
            "Circuit-breaker state transitions, by resulting state",
            labels=("model", "state"))
        self._m_rejected = reg.counter(
            "dl4j_breaker_rejections_total",
            "Requests failed fast by an open circuit breaker",
            labels=("model", "version")).labels(model=self.model,
                                                version=self.version)

    # -- introspection ----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return _STATE_NAMES[self._state]

    def snapshot(self) -> Dict:
        with self._lock:
            return {"model": self.model, "version": self.version,
                    "state": _STATE_NAMES[self._state],
                    "consecutive_failures": self._failures,
                    "consecutive_opens": self.consecutive_opens,
                    "threshold": self.threshold, "probe_s": self.probe_s}

    def _transition(self, state: int):
        self._state = state
        self._m_state.set(state)
        self._m_transitions.labels(model=self.model,
                                   state=_STATE_NAMES[state]).inc()

    # -- the contract ------------------------------------------------------
    def preflight(self):
        """Gate one dispatch attempt. Open: raise
        :class:`BreakerOpenError` until the probe window elapses, then
        let exactly ONE caller through half-open (concurrent callers
        keep failing fast until the probe resolves)."""
        with self._lock:
            if self._state == CLOSED:
                return
            now = self._clock()
            if self._state == OPEN and \
                    now - self._opened_at >= self.probe_s:
                self._transition(HALF_OPEN)
                self._probe_inflight = False
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True  # this caller IS the probe
                return
            remaining = (max(self._opened_at + self.probe_s - now, 0.0)
                         if self._opened_at is not None else self.probe_s)
            self._m_rejected.inc()
            raise BreakerOpenError(
                f"model '{self.model}' version '{self.version}' breaker "
                f"is {_STATE_NAMES[self._state]} "
                f"({self._failures} consecutive dispatch failures); "
                "failing fast", retry_after_s=remaining or self.probe_s)

    def record_success(self):
        with self._lock:
            if self._state != CLOSED:
                log.info("breaker %s:%s re-closed after probe success",
                         self.model, self.version)
                self._transition(CLOSED)
            self._failures = 0
            self.consecutive_opens = 0
            self._probe_inflight = False

    def record_failure(self) -> bool:
        """Count one dispatch failure; returns True when this failure
        opened (or re-opened) the breaker."""
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN:
                # the probe failed: straight back to open
                self._transition(OPEN)
                self._opened_at = self._clock()
                self._probe_inflight = False
                self.consecutive_opens += 1
                log.warning("breaker %s:%s probe failed; re-opened "
                            "(%d consecutive opens)", self.model,
                            self.version, self.consecutive_opens)
                return True
            if self._state == CLOSED and self._failures >= self.threshold:
                self._transition(OPEN)
                self._opened_at = self._clock()
                self.consecutive_opens += 1
                log.warning(
                    "breaker %s:%s opened after %d consecutive dispatch "
                    "failures", self.model, self.version, self._failures)
                return True
            return False


# ---------------------------------------------------------------------------
# engine health (the /readyz signal)
# ---------------------------------------------------------------------------

class HealthRegistry:
    """Aggregated engine-health flags. Empty = healthy. Keys are
    ``model:version`` (or any engine identity); each carries a reason
    so ``/readyz`` and the flight recorder can say *why*."""

    def __init__(self):
        self._lock = ordered_lock("health")
        self._unhealthy: Dict[str, str] = {}
        self._m = metrics_registry().gauge(
            "dl4j_engine_healthy",
            "1 while the engine's dispatch path is healthy, else 0",
            labels=("engine",))

    def set_unhealthy(self, key: str, reason: str):
        with self._lock:
            known = key in self._unhealthy
            self._unhealthy[key] = reason
        self._m.labels(engine=key).set(0)
        if not known:
            log.warning("engine %s marked unhealthy: %s", key, reason)

    def clear(self, key: str):
        with self._lock:
            was = self._unhealthy.pop(key, None)
        self._m.labels(engine=key).set(1)
        if was is not None:
            log.info("engine %s healthy again (was: %s)", key, was)

    def healthy(self) -> bool:
        with self._lock:
            return not self._unhealthy

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._unhealthy)

    def reset(self):
        with self._lock:
            self._unhealthy.clear()


_HEALTH: Optional[HealthRegistry] = None
_HEALTH_LOCK = ordered_lock("resilience.health_singleton")


def health() -> HealthRegistry:
    global _HEALTH
    if _HEALTH is None:
        with _HEALTH_LOCK:
            if _HEALTH is None:
                _HEALTH = HealthRegistry()
    return _HEALTH


# ---------------------------------------------------------------------------
# dispatch watchdog
# ---------------------------------------------------------------------------

class EngineWatchdog:
    """Polls registered engines for stuck dispatches and dead workers.

    Engines expose two cheap fields the watchdog reads from outside —
    ``_dispatch_started_at`` (monotonic instant of the in-flight device
    dispatch, or None) and ``worker_dead`` (the supervised worker
    thread exhausted its restart budget) — so the runtime layer stays
    free of serving imports and the hot path pays two attribute stores
    per dispatch. An overdue dispatch or a dead worker flips the engine
    unhealthy in :func:`health`; recovery clears it."""

    def __init__(self, poll_s: float = 0.25):
        self.poll_s = float(poll_s)
        self._lock = ordered_lock("watchdog")
        self._watched: Dict[str, Tuple[object, float]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def register(self, key: str, engine, budget_s: float):
        """Watch ``engine`` under ``key``; dispatches older than
        ``budget_s`` mark it unhealthy."""
        with self._lock:
            self._watched[str(key)] = (engine, float(budget_s))
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="dl4j-tpu-engine-watchdog",
                    daemon=True)
                self._thread.start()

    def unregister(self, key: str):
        with self._lock:
            self._watched.pop(str(key), None)
        health().clear(str(key))

    def watched(self) -> Dict[str, float]:
        with self._lock:
            return {k: b for k, (_, b) in self._watched.items()}

    def check_now(self):
        """One evaluation pass (tests call this instead of sleeping)."""
        now = time.monotonic()
        with self._lock:
            watched = dict(self._watched)
        h = health()
        for key, (engine, budget) in watched.items():
            if getattr(engine, "worker_dead", False):
                h.set_unhealthy(key, "worker thread permanently failed "
                                     "(restart budget exhausted)")
                continue
            started = getattr(engine, "_dispatch_started_at", None)
            if started is not None and now - started > budget:
                h.set_unhealthy(
                    key, f"dispatch in flight for {now - started:.2f}s "
                         f"(budget {budget:.2f}s)")
            else:
                h.clear(key)

    def _loop(self):
        while not self._stop.wait(self.poll_s):
            with self._lock:
                if not self._watched:
                    self._thread = None
                    return
            try:
                self.check_now()
            except Exception:
                log.exception("engine watchdog pass failed")

    def stop(self):
        self._stop.set()
        with self._lock:
            self._watched.clear()
            t = self._thread
        if t is not None:
            t.join(timeout=5)


_WATCHDOG: Optional[EngineWatchdog] = None
_WATCHDOG_LOCK = ordered_lock("resilience.watchdog_singleton")


def watchdog() -> EngineWatchdog:
    global _WATCHDOG
    if _WATCHDOG is None:
        with _WATCHDOG_LOCK:
            if _WATCHDOG is None:
                _WATCHDOG = EngineWatchdog()
    return _WATCHDOG


def watchdog_budget_s() -> Optional[float]:
    """The dispatch budget engines are watched against: default serving
    deadline × ``DL4J_TPU_WATCHDOG_FACTOR``; None = watchdog disabled
    (factor <= 0)."""
    env = environment()
    factor = env.watchdog_factor()
    if factor <= 0:
        return None
    deadline = env.serving_default_timeout_s() or 30.0
    return deadline * factor


# ---------------------------------------------------------------------------
# rolling dispatch outcomes (outlier detection substrate)
# ---------------------------------------------------------------------------

class DispatchStats:
    """Rolling window over actual dispatch outcomes of one upstream —
    the Envoy-style outlier-detection substrate. ``/readyz`` polls only
    prove a replica can answer its health endpoint; a *zombie* answers
    those and fails traffic, so ejection decisions must come from the
    outcomes of real dispatches. Deliberately unsynchronized: the owner
    (``FleetRouter``) already serializes access under its own lock."""

    __slots__ = ("window", "_outcomes")

    def __init__(self, window: int = 20):
        self.window = max(int(window), 1)
        self._outcomes: deque = deque(maxlen=self.window)

    def record(self, ok: bool, latency_s: Optional[float] = None):
        self._outcomes.append((bool(ok), latency_s))

    def __len__(self) -> int:
        return len(self._outcomes)

    def error_rate(self) -> float:
        if not self._outcomes:
            return 0.0
        errors = sum(1 for ok, _ in self._outcomes if not ok)
        return errors / len(self._outcomes)

    def mean_latency_s(self) -> Optional[float]:
        """Mean over outcomes that carry a latency (errors usually
        don't); None until one does."""
        vals = [lat for _, lat in self._outcomes if lat is not None]
        if not vals:
            return None
        return sum(vals) / len(vals)

    def reset(self):
        """Forget history (probe re-admission: the replica restarts its
        audition from a clean slate)."""
        self._outcomes.clear()

    def snapshot(self) -> Dict[str, object]:
        mean = self.mean_latency_s()
        return {"samples": len(self._outcomes),
                "error_rate": round(self.error_rate(), 4),
                "mean_latency_s": None if mean is None else round(mean, 6)}


def latency_zscore(mean_s: float, peer_means_s: "list[float]",
                   min_peers: int = 2, min_ratio: float = 2.0) -> float:
    """How many standard deviations ``mean_s`` sits above its peers'
    mean latencies. Too few peers → 0 (no basis to call an outlier).
    Statistical significance alone is not enough: when peers agree to
    the microsecond the std collapses and a replica 0.2 ms slower would
    score z > 3, so the candidate must ALSO be at least ``min_ratio``
    times the peer mean before any non-zero score is returned.
    Degenerate peer spread (std ~ 0, the common case on a quiet fleet)
    then falls back to that ratio test alone: past it reads as +inf —
    a lone slow replica must not hide behind zero variance."""
    peers = [m for m in peer_means_s if m is not None]
    if len(peers) < max(int(min_peers), 1):
        return 0.0
    pmean = sum(peers) / len(peers)
    if pmean <= 0 or mean_s <= min_ratio * pmean:
        return 0.0
    var = sum((m - pmean) ** 2 for m in peers) / len(peers)
    std = var ** 0.5
    if std < 1e-9:
        return float("inf")
    return (mean_s - pmean) / std
