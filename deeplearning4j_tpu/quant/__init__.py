"""Post-training quantization (PTQ) as a pure params -> params transform.

The paper's TPU-native answer to libnd4j's hand-tuned low-precision
kernels: per-channel symmetric int8 (LLM.int8()-style, Dettmers et al.,
2022) or fp8 weight quantization expressed entirely in XLA-friendly ops —
weights live int8/fp8 *at rest* and dequantize inside the jitted forward,
so the compiler fuses the dequant into the matmul epilogue and the HBM
footprint (and weight-streaming bandwidth) drops ~4x with zero custom
kernels. The AQT-style ``dequant_matmul`` keeps the per-output-channel
scale out of the contraction so accuracy survives the 8-bit weights.

Three modules:

- ``transforms``  — ``QuantizedTensor`` (a pytree leaf holding q + scale),
  ``quantize_params``/``quantize_model`` recipes for MLN/CG dense+conv
  layers, BERT blocks and ``CausalLM``, and the dequantizing compute ops
  (``dequant_matmul``, ``dequantize``, ``take_rows``, ``tied_logits``).
- ``calibrate``   — activation-range calibration (absmax + percentile)
  from a user-supplied sample batch, producing a serializable
  ``QuantSpec``.
- ``validate``    — the max-divergence gate ``ModelRegistry.deploy(
  quantize=...)`` runs between warmup and cutover: logits max-abs-err +
  top-1 agreement on the calibration batch (per-token agreement for
  generative models). A failing gate raises ``QuantizationRejectedError``
  and the swap aborts with the full-precision version still live.
"""
from .calibrate import QuantSpec, calibrate
from .transforms import (QuantizedTensor, default_act_dtype, dequant_matmul,
                         dequantize, fp8_supported, param_bytes_of,
                         precision_of, precision_of_model, quantize_model,
                         quantize_params, quantize_tensor, take_rows,
                         tied_logits)
from .validate import (QuantizationRejectedError, divergence_report,
                       validate)

__all__ = [
    "QuantSpec", "calibrate", "QuantizedTensor", "default_act_dtype",
    "dequant_matmul", "dequantize", "fp8_supported", "param_bytes_of",
    "precision_of", "precision_of_model", "quantize_model",
    "quantize_params", "quantize_tensor", "take_rows", "tied_logits",
    "QuantizationRejectedError", "divergence_report", "validate",
]
