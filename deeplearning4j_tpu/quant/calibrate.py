"""Activation-range calibration -> a serializable ``QuantSpec``.

Weight quantization needs no data (the scales come from the weights
themselves); what the *calibration batch* buys is (a) recorded activation
ranges per observation site — absmax or a percentile, the classic
outlier-robust choice — so an operator can see whether the traffic the
gate judged resembles production before trusting the top-1 agreement
number, and (b) a batch fingerprint binding the spec to the data the
divergence gate validated on. The spec is plain JSON either way: it
travels with the deploy request, lands in the flight recorder, and
round-trips byte-identically (``from_json(to_json(s)) == s``).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional, Tuple

import numpy as np

_METHODS = ("absmax", "percentile")
_MODES = ("int8", "fp8")


@dataclasses.dataclass
class QuantSpec:
    """Everything a quantized deploy needs, serializable.

    - ``mode``            — ``int8`` or ``fp8`` storage;
    - ``act_dtype``       — activation compute dtype of the twin (None =
      platform default: bf16 on accelerators, f32 on CPU);
    - ``method``/``percentile`` — activation-range statistic collected at
      calibration (absmax, or the given percentile of ``|a|``);
    - ``min_size``/``skip_keys``/``embedding_keys`` — eligibility knobs
      of :func:`~deeplearning4j_tpu.quant.transforms.quantize_params`;
    - ``act_ranges``      — the calibrated per-site ranges;
    - ``batch_fingerprint`` — shape/dtype signature of the calibration
      batch the ranges (and the divergence gate) were computed on;
    - ``scale_overrides`` — path-substring -> scale multiplier, the
      deliberate-mis-scale hook for gate drills and tests.
    """

    mode: str = "int8"
    act_dtype: Optional[str] = None
    method: str = "absmax"
    percentile: float = 99.9
    min_size: int = 256
    skip_keys: Tuple[str, ...] = ("position", "token_type")
    embedding_keys: Tuple[str, ...] = ("word",)
    act_ranges: Dict[str, float] = dataclasses.field(default_factory=dict)
    batch_fingerprint: Optional[str] = None
    scale_overrides: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(f"QuantSpec.mode must be one of {_MODES}, "
                             f"got {self.mode!r}")
        if self.method not in _METHODS:
            raise ValueError(f"QuantSpec.method must be one of {_METHODS}, "
                             f"got {self.method!r}")
        self.skip_keys = tuple(self.skip_keys)
        self.embedding_keys = tuple(self.embedding_keys)

    # -- serde ------------------------------------------------------------
    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["skip_keys"] = list(self.skip_keys)
        d["embedding_keys"] = list(self.embedding_keys)
        return json.dumps(d, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "QuantSpec":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def _fingerprint(batch) -> str:
    arrs = (list(batch.values()) if isinstance(batch, dict)
            else list(batch) if isinstance(batch, (list, tuple))
            else [batch])
    parts = []
    for a in arrs:
        a = np.asarray(a)
        parts.append(f"{a.dtype}{list(a.shape)}")
    return "+".join(parts)


def _range_of(a, method: str, percentile: float) -> float:
    mag = np.abs(np.asarray(a, dtype=np.float32))
    if method == "percentile":
        return float(np.percentile(mag, percentile))
    return float(np.max(mag)) if mag.size else 0.0


def calibrate(model, batch, *, mode: str = "int8",
              act_dtype: Optional[str] = None, method: str = "absmax",
              percentile: float = 99.9, **spec_kwargs) -> QuantSpec:
    """Run ``model`` over ``batch`` (eagerly — calibration is a deploy-time
    operation, never traced) and return a :class:`QuantSpec` carrying the
    observed activation ranges.

    Observation sites by model family: layer-API networks record every
    layer activation via ``feed_forward`` (``layer0..layerN``); generative
    models (``CausalLM`` protocol) record the full-sequence forward logits
    (``logits``); anything else with an ``output`` callable records its
    output."""
    ranges: Dict[str, float] = {}
    if all(callable(getattr(model, m, None))
           for m in ("init_kv_cache", "forward")):
        import jax.numpy as jnp
        logits = model.forward(jnp.asarray(np.asarray(batch)))
        ranges["logits"] = _range_of(logits, method, percentile)
    elif callable(getattr(model, "feed_forward", None)):
        acts = model.feed_forward(batch)
        for i, a in enumerate(acts):
            ranges[f"layer{i}"] = _range_of(
                a.jax() if hasattr(a, "jax") else a, method, percentile)
    elif callable(getattr(model, "output", None)):
        out = model.output(batch)
        if isinstance(out, (list, tuple)):
            out = out[0]
        ranges["output"] = _range_of(
            out.jax() if hasattr(out, "jax") else out, method, percentile)
    else:
        raise TypeError(
            f"cannot calibrate {type(model).__name__}: expected a model "
            "with forward/feed_forward/output")
    return QuantSpec(mode=mode, act_dtype=act_dtype, method=method,
                     percentile=percentile, act_ranges=ranges,
                     batch_fingerprint=_fingerprint(batch), **spec_kwargs)
