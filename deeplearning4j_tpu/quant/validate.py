"""The max-divergence gate between warmup and cutover.

A quantized twin that compiles and warms is not yet safe to serve: a
mis-scaled spec produces confidently wrong logits at full speed. So
``ModelRegistry.deploy(quantize=...)`` runs this gate AFTER the incoming
engine warms and BEFORE the pointer swap — the full-precision and
quantized models both run the calibration batch eagerly, and the twin
must stay within the divergence budget:

- ``max_abs_err``  — worst logit absolute error <= ``max_divergence``
  (``DL4J_TPU_QUANT_MAX_DIVERGENCE``);
- ``top1_agreement`` — argmax agreement >= ``min_top1``
  (``DL4J_TPU_QUANT_MIN_TOP1``); for generative models this is the
  next-token agreement at the last position, and ``per_token_agreement``
  (argmax at every position) is additionally gated — the quantity that
  actually predicts greedy-decode drift.

Failure raises :class:`QuantizationRejectedError`; deploy aborts, the
incoming engine closes, and the full-precision version never stops
serving. The measured divergence is exported either way on the
``dl4j_quant_divergence{model,version}`` gauge, so dashboards see how
close passing deploys run to the budget.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

from ..common.environment import environment
from ..common.metrics import registry as metrics_registry

log = logging.getLogger(__name__)


class QuantizationRejectedError(RuntimeError):
    """The quantized twin diverged past the gate budget; the swap was
    aborted with the full-precision version still live."""


_GAUGE = None


def _divergence_gauge():
    global _GAUGE
    if _GAUGE is None:
        _GAUGE = metrics_registry().gauge(
            "dl4j_quant_divergence",
            "Max logit abs error of the last gated quantized deploy",
            labels=("model", "version"))
    return _GAUGE


def _logits_of(model, batch) -> np.ndarray:
    """Eager forward of either model family over the gate batch, as a f32
    numpy array: ``[B, T, V]`` for generative models (full-sequence
    forward), ``[B, n_out]`` for predict models."""
    import jax.numpy as jnp

    if all(callable(getattr(model, m, None))
           for m in ("init_kv_cache", "forward")):
        out = model.forward(jnp.asarray(np.asarray(batch)))
    else:
        out = model.output(batch)
        if isinstance(out, (list, tuple)):
            out = out[0]
    if hasattr(out, "jax"):
        out = out.jax()
    return np.asarray(out, dtype=np.float32)


def divergence_report(full_model, quant_model, batch) -> Dict[str, float]:
    """Compare the two models on ``batch``. Keys: ``max_abs_err``,
    ``mean_abs_err``, ``top1_agreement``, ``generative``, and (generative
    only) ``per_token_agreement``."""
    a = _logits_of(full_model, batch)
    b = _logits_of(quant_model, batch)
    if a.shape != b.shape:
        raise ValueError(
            f"model outputs disagree in shape: full {a.shape} vs "
            f"quantized {b.shape} — not the same model family")
    err = np.abs(a - b)
    generative = a.ndim >= 3
    rep = {
        "max_abs_err": float(np.max(err)) if err.size else 0.0,
        "mean_abs_err": float(np.mean(err)) if err.size else 0.0,
        "generative": generative,
    }
    am, bm = np.argmax(a, axis=-1), np.argmax(b, axis=-1)
    if generative:
        rep["per_token_agreement"] = float(np.mean(am == bm))
        rep["top1_agreement"] = float(np.mean(am[..., -1] == bm[..., -1]))
    else:
        rep["top1_agreement"] = float(np.mean(am == bm))
    return rep


def validate(full_model, quant_model, batch, *,
             max_divergence: Optional[float] = None,
             min_top1: Optional[float] = None,
             model_name: str = "", version: str = "") -> Dict[str, float]:
    """Run the gate; returns the divergence report on success, raises
    :class:`QuantizationRejectedError` past budget. Env defaults:
    ``DL4J_TPU_QUANT_MAX_DIVERGENCE`` / ``DL4J_TPU_QUANT_MIN_TOP1``."""
    env = environment()
    if max_divergence is None:
        max_divergence = env.quant_max_divergence()
    if min_top1 is None:
        min_top1 = env.quant_min_top1()
    rep = divergence_report(full_model, quant_model, batch)
    _divergence_gauge().labels(
        model=model_name or "unnamed",
        version=version or "unversioned").set(rep["max_abs_err"])
    failures = []
    if rep["max_abs_err"] > max_divergence:
        failures.append(
            f"max logit abs error {rep['max_abs_err']:.4g} > budget "
            f"{max_divergence:.4g}")
    if rep["top1_agreement"] < min_top1:
        failures.append(
            f"top-1 agreement {rep['top1_agreement']:.4f} < required "
            f"{min_top1:.4f}")
    if rep.get("per_token_agreement", 1.0) < min_top1:
        failures.append(
            f"per-token agreement {rep['per_token_agreement']:.4f} < "
            f"required {min_top1:.4f}")
    if failures:
        raise QuantizationRejectedError(
            "quantized model rejected by the divergence gate ("
            + "; ".join(failures) + ") — full-precision version stays live")
    log.info("quantization gate passed for %s:%s (max_abs_err=%.4g, "
             "top1=%.4f)", model_name, version, rep["max_abs_err"],
             rep["top1_agreement"])
    return rep
