"""Params -> params quantization transforms and the dequantizing ops.

Weight-only PTQ in the per-channel symmetric recipe: for a weight ``W``
the scale is ``absmax(W, reduction_axes) / qmax`` (one scale per output
channel, never per tensor) and the stored value is ``round(W / scale)``
in int8 (or ``W / scale`` cast to fp8). Weights stay quantized *at rest*
— in the params pytree, in HBM, in the engine — and every consumer
dequantizes inside its jitted forward, where XLA folds the
``q.astype(f32) * scale`` into the surrounding dot/conv. The activations
are NOT quantized (bf16/f32 per ``QuantSpec.act_dtype``), except on the
optional fp8 path where ``dequant_matmul`` dynamically scales the
activation tensor and issues a real fp8 ``dot_general`` with
``preferred_element_type`` (platform-gated by :func:`fp8_supported`).

``QuantizedTensor`` is a registered pytree node so quantized params flow
through ``jax.jit``/``tree_map``/donation unchanged; it exposes enough of
the array protocol (``shape``/``ndim``/``dtype``/``astype``) for the
mixed-precision casting helpers to pass it through untouched.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: saturation range of the two storage formats
_INT8_QMAX = 127.0
_FP8_QMAX = 448.0  # float8_e4m3fn finite max

_FP8_PROBED: list = []  # [bool] once probed (module-lifetime memo)


def fp8_supported() -> bool:
    """Whether this jax/platform pair can run an fp8 ``dot_general`` with
    ``preferred_element_type`` — probed once, eagerly, never in a trace."""
    if _FP8_PROBED:
        return _FP8_PROBED[0]
    ok = hasattr(jnp, "float8_e4m3fn")
    if ok:
        try:
            x = jnp.ones((2, 2), jnp.float8_e4m3fn)
            jax.block_until_ready(jax.lax.dot_general(
                x, x, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        except Exception:
            ok = False
    _FP8_PROBED.append(ok)
    return ok


def default_act_dtype() -> str:
    """Activation dtype for quantized twins when the spec leaves it to the
    platform: bf16 where the MXU/tensor cores eat it natively, f32 on CPU
    (XLA:CPU emulates bf16 arithmetic — measurably *slower* than f32)."""
    return "float32" if jax.default_backend() == "cpu" else "bfloat16"


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """One quantized weight: ``q`` (int8/fp8, full shape) + ``scale``
    (f32, keepdims-broadcast over the reduction axes). Dequantized value
    is ``q * scale`` in f32, cast to the consumer's compute dtype."""

    __slots__ = ("q", "scale", "orig_dtype")

    def __init__(self, q, scale, orig_dtype: str = "float32"):
        self.q = q
        self.scale = scale
        self.orig_dtype = str(orig_dtype)

    # -- pytree protocol --------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), self.orig_dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], orig_dtype=aux)

    # -- enough array protocol for tree-walking params code ---------------
    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return int(getattr(self.q, "nbytes", 0)) + \
            int(getattr(self.scale, "nbytes", 0))

    def astype(self, dtype):
        """No-op: quantized storage is compute-dtype-invariant — the cast
        happens at dequantization, inside the consuming op. Keeps the
        mixed-precision param-casting helpers from corrupting the int8
        payload."""
        return self

    @property
    def mode(self) -> str:
        return "fp8" if "float8" in str(self.q.dtype) else "int8"

    def __repr__(self):
        return (f"QuantizedTensor(shape={tuple(self.shape)}, "
                f"mode={self.mode}, scale={tuple(self.scale.shape)})")


def quantize_tensor(w, axes=None, mode: str = "int8") -> QuantizedTensor:
    """Per-channel symmetric quantization of one weight.

    ``axes`` are the *reduction* axes of the absmax (default: every axis
    but the last, i.e. one scale per output channel of an ``x @ W``-style
    weight; embedding tables pass ``range(1, ndim)`` for per-row scales
    that serve both the lookup and the tied logits head).
    """
    if isinstance(w, QuantizedTensor):
        return w
    orig = str(w.dtype)
    w32 = jnp.asarray(w).astype(jnp.float32)
    if axes is None:
        axes = tuple(range(w32.ndim - 1))
    amax = jnp.maximum(jnp.max(jnp.abs(w32), axis=axes, keepdims=True),
                       1e-12)
    if mode == "int8":
        scale = amax / _INT8_QMAX
        q = jnp.clip(jnp.round(w32 / scale),
                     -_INT8_QMAX, _INT8_QMAX).astype(jnp.int8)
    elif mode == "fp8":
        if not fp8_supported():
            raise ValueError(
                "fp8 quantization requested but this jax/platform cannot "
                "run an fp8 dot_general (fp8_supported() is False)")
        scale = amax / _FP8_QMAX
        q = (w32 / scale).astype(jnp.float8_e4m3fn)
    else:
        raise ValueError(f"unknown quantization mode {mode!r} "
                         "(expected 'int8' or 'fp8')")
    return QuantizedTensor(q, scale, orig_dtype=orig)


# ---------------------------------------------------------------------------
# dequantizing compute ops (called inside jitted forwards; every op is a
# transparent identity for plain arrays, so one code path serves both the
# full-precision model and its quantized twin)
# ---------------------------------------------------------------------------

def dequantize(w, dtype=None):
    """``w`` as a plain array in ``dtype`` (f32 dequant, then cast). Plain
    arrays pass through (cast only when a dtype is given)."""
    if not isinstance(w, QuantizedTensor):
        return w if dtype is None else jnp.asarray(w).astype(dtype)
    out = w.q.astype(jnp.float32) * w.scale
    return out.astype(dtype if dtype is not None else w.orig_dtype)


def _mm_fit_tile(dim: int, want: int, base: int) -> int:
    """Largest multiple of ``base`` ≤ ``want`` that divides ``dim``;
    ``dim`` itself when nothing divides (interpret mode takes any
    shape, hardware eligibility is gated before we get here)."""
    if dim % base:
        return dim
    t = min(want, dim)
    t -= t % base
    while t > 0 and dim % t:
        t -= base
    return t if t > 0 else dim


def _fused_mm_kernel(x_ref, w_ref, s_ref, o_ref, acc_sc, *, n_k):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # the dequant lives inside the contraction loop: the int8 tile is
    # widened to the activation dtype in VMEM registers on its way into
    # the MXU — a full-precision weight copy never exists, in HBM or out
    acc_sc[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...].astype(x_ref.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ik == n_k - 1)
    def _done():
        o_ref[...] = (acc_sc[...] * s_ref[...]).astype(o_ref.dtype)


def _fused_dequant_matmul(x2, q, scale_row, interpret):
    """int8 weight-stationary ``[M,K] @ [K,N]`` with the per-channel
    scale applied to the f32 accumulator at the final K step —
    numerically ``(x @ q) * scale``, the exact XLA-path contraction."""
    import functools
    from ..kernels.flash_attention import _params

    M, K = x2.shape
    N = q.shape[1]
    tk = _mm_fit_tile(K, 512, 128)
    tn = _mm_fit_tile(N, 256, 128)
    M_pad = -(-M // 8) * 8
    if M_pad != M:
        x2 = jnp.pad(x2, [(0, M_pad - M), (0, 0)])
    tm = _mm_fit_tile(M_pad, 256, 8)
    grid = (M_pad // tm, N // tn, K // tk)
    out = pl.pallas_call(
        functools.partial(_fused_mm_kernel, n_k=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, k: (i, k)),
            pl.BlockSpec((tk, tn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, tn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M_pad, N), x2.dtype),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        compiler_params=_params(2),
        interpret=interpret,
    )(x2, q, scale_row.reshape(1, N).astype(jnp.float32))
    return out[:M] if M_pad != M else out


def _fused_path(x, w):
    """(path, reason, interpret) for the int8 per-channel branch of
    :func:`dequant_matmul` — "fused" (Pallas) or "xla" (cast-then-dot).
    Trace-time, mirroring ``kernels.attention_dispatch``'s contract."""
    from ..common.environment import environment
    mode = environment().fused_dequant()
    if mode == "off":
        return "xla", "DL4J_TPU_FUSED_DEQUANT=off", False
    M = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1
    if M == 0:
        return "xla", "empty activation batch", False
    K, N = w.q.shape
    if mode == "on":
        return "fused", "", jax.default_backend() == "cpu"
    if jax.default_backend() == "cpu":
        return "xla", "cpu backend (auto gates the kernel to accelerators)", \
            False
    if K % 128 or N % 128:
        return "xla", f"untileable weight: K={K} N={N}", False
    return "fused", "", False


def dequant_matmul(x, w):
    """``x @ W`` with int8/fp8-at-rest ``W`` (last-dim contraction, any
    leading ``x`` dims). int8: per ``DL4J_TPU_FUSED_DEQUANT`` either the
    Pallas fused kernel (int8 weight tiles + f32 scales stay in VMEM and
    dequantize inside the MXU contraction loop — a full-precision weight
    copy never exists in HBM) or the XLA fallback where the matmul runs
    in ``x.dtype`` against the casted payload; either way the
    per-output-channel scale multiplies the *result*. fp8: the
    activation is dynamically scaled per tensor and the contraction is a
    real fp8 ``dot_general`` accumulated in f32 via
    ``preferred_element_type``. Plain arrays pass straight through to
    ``jnp.matmul`` so one code path serves both precisions."""
    if not isinstance(w, QuantizedTensor):
        return jnp.matmul(x, w)
    if w.ndim != 2 or w.scale.shape[0] != 1:
        # not a per-output-channel 2D weight: dequantize then contract
        return jnp.matmul(x, dequantize(w, x.dtype))
    out_scale = w.scale.reshape(-1)  # [n_out]
    if w.mode == "fp8":
        sx = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12) / _FP8_QMAX
        xq = (x / sx).astype(w.q.dtype)
        out = jax.lax.dot_general(
            xq, w.q, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (out * (sx * out_scale)).astype(x.dtype)
    path, reason, interpret = _fused_path(x, w)
    try:
        from ..kernels import kernel_dispatch
        kernel_dispatch("dequant_matmul", path, reason)
    except Exception:
        pass  # observability must never break a trace
    if path == "fused":
        x2 = jnp.asarray(x).reshape(-1, x.shape[-1])
        out = _fused_dequant_matmul(x2, w.q, out_scale, interpret)
        return out.reshape(tuple(x.shape[:-1]) + (w.q.shape[1],))
    out = jnp.matmul(x, w.q.astype(x.dtype))
    return (out * out_scale.astype(x.dtype)).astype(x.dtype)


def take_rows(w, ids, dtype=None):
    """Row lookup (``jnp.take(w, ids, axis=0)``) through a per-row-scaled
    quantized table: gather the int8 rows AND their scales, multiply."""
    if not isinstance(w, QuantizedTensor):
        out = jnp.take(w, ids, axis=0)
        return out if dtype is None else out.astype(dtype)
    rows = jnp.take(w.q, ids, axis=0).astype(jnp.float32)
    scales = jnp.take(w.scale, ids, axis=0)
    return (rows * scales).astype(dtype if dtype is not None
                                  else w.orig_dtype)


def tied_logits(h, w):
    """Tied word-embedding head ``einsum('...e,ve->...v')`` in f32 against
    a per-row-scaled quantized table: the row scale IS the output-channel
    scale of the transposed contraction, so it multiplies the logits."""
    if not isinstance(w, QuantizedTensor):
        return jnp.einsum("...e,ve->...v", h, w).astype(jnp.float32)
    out = jnp.einsum("...e,ve->...v", h,
                     w.q.astype(h.dtype)).astype(jnp.float32)
    return out * w.scale.reshape(-1)


# ---------------------------------------------------------------------------
# params -> params recipes
# ---------------------------------------------------------------------------

def _spec_field(spec, name, default):
    return getattr(spec, name, default) if spec is not None else default


def quantize_params(params, spec=None):
    """Quantize every eligible weight leaf of a params pytree, preserving
    structure. Eligible: floating, ndim >= 2, ``size >= spec.min_size``,
    key not in ``spec.skip_keys`` and not a ``state_*`` running stat.
    Keys in ``spec.embedding_keys`` get per-row scales (reduction axes
    ``1..ndim``); everything else per-output-channel (axes ``0..ndim-1``).
    ``spec.scale_overrides`` maps a path substring to a multiplier applied
    to the matching tensors' scales — the deliberate-mis-scale hook the
    divergence-gate tests (and chaos drills) use."""
    mode = _spec_field(spec, "mode", "int8")
    min_size = int(_spec_field(spec, "min_size", 256))
    skip = tuple(_spec_field(spec, "skip_keys", ("position", "token_type")))
    emb = tuple(_spec_field(spec, "embedding_keys", ("word",)))
    overrides = dict(_spec_field(spec, "scale_overrides", {}) or {})

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (str(k),)) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            seq = [walk(v, path + (str(i),)) for i, v in enumerate(node)]
            return seq if isinstance(node, list) else tuple(seq)
        if isinstance(node, QuantizedTensor):
            return node
        key = path[-1] if path else ""
        if (not hasattr(node, "dtype")
                or not jnp.issubdtype(node.dtype, jnp.floating)
                or getattr(node, "ndim", 0) < 2
                or int(getattr(node, "size", 0)) < min_size
                or key in skip or key.startswith("state_")):
            return node
        axes = (tuple(range(1, node.ndim)) if key in emb
                else tuple(range(node.ndim - 1)))
        qt = quantize_tensor(node, axes=axes, mode=mode)
        dotted = ".".join(path)
        for frag, factor in overrides.items():
            if frag in dotted:
                qt = QuantizedTensor(qt.q, qt.scale * float(factor),
                                     orig_dtype=qt.orig_dtype)
        return qt

    return walk(params, ())


def _resolved_act_dtype(spec) -> str:
    act = _spec_field(spec, "act_dtype", None)
    return str(act) if act else default_act_dtype()


def quantize_model(model, spec=None):
    """The model-level transform: returns an *inference-only quantized
    twin* of ``model`` with int8/fp8 params at rest and activations in
    ``spec.act_dtype`` (platform default when unset). Dispatches on the
    duck-typed model families the serving stack knows:

    - ``CausalLM`` protocol (``init_kv_cache``/``prefill``/``decode``) —
      a new instance of the same class over quantized params, config
      dtype flipped to the activation dtype (KV cache included);
    - layer-API networks (MLN/CG: ``conf`` + ``_params``) — a twin
      network over the same layer configs with quantized params and the
      conf compute dtype flipped (dense/conv forwards dequantize via
      ``dequant_matmul``);
    - a bare params pytree — ``quantize_params``.

    The twin is a distinct object, so ``counted_jit``'s per-model tags
    (and the StableHLO-keyed persistent executable store) key its
    executables separately from the full-precision original's.
    """
    import copy
    import dataclasses

    act = _resolved_act_dtype(spec)
    if all(callable(getattr(model, m, None))
           for m in ("init_kv_cache", "prefill", "decode")) \
            and hasattr(model, "params") and hasattr(model, "config"):
        qp = quantize_params(model.params, spec)
        cfg = dataclasses.replace(model.config, dtype=jnp.dtype(act))
        twin = type(model)(cfg, params=qp)
        twin._precision = precision_of(qp)
        return twin
    if hasattr(model, "_params") and hasattr(model, "conf"):
        twin = type(model)(copy.copy(model.conf))
        twin.conf.dtype = act
        twin._params = quantize_params(model._params, spec)
        twin._updater_state = None  # inference-only: no optimizer state
        twin._initialized = True
        twin._precision = precision_of(twin._params)
        return twin
    if isinstance(model, (dict, list)):
        return quantize_params(model, spec)
    raise TypeError(
        f"don't know how to quantize {type(model).__name__}: expected a "
        "CausalLM-protocol model, a layer-API network (conf + _params), "
        "or a bare params pytree")


# ---------------------------------------------------------------------------
# introspection (serving metadata: /v1/models precision + param-bytes)
# ---------------------------------------------------------------------------

def _leaves(params):
    return jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def precision_of(params) -> str:
    """Dominant storage precision of a params pytree: ``int8``/``fp8``
    when any leaf is quantized, else the widest floating dtype seen."""
    seen = set()
    for leaf in _leaves(params):
        if isinstance(leaf, QuantizedTensor):
            return leaf.mode
        dt = getattr(leaf, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.floating):
            seen.add(str(dt))
    for dt in ("float64", "float32", "bfloat16", "float16"):
        if dt in seen:
            return dt
    return "float32"


def _params_of(model):
    if hasattr(model, "params") and not callable(model.params):
        return model.params
    if hasattr(model, "_params"):
        return model._params
    return model if isinstance(model, (dict, list)) else None


def precision_of_model(model) -> str:
    p = _params_of(model)
    return precision_of(p) if p is not None else "float32"


def param_bytes(params) -> int:
    """At-rest parameter bytes (quantized leaves count q + scale)."""
    total = 0
    for leaf in _leaves(params):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes"):  # covers bf16, which numpy can't name
            total += int(leaf.nbytes)
        elif hasattr(leaf, "dtype") and hasattr(leaf, "size"):
            total += int(np.dtype(str(leaf.dtype)).itemsize) * int(leaf.size)
    return total


def param_bytes_of(model) -> int:
    p = _params_of(model)
    return param_bytes(p) if p is not None else 0
