"""Fluent op namespaces: sd.math / sd.nn / sd.cnn / sd.rnn / sd.loss / ...

Reference: the generated namespace classes `SDMath`, `SDNN`, `SDCNN`, `SDRNN`,
`SDLoss`, `SDImage`, `SDRandom`, `SDLinalg`, `SDBitwise`, `SDBaseOps`
(`org/nd4j/autodiff/samediff/ops/`, generated from contrib/codegen-tools).
Here the registry *is* the codegen source: namespace methods are generated at
import time from registered op names — no Kotlin DSL needed.
"""
from __future__ import annotations

from typing import Sequence

from ..ops.registry import OpRegistry


class _Namespace:
    """Auto-generates methods for a set of registered op names."""

    OPS: Sequence[str] = ()
    ALIASES = {}  # method name -> op name

    def __init__(self, sd):
        self.sd = sd

    def __getattr__(self, item):
        op_name = self.ALIASES.get(item, item)
        if OpRegistry.get().has(op_name):
            def call(*inputs, **kwargs):
                n_outputs = kwargs.pop("n_outputs", 1)
                return self.sd.invoke(op_name, *inputs, n_outputs=n_outputs,
                                      **kwargs)
            call.__name__ = item
            return call
        raise AttributeError(f"{type(self).__name__} has no op {item!r}")

    def __dir__(self):
        reg = OpRegistry.get()
        return sorted(set(list(self.OPS) + list(self.ALIASES)
                          + [n for n in reg.names()]))


class SDMath(_Namespace):
    ALIASES = {
        "pow": "Pow", "floor": "Floor", "log1p": "Log1p",
        "mmul": "matmul", "sub": "subtract", "mul": "multiply",
        "div": "divide", "rsub": "reversesubtract", "rdiv": "reversedivide",
        "neq": "not_equals", "eq": "equals", "gt": "greater",
        "gte": "greater_equal", "lt": "less", "lte": "less_equal",
        "and_": "boolean_and", "or_": "boolean_or", "xor": "boolean_xor",
        "not_": "boolean_not",
    }


class SDNN(_Namespace):
    ALIASES = {
        "linear": "xw_plus_b",
        "bias_add": "biasadd",
        "leaky_relu": "lrelu",
        "multi_head_attention": "multi_head_dot_product_attention",
        "attention": "dot_product_attention",
    }


class SDCNN(_Namespace):
    ALIASES = {
        "conv3d": "conv3dnew",
        "max_pooling2d": "maxpool2d",
        "avg_pooling2d": "avgpool2d",
        "max_pooling3d": "maxpool3dnew",
        "avg_pooling3d": "avgpool3dnew",
        "separable_conv2d": "sconv2d",
        "local_response_normalization": "lrn",
    }


class SDRNN(_Namespace):
    ALIASES = {
        "lstm_layer": "lstmLayer",
        "lstm_cell": "lstmLayerCell",
        "gru_cell": "gruCell",
    }


class SDLoss(_Namespace):
    ALIASES = {
        "mean_squared_error": "mean_sqerr_loss",
        "absolute_difference": "absolute_difference_loss",
        "softmax_cross_entropy": "softmax_cross_entropy_loss",
        "sigmoid_cross_entropy": "sigm_cross_entropy_loss",
        "sparse_softmax_cross_entropy": "sparse_softmax_cross_entropy_loss_with_logits",
        "huber": "huber_loss", "hinge": "hinge_loss", "log": "log_loss",
        "cosine_distance": "cosine_distance_loss",
        "mean_pairwise_squared_error": "mean_pairwssqerr_loss",
        "ctc": "ctc_loss",
    }


class SDImage(_Namespace):
    pass


class SDRandom(_Namespace):
    ALIASES = {
        "uniform": "randomuniform", "normal": "random_normal",
        "bernoulli": "random_bernoulli", "exponential": "random_exponential",
    }


class SDLinalg(_Namespace):
    ALIASES = {"inverse": "matrix_inverse", "det": "matrix_determinant"}


class SDBitwise(_Namespace):
    ALIASES = {
        "and_": "bitwise_and", "or_": "bitwise_or", "xor": "bitwise_xor",
        "left_shift": "shift_bits", "right_shift": "rshift_bits",
    }
