"""SameDiff: define-then-run autodiff graph.

Reference: `org/nd4j/autodiff/samediff/SameDiff.java` (6865 lines),
`SDVariable.java`, and the session interpreters
(`internal/AbstractSession.java:296-391`, `InferenceSession.java`).

TPU-native redesign (SURVEY.md §3.2 note): the reference interprets the graph
op-by-op with a dependency tracker, one JNI call per op. Here the graph is a
lightweight recorded program; execution *traces* it once into a jittable
callable, so XLA compiles the whole graph into a single TPU computation —
`jit` replaces InferenceSession, `jax.grad` replaces per-op `doDiff`
(`DifferentialFunction.diff` / `createGradFunction` at SameDiff.java:4663),
and TF-style Enter/Exit/Merge control-flow frames disappear in favor of
`lax.cond`/`lax.while_loop`/`lax.scan` wrappers.

Variable types mirror the reference's `VariableType`:
VARIABLE (trainable), CONSTANT, PLACEHOLDER, ARRAY (op output).
"""
from __future__ import annotations

import enum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common.dtype import DataType
from ..ndarray.ndarray import NDArray
from ..ops.registry import OpRegistry


class VariableType(enum.Enum):
    VARIABLE = "VARIABLE"      # trainable parameter
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"            # op output


class SDVariable:
    """Symbolic variable handle (reference SDVariable.java).

    Arithmetic on SDVariables records ops into the owning SameDiff graph.
    """

    def __init__(self, sd: "SameDiff", name: str, var_type: VariableType,
                 shape: Optional[Tuple[int, ...]] = None, dtype: str = "float32"):
        self.sd = sd
        self.name = name
        self.var_type = var_type
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    # -- graph-building arithmetic --------------------------------------
    def _bin(self, other, op_name):
        other = self.sd._as_var(other)
        return self.sd._record(op_name, [self, other])

    def __add__(self, o): return self._bin(o, "add")
    def __radd__(self, o): return self.sd._as_var(o)._bin(self, "add")
    def __sub__(self, o): return self._bin(o, "subtract")
    def __rsub__(self, o): return self.sd._as_var(o)._bin(self, "subtract")
    def __mul__(self, o): return self._bin(o, "multiply")
    def __rmul__(self, o): return self.sd._as_var(o)._bin(self, "multiply")
    def __truediv__(self, o): return self._bin(o, "divide")
    def __rtruediv__(self, o): return self.sd._as_var(o)._bin(self, "divide")
    def __pow__(self, o): return self._bin(o, "Pow")
    def __neg__(self): return self.sd._record("neg", [self])
    def __matmul__(self, o): return self._bin(o, "matmul")

    def add(self, o): return self.__add__(o)
    def sub(self, o): return self.__sub__(o)
    def mul(self, o): return self.__mul__(o)
    def div(self, o): return self.__truediv__(o)
    def mmul(self, o): return self._bin(o, "matmul")
    def dot(self, o): return self._bin(o, "dot")

    # comparisons record ops (python == stays identity so vars stay hashable)
    def __lt__(self, o): return self._bin(o, "less")
    def __le__(self, o): return self._bin(o, "less_equal")
    def __gt__(self, o): return self._bin(o, "greater")
    def __ge__(self, o): return self._bin(o, "greater_equal")
    def eq(self, o): return self._bin(o, "equals")
    def neq(self, o): return self._bin(o, "not_equals")
    def lt(self, o): return self.__lt__(o)
    def lte(self, o): return self.__le__(o)
    def gt(self, o): return self.__gt__(o)
    def gte(self, o): return self.__ge__(o)

    def __getitem__(self, idx):
        # basic indexing lowers to the serializable tf_strided_slice op
        # (fixes VERDICT round-1 weak #2: sliced graphs must save/load)
        if isinstance(idx, SDVariable):
            return self.sd._record("gather", [self, idx], axis=0)
        if not isinstance(idx, tuple):
            idx = (idx,)
        spec = []
        for e in idx:
            if isinstance(e, slice):
                if e.start is None and e.stop is None and e.step is None:
                    spec.append(("all",))
                else:
                    spec.append(("slice", e.start, e.stop, e.step or 1))
            elif e is Ellipsis:
                spec.append(("ellipsis",))
            elif e is None:
                spec.append(("newaxis",))
            elif isinstance(e, (int, np.integer)):
                spec.append(("int", int(e)))
            else:
                raise TypeError(f"unsupported index element {e!r}")
        return self.sd._record("tf_strided_slice", [self], spec=spec)

    # common methods routed through the op registry
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.sd._record("reshape", [self], shape=shape)

    def transpose(self, *axes):
        return self.sd._record("transpose", [self],
                               axes=axes if axes else None)

    def sum(self, *dims, keep_dims=False):
        return self.sd._record("reduce_sum", [self], dims=dims or None,
                               keep_dims=keep_dims)

    def mean(self, *dims, keep_dims=False):
        return self.sd._record("reduce_mean", [self], dims=dims or None,
                               keep_dims=keep_dims)

    def max(self, *dims, keep_dims=False):
        return self.sd._record("reduce_max", [self], dims=dims or None,
                               keep_dims=keep_dims)

    def min(self, *dims, keep_dims=False):
        return self.sd._record("reduce_min", [self], dims=dims or None,
                               keep_dims=keep_dims)

    def std(self, *dims, keep_dims=False):
        return self.sd._record("reduce_stdev", [self], dims=dims or None,
                               keep_dims=keep_dims)

    def argmax(self, dim=None):
        return self.sd._record("argmax", [self], dims=dim)

    def norm2(self, *dims):
        return self.sd._record("reduce_norm2", [self], dims=dims or None)

    def cast(self, dtype):
        return self.sd._record("cast", [self], dtype=dtype)

    def rank(self):
        return self.sd._record("rank", [self])

    # -- evaluation ------------------------------------------------------
    def eval(self, placeholders: Dict[str, Any] = None) -> NDArray:
        """Execute the graph up to this variable (reference SDVariable.eval)."""
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def get_arr(self) -> Optional[NDArray]:
        return self.sd.get_arr_for_var(self.name)

    def set_array(self, value):
        self.sd.set_array(self.name, value)

    def rename(self, new_name: str) -> "SDVariable":
        self.sd.rename_variable(self.name, new_name)
        return self

    def __repr__(self):
        return (f"SDVariable(name={self.name!r}, type={self.var_type.value}, "
                f"shape={self.shape}, dtype={self.dtype})")


class TensorArray:
    """Functional TensorArray (reference nd4j TensorArray ops).

    Writes return nothing but rebind the backing SDVariable, matching the
    reference's mutate-in-session semantics at the API level while staying
    purely functional underneath (scatter_update on a dense backing array).
    For trainable accumulation loops prefer `sd.scan`."""

    def __init__(self, sd: "SameDiff", size: int, element_shape, dtype):
        import numpy as _np
        self.sd = sd
        self.size_ = int(size)
        self.element_shape = tuple(element_shape)
        self._var = sd.constant(
            _np.zeros((self.size_,) + self.element_shape, dtype), "ta")

    def write(self, index: int, value) -> "TensorArray":
        v = self.sd._as_var(value)
        expanded = self.sd._record("expand_dims", [v], axis=0)
        self._var = self.sd._record("scatter_upd",
                                    [self._var,
                                     self.sd.constant(
                                         np.asarray([index], np.int32)),
                                     expanded])
        return self

    def read(self, index: int) -> "SDVariable":
        return self.sd._record("tf_strided_slice", [self._var],
                               spec=[("int", int(index))])

    def stack(self) -> "SDVariable":
        return self.sd._record("identity", [self._var])

    def unstack(self, value) -> "TensorArray":
        self._var = self.sd._as_var(value)
        return self

    def size(self) -> int:
        return self.size_


class SameDiffOp:
    """A recorded graph node (reference internal/SameDiffOp.java)."""

    __slots__ = ("name", "op_name", "fn", "inputs", "outputs", "kwargs",
                 "n_outputs", "needs_key")

    def __init__(self, name, op_name, fn, inputs, outputs, kwargs,
                 needs_key=False):
        self.name = name
        self.op_name = op_name
        self.fn = fn
        self.inputs = inputs       # list[str] variable names
        self.outputs = outputs     # list[str] variable names
        self.kwargs = kwargs
        self.needs_key = needs_key  # op consumes a jax PRNG key (dropout etc.)


class SameDiff:
    """The define-then-run graph container + compiler.

    Usage mirrors the reference:
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 784))
        w = sd.var("w", nd.randn(784, 10))
        out = sd.nn.softmax(x.mmul(w))
        result = out.eval({"x": batch})
    """

    def __init__(self, eager: bool = False):
        self._vars: Dict[str, SDVariable] = {}
        self._arrays: Dict[str, jax.Array] = {}   # VARIABLE/CONSTANT values
        self._ops: Dict[str, SameDiffOp] = {}
        self._op_order: List[str] = []
        self._producer: Dict[str, Tuple[str, int]] = {}  # var -> (op, out_idx)
        self._name_counter = 0
        self._scope: List[str] = []
        self._jit_cache: Dict[Any, Callable] = {}
        self._loss_variables: List[str] = []
        self.training_config = None
        self._updater_state = None
        self._listeners: List[Any] = []
        self._rng_seed = 0
        # eager mode (reference SameDiff.java eagerMode flag, :153,379):
        # ops also execute immediately as they are recorded, using the
        # arrays known at record time — a debugging aid; the compiled
        # define-then-run path is unchanged
        self._eager = bool(eager)
        self._eager_vals: Dict[str, jax.Array] = {}
        self._eager_key = None

    # ------------------------------------------------------------------
    @staticmethod
    def create(eager: bool = False) -> "SameDiff":
        return SameDiff(eager=eager)

    # -- eager mode ------------------------------------------------------
    def enable_eager_mode(self):
        """Execute each op as it is defined (reference enableEagerMode)."""
        self._eager = True
        return self

    def is_eager_mode(self) -> bool:
        return self._eager

    def _try_eager(self, node: "SameDiffOp") -> None:
        """Run a just-recorded node on concrete arrays if every input has
        one (VARIABLE/CONSTANT initial values, placeholder arrays set via
        set_array, or earlier eager results). Failures are non-fatal: the
        graph records regardless; eval()/output() recompute properly."""
        vals = []
        for name in node.inputs:
            if name is None:
                vals.append(None)
                continue
            v = self._eager_vals.get(name)
            if v is None:
                v = self._arrays.get(name)
            if v is None:
                return  # e.g. placeholder with no array yet
            vals.append(v)
        kwargs = dict(node.kwargs)
        if node.needs_key:
            if self._eager_key is None:
                self._eager_key = jax.random.key(self._rng_seed)
            self._eager_key, sub = jax.random.split(self._eager_key)
            kwargs["key"] = sub
        try:
            result = node.fn(*vals, **kwargs)
        except Exception:
            return
        self._bind_outputs(node, result, self._eager_vals)

    @staticmethod
    def _bind_outputs(node: "SameDiffOp", result, env: Dict[str, Any]):
        """Store an op's result under its declared output names, raising on
        any arity mismatch (a silent zip would slice rows instead)."""
        if len(node.outputs) == 1:
            if isinstance(result, (tuple, list)):
                raise ValueError(
                    f"op '{node.name}' ({node.op_name}) declares 1 output "
                    f"but returned {len(result)} values; record it with "
                    f"n_outputs={len(result)}")
            env[node.outputs[0]] = result
        else:
            if (not isinstance(result, (tuple, list))
                    or len(result) != len(node.outputs)):
                got = (len(result) if isinstance(result, (tuple, list))
                       else f"a single {type(result).__name__}")
                raise ValueError(
                    f"op '{node.name}' ({node.op_name}) declares "
                    f"{len(node.outputs)} outputs but returned {got}")
            for oname, r in zip(node.outputs, result):
                env[oname] = r

    def eager_arr(self, name: str) -> Optional[NDArray]:
        """The eagerly computed value for a variable, if one exists."""
        v = self._eager_vals.get(name)
        return NDArray(v) if v is not None else None

    # -- naming ----------------------------------------------------------
    def _unique_name(self, base: str) -> str:
        name = "/".join(self._scope + [base]) if self._scope else base
        if name not in self._vars and name not in self._ops:
            return name
        while True:
            self._name_counter += 1
            cand = f"{name}_{self._name_counter}"
            if cand not in self._vars and cand not in self._ops:
                return cand

    def name_scope(self, name: str):
        sd = self

        class _Scope:
            def __enter__(self):
                sd._scope.append(name)
                return sd

            def __exit__(self, *a):
                sd._scope.pop()

        return _Scope()

    # -- variable creation ----------------------------------------------
    def var(self, name: str, value=None, shape=None, dtype="float32",
            initializer=None) -> SDVariable:
        """Trainable VARIABLE (reference SameDiff.var)."""
        name = self._unique_name(name)
        if value is not None:
            arr = value.jax() if isinstance(value, NDArray) else jnp.asarray(value)
            shape = arr.shape
            dtype = str(arr.dtype)
        elif initializer is not None:
            arr = initializer(shape)
            arr = arr.jax() if isinstance(arr, NDArray) else jnp.asarray(arr)
        else:
            arr = jnp.zeros(shape, DataType.from_any(dtype).jax)
        v = SDVariable(self, name, VariableType.VARIABLE, tuple(arr.shape),
                       str(arr.dtype))
        self._vars[name] = v
        self._arrays[name] = arr
        return v

    def constant(self, value, name: str = "const") -> SDVariable:
        name = self._unique_name(name)
        arr = value.jax() if isinstance(value, NDArray) else jnp.asarray(value)
        v = SDVariable(self, name, VariableType.CONSTANT, tuple(arr.shape),
                       str(arr.dtype))
        self._vars[name] = v
        self._arrays[name] = arr
        return v

    def placeholder(self, name: str, shape=None, dtype="float32") -> SDVariable:
        name = self._unique_name(name)
        v = SDVariable(self, name, VariableType.PLACEHOLDER,
                       tuple(shape) if shape else None, dtype)
        self._vars[name] = v
        return v

    # aliases matching the reference API
    def variable(self, *a, **k):
        return self.var(*a, **k)

    def one(self, name, shape):
        return self.constant(jnp.ones(shape), name)

    def zero(self, name, shape):
        return self.constant(jnp.zeros(shape), name)

    # -- graph recording -------------------------------------------------
    def _as_var(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            return x
        return self.constant(x)

    def _record(self, op_name: str, inputs: Sequence[SDVariable],
                n_outputs: int = 1, out_name: str = None,
                out_names: Sequence[str] = None, **kwargs) -> Union[
                    SDVariable, Tuple[SDVariable, ...]]:
        """Record a registered op as a graph node."""
        opdef = OpRegistry.get().lookup(op_name)
        OpRegistry.get().mark_executed(opdef.name)
        return self._record_fn(opdef.fn, inputs, label=op_name,
                               n_outputs=n_outputs, out_name=out_name,
                               out_names=out_names, **kwargs)

    def _record_fn(self, fn: Callable, inputs: Sequence[SDVariable],
                   label: str = "fn", n_outputs: int = 1, out_name: str = None,
                   out_names: Sequence[str] = None, needs_key: bool = False,
                   **kwargs):
        node_name = self._unique_name(label)
        if out_names is not None:
            if len(out_names) != n_outputs:
                raise ValueError(
                    f"out_names has {len(out_names)} entries for "
                    f"n_outputs={n_outputs}")
            bases = list(out_names)
        else:
            bases = [out_name if (out_name and n_outputs == 1) else
                     (f"{out_name}_{i}" if out_name else
                      (node_name if n_outputs == 1 else f"{node_name}:{i}"))
                     for i in range(n_outputs)]
        names = []
        outs = []
        for i, base in enumerate(bases):
            oname = self._unique_name(base) if base in self._vars else base
            if oname in self._vars:
                oname = self._unique_name(base)
            v = SDVariable(self, oname, VariableType.ARRAY)
            self._vars[oname] = v
            self._producer[oname] = (node_name, i)
            names.append(oname)
            outs.append(v)
        out_names = names
        node = SameDiffOp(node_name, label, fn,
                          [v.name if v is not None else None for v in inputs],
                          out_names, kwargs, needs_key=needs_key)
        self._ops[node_name] = node
        self._op_order.append(node_name)
        if self._eager:
            self._try_eager(node)
        return outs[0] if n_outputs == 1 else tuple(outs)

    # -- generic op invocation (sd.op("conv2d", x, w, ...)) --------------
    def invoke(self, op_name: str, *inputs, n_outputs: int = 1, **kwargs):
        # None positional inputs pass through as literals (e.g. optional
        # weights arg of loss ops)
        return self._record(op_name,
                            [self._as_var(i) if i is not None else None
                             for i in inputs],
                            n_outputs=n_outputs, **kwargs)

    # -- tracing / execution ---------------------------------------------
    def _trace(self, var_values: Dict[str, Any],
               placeholder_values: Dict[str, Any],
               requested: Sequence[str], rng_key=None) -> List[Any]:
        """Interpret the recorded graph with jax values.

        Runs once under jit tracing; afterwards XLA owns execution. This is
        the whole-graph compile that replaces AbstractSession's
        dependency-tracked loop (AbstractSession.java:296-391).
        """
        env: Dict[str, Any] = {}
        env.update(var_values)
        env.update(placeholder_values)
        needed = self._dependencies(requested, set(env))
        key = rng_key
        for op_name in self._op_order:
            if op_name not in needed:
                continue
            node = self._ops[op_name]
            args = [env[i] if i is not None else None for i in node.inputs]
            kwargs = dict(node.kwargs)
            if node.needs_key:
                key, sub = jax.random.split(key)
                kwargs["key"] = sub
            result = node.fn(*args, **kwargs)
            self._bind_outputs(node, result, env)
        return [env[r] for r in requested]

    def _dependencies(self, requested: Sequence[str],
                      available: set) -> set:
        """Ops needed (transitively) to produce `requested`."""
        needed_ops = set()
        stack = [r for r in requested if r not in available]
        seen_vars = set()
        while stack:
            var = stack.pop()
            if var in seen_vars:
                continue
            seen_vars.add(var)
            prod = self._producer.get(var)
            if prod is None:
                if var not in available and var not in self._arrays:
                    raise KeyError(
                        f"variable {var!r} has no value and no producer; "
                        f"missing placeholder?")
                continue
            op_name, _ = prod
            needed_ops.add(op_name)
            for i in self._ops[op_name].inputs:
                if i is not None and i not in available:
                    stack.append(i)
        return needed_ops

    def _graph_epoch(self):
        """Cache key component: changes whenever the graph mutates."""
        return (len(self._op_order), len(self._vars))

    def make_function(self, outputs: Sequence[str],
                      placeholders: Sequence[str],
                      with_rng: bool = False) -> Callable:
        """Compile graph → jitted fn(var_dict, placeholder_dict[, key]) -> list."""
        outputs = tuple(outputs)
        placeholders = tuple(placeholders)
        cache_key = (outputs, placeholders, with_rng, self._graph_epoch())
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            from ..runtime.inference import counted_jit
            if with_rng:
                def raw(variables, ph, key):
                    return self._trace(variables, ph, outputs, key)
            else:
                def raw(variables, ph):
                    return self._trace(variables, ph, outputs)
            fn = counted_jit(raw, tag=f"sd:{id(self)}:{cache_key}")
            self._jit_cache[cache_key] = fn
        return fn

    def output(self, placeholders: Dict[str, Any],
               outputs: Sequence[Union[str, SDVariable]]) -> Dict[str, NDArray]:
        """Inference execution (reference SameDiff.output, SameDiff.java:2746).

        Batch-bucketed by default for serving workloads (see
        runtime/inference.py): placeholders sharing a leading batch dim are
        zero-padded up to the bucket and batch-shaped results sliced back.
        Because a SameDiff graph is arbitrary code, bucketing is attempted
        only when `_bucketable_padding` proves the padded trace shape-checks
        and every requested output keeps the batch dim; rng-consuming
        graphs and everything else fall back to the exact shape.
        """
        out_names = [o.name if isinstance(o, SDVariable) else o for o in outputs]
        ph = {k: (v.jax() if isinstance(v, NDArray) else jnp.asarray(v))
              for k, v in (placeholders or {}).items()}
        if any(op.needs_key for op in self._ops.values()):
            fn = self.make_function(out_names, tuple(sorted(ph)),
                                    with_rng=True)
            self._rng_calls = getattr(self, "_rng_calls", 0) + 1
            results = fn(self._arrays, ph,
                         jax.random.key(self._rng_seed + self._rng_calls))
            return {n: NDArray(r) for n, r in zip(out_names, results)}
        fn = self.make_function(out_names, tuple(sorted(ph)))
        ph_p, pad = self._bucketable_padding(fn, ph)
        results = fn(self._arrays, ph_p)
        if pad is not None:
            from ..runtime.inference import slice_batch
            results = slice_batch(results, *pad)
        return {n: NDArray(r) for n, r in zip(out_names, results)}

    def _bucketable_padding(self, fn, ph):
        """(padded placeholders, (n, bucket)) when batch-dim bucketing is
        provably shape-safe for this graph, else (ph, None).

        Safe means: env flag on, every placeholder shares the leading dim,
        and abstract evaluation (jax.eval_shape — no compile) shows every
        requested output maps (n, *rest) -> (bucket, *rest) under padding.
        That rejects batch reductions, transposes, concats along batch,
        and any graph the padded shapes don't trace through; a graph that
        couples rows but preserves shape (e.g. `x - x.mean(0)`) is on the
        caller to exclude by disabling bucketing. The verdict is cached per
        placeholder signature on the compiled fn.
        """
        from ..runtime.inference import maybe_pad_tree
        ph_p, pad = maybe_pad_tree(ph)
        if pad is None:
            return ph, None
        n, b = pad
        cache = getattr(fn, "_pad_gate", None)
        if cache is None:
            cache = fn._pad_gate = {}
        sig = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                           for k, v in ph.items()))
        ok = cache.get(sig)
        if ok is None:
            try:
                exact = jax.eval_shape(fn._jit, self._arrays, ph)
                padded = jax.eval_shape(fn._jit, self._arrays, ph_p)
                ok = all(getattr(e, "ndim", 0) >= 1 and e.shape[0] == n
                         and tuple(p.shape) == (b,) + tuple(e.shape[1:])
                         for e, p in zip(exact, padded))
            except Exception:
                ok = False
            cache[sig] = ok
        return (ph_p, pad) if ok else (ph, None)

    def batch_output(self, placeholders=None, outputs=None):
        return self.output(placeholders or {}, outputs)

    # -- array access ----------------------------------------------------
    def get_arr_for_var(self, name: str) -> Optional[NDArray]:
        arr = self._arrays.get(name)
        if arr is None and self._eager:
            arr = self._eager_vals.get(name)
        return NDArray(arr) if arr is not None else None

    def set_array(self, name: str, value):
        arr = value.jax() if isinstance(value, NDArray) else jnp.asarray(value)
        self._arrays[name] = arr

    def get_variable(self, name: str) -> SDVariable:
        return self._vars[name]

    def has_variable(self, name: str) -> bool:
        return name in self._vars

    def variables(self) -> List[SDVariable]:
        return list(self._vars.values())

    def variable_names(self) -> List[str]:
        return list(self._vars)

    def trainable_variables(self) -> List[SDVariable]:
        return [v for v in self._vars.values()
                if v.var_type == VariableType.VARIABLE]

    def rename_variable(self, old: str, new: str):
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        if old in self._arrays:
            self._arrays[new] = self._arrays.pop(old)
        if old in self._eager_vals:
            self._eager_vals[new] = self._eager_vals.pop(old)
        if old in self._producer:
            self._producer[new] = self._producer.pop(old)
        for node in self._ops.values():
            node.inputs = [new if i == old else i for i in node.inputs]
            node.outputs = [new if o == old else o for o in node.outputs]
        self._jit_cache.clear()

    # -- loss marking ----------------------------------------------------
    def set_loss_variables(self, *names):
        self._loss_variables = [n.name if isinstance(n, SDVariable) else n
                                for n in names]

    def loss_variables(self):
        return list(self._loss_variables)

    # -- gradients -------------------------------------------------------
    def calculate_gradients(self, placeholders: Dict[str, Any],
                            wrt: Sequence[Union[str, SDVariable]],
                            loss: Union[str, SDVariable] = None
                            ) -> Dict[str, NDArray]:
        """Analytic gradients of the (summed) loss wrt given variables.

        Replaces the reference's grad-graph construction
        (SameDiff.createGradFunction, SameDiff.java:4663): jax.grad of the
        traced forward *is* the grad graph.
        """
        wrt_names = [w.name if isinstance(w, SDVariable) else w for w in wrt]
        loss_name = (loss.name if isinstance(loss, SDVariable) else loss) or \
            (self._loss_variables[0] if self._loss_variables else None)
        if loss_name is None:
            raise ValueError("no loss variable set")
        ph = {k: (v.jax() if isinstance(v, NDArray) else jnp.asarray(v))
              for k, v in (placeholders or {}).items()}

        def loss_fn(wrt_vals):
            variables = dict(self._arrays)
            variables.update(wrt_vals)
            out = self._trace(variables, ph, [loss_name])[0]
            return jnp.sum(out)

        grads = jax.grad(loss_fn)({n: self._arrays[n] for n in wrt_names})
        return {n: NDArray(g) for n, g in grads.items()}

    # -- control flow (reference If/While/TensorArray, InferenceSession
    # :828; TPU lowering: lax.cond/while_loop/scan via SubGraph bodies) ---
    def cond(self, pred, true_fn, false_fn, *operands):
        """If-op with sub-graph branches (reference SameDiff.ifCond).

        Branch fns receive one SDVariable per operand (optionally preceded
        by the sub-SameDiff: `lambda sd, x: ...`) and must return the same
        number of outputs. Reverse-mode differentiable."""
        from .subgraph import SubGraph
        tg, n_out_t = SubGraph.record(true_fn, len(operands), "t")
        fg, n_out_f = SubGraph.record(false_fn, len(operands), "f")
        if n_out_t != n_out_f:
            raise ValueError("cond branches must return the same number of "
                             f"outputs ({n_out_t} vs {n_out_f})")
        cap = self._captured_union(tg, fg)
        return self._record("cond",
                            [self._as_var(pred)] +
                            [self._as_var(o) for o in operands] +
                            [self._vars[n] for n in cap],
                            n_outputs=n_out_t, true_graph=tg, false_graph=fg,
                            n_base=len(operands), cap_names=cap)

    def while_loop(self, cond_fn, body_fn, *loop_vars):
        """While-op (reference SameDiff.whileLoop). Forward-mode only —
        use `scan` for trainable loops (XLA while has no reverse-mode)."""
        from .subgraph import SubGraph
        cg, n_c = SubGraph.record(cond_fn, len(loop_vars), "c")
        if n_c != 1:
            raise ValueError("while_loop cond must return one boolean")
        bg, n_b = SubGraph.record(body_fn, len(loop_vars), "b")
        if n_b != len(loop_vars):
            raise ValueError(f"while_loop body must return {len(loop_vars)} "
                             f"values (got {n_b})")
        cap = self._captured_union(cg, bg)
        return self._record("while_loop",
                            [self._as_var(v) for v in loop_vars] +
                            [self._vars[n] for n in cap],
                            n_outputs=len(loop_vars),
                            cond_graph=cg, body_graph=bg,
                            n_loop_vars=len(loop_vars), cap_names=cap)

    def scan(self, body_fn, init, xs=None, length=None, reverse=False):
        """lax.scan as a graph op — the trainable loop (replaces the
        reference's While + TensorArray accumulation pattern).

        body_fn(*carry, *x_slices) -> (*new_carry, *ys). Returns
        (final_carry..., stacked_ys...) SDVariables."""
        from .subgraph import SubGraph
        init = list(init) if isinstance(init, (tuple, list)) else [init]
        xs = list(xs) if isinstance(xs, (tuple, list)) else \
            ([xs] if xs is not None else [])
        bg, n_out = SubGraph.record(body_fn, len(init) + len(xs), "s")
        n_ys = n_out - len(init)
        if n_ys < 0:
            raise ValueError("scan body must return at least the carry")
        cap = list(bg.captured)
        return self._record("scan",
                            [self._as_var(v) for v in init + xs] +
                            [self._vars[n] for n in cap],
                            n_outputs=n_out, body_graph=bg,
                            n_carry=len(init), n_scan=len(xs),
                            cap_names=cap, length=length, reverse=reverse)

    def _captured_union(self, *graphs):
        cap: List[str] = []
        for g in graphs:
            for n in g.captured:
                if n not in cap:
                    if n not in self._vars:
                        raise KeyError(
                            f"control-flow body captured unknown variable "
                            f"{n!r}")
                    cap.append(n)
        return cap

    def tensor_array(self, size: int, element_shape, dtype="float32"):
        """TensorArray analog (reference TensorArray ops, InferenceSession
        :828): a functional fixed-size array backed by an SDVariable."""
        return TensorArray(self, size, element_shape, dtype)

    # -- namespaces (populated in ops_namespaces.py) ---------------------
    @property
    def math(self):
        from .ops_namespaces import SDMath
        return SDMath(self)

    @property
    def nn(self):
        from .ops_namespaces import SDNN
        return SDNN(self)

    @property
    def cnn(self):
        from .ops_namespaces import SDCNN
        return SDCNN(self)

    @property
    def rnn(self):
        from .ops_namespaces import SDRNN
        return SDRNN(self)

    @property
    def loss(self):
        from .ops_namespaces import SDLoss
        return SDLoss(self)

    @property
    def image(self):
        from .ops_namespaces import SDImage
        return SDImage(self)

    @property
    def random(self):
        from .ops_namespaces import SDRandom
        return SDRandom(self)

    @property
    def linalg(self):
        from .ops_namespaces import SDLinalg
        return SDLinalg(self)

    @property
    def bitwise(self):
        from .ops_namespaces import SDBitwise
        return SDBitwise(self)

    # -- training (TrainingSession analog) in training.py ----------------
    def fit(self, *args, **kwargs):
        from .training import fit as _fit
        return _fit(self, *args, **kwargs)

    def set_training_config(self, config):
        self.training_config = config

    def add_listener(self, listener):
        self._listeners.append(listener)

    # -- summary ---------------------------------------------------------
    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} variables, {len(self._ops)} ops"]
        for v in self._vars.values():
            lines.append(f"  {v.var_type.value:<12} {v.name:<30} "
                         f"{v.shape} {v.dtype}")
        for name in self._op_order:
            node = self._ops[name]
            lines.append(f"  OP {node.op_name:<20} {node.inputs} -> "
                         f"{node.outputs}")
        return "\n".join(lines)

    # -- serialization (serialization.py) --------------------------------
    def save(self, path, save_updater_state: bool = False):
        from .serialization import save as _save
        _save(self, path, save_updater_state)

    @staticmethod
    def load(path) -> "SameDiff":
        from .serialization import load as _load
        return _load(path)

    def save_flatbuffers(self, path, save_updater_state: bool = False):
        """Write the reference FlatBuffers format (SameDiff.asFlatBuffers,
        `SameDiff.java:5465-5727`) — loadable by the JVM reference and by
        `modelimport.samediff_fb.load_samediff_fb`."""
        from .serialization import save_flatbuffers as _save_fb
        _save_fb(self, path, save_updater_state)
