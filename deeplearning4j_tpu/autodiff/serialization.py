"""SameDiff graph serialization.

Reference: FlatBuffers save/load (`SameDiff.java:1485, 5465-5727`,
schemas `libnd4j/include/graph/scheme/*.fbs`). TPU-native format: a zip
holding `graph.json` (variables + op nodes by registry name) and `arrays.npz`
(VARIABLE/CONSTANT values + optional updater state) — same round-trip
guarantees (OpValidation checks serialization equality), human-inspectable,
no schema compiler. Ops recorded from raw Python lambdas (``_record_fn``)
are rejected at save time, mirroring the reference's requirement that every
node be a registered op.
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..ops.registry import OpRegistry

FORMAT_VERSION = 1


def _json_safe(v: Any):
    from .subgraph import SubGraph
    if isinstance(v, SubGraph):
        return {"__subgraph__": v.to_dict()}
    if isinstance(v, (jnp.dtype, np.dtype)):
        return {"__dtype__": str(v)}
    if isinstance(v, type) and hasattr(jnp, getattr(v, "__name__", "")):
        return {"__dtype__": v.__name__}
    if isinstance(v, (jnp.ndarray, np.ndarray)):
        return {"__array__": np.asarray(v).tolist(),
                "__adtype__": str(v.dtype)}
    if isinstance(v, (tuple, list)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def _json_restore(v: Any):
    if isinstance(v, dict):
        if "__subgraph__" in v:
            from .subgraph import SubGraph
            return SubGraph.from_dict(v["__subgraph__"])
        if "__dtype__" in v:
            return jnp.dtype(v["__dtype__"])
        if "__array__" in v:
            return jnp.asarray(v["__array__"], dtype=v["__adtype__"])
        return {k: _json_restore(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_json_restore(x) for x in v]
    return v


def save(sd, path, save_updater_state: bool = False):
    from .samediff import SameDiff, VariableType

    reg = OpRegistry.get()
    nodes = []
    for name in sd._op_order:
        node = sd._ops[name]
        if not reg.has(node.op_name):
            raise ValueError(
                f"op {node.name!r} ({node.op_name}) was recorded from a raw "
                f"function and cannot be serialized; register it as a named op")
        nodes.append({
            "name": node.name, "op": node.op_name, "inputs": node.inputs,
            "outputs": node.outputs, "kwargs": _json_safe(node.kwargs),
            "needs_key": node.needs_key,
        })

    graph = {
        "format_version": FORMAT_VERSION,
        "variables": [
            {"name": v.name, "type": v.var_type.value, "shape": v.shape,
             "dtype": v.dtype}
            for v in sd._vars.values()
        ],
        "ops": nodes,
        "op_order": sd._op_order,
        "loss_variables": sd._loss_variables,
        "training_config": _training_config_dict(sd.training_config),
    }

    arrays = {n: np.asarray(a) for n, a in sd._arrays.items()}
    if save_updater_state and sd._updater_state is not None:
        import jax
        flat, _ = jax.tree_util.tree_flatten(sd._updater_state)
        for i, leaf in enumerate(flat):
            arrays[f"__updater__/{i}"] = np.asarray(leaf)

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("graph.json", json.dumps(graph, indent=1))
        buf = io.BytesIO()
        np.savez(buf, **{k.replace("/", "__SLASH__"): v
                         for k, v in arrays.items()})
        z.writestr("arrays.npz", buf.getvalue())


def _training_config_dict(tc):
    if tc is None:
        return None
    return {
        "updater": tc.updater.to_dict(),
        "l1": tc.l1, "l2": tc.l2, "weight_decay": tc.weight_decay,
        "data_set_feature_mapping": list(tc.data_set_feature_mapping),
        "data_set_label_mapping": list(tc.data_set_label_mapping),
        "loss_variables": list(tc.loss_variables),
        "minimize": tc.minimize,
    }


def load(path):
    from ..learning import IUpdater
    from .samediff import SameDiff, SDVariable, SameDiffOp, VariableType
    from .training import TrainingConfig

    with zipfile.ZipFile(path) as z:
        graph = json.loads(z.read("graph.json"))
        with z.open("arrays.npz") as f:
            npz = np.load(io.BytesIO(f.read()), allow_pickle=False)
            arrays = {k.replace("__SLASH__", "/"): npz[k] for k in npz.files}

    sd = SameDiff()
    for vd in graph["variables"]:
        v = SDVariable(sd, vd["name"], VariableType(vd["type"]),
                       tuple(vd["shape"]) if vd["shape"] else None, vd["dtype"])
        sd._vars[v.name] = v
    reg = OpRegistry.get()
    for nd_ in graph["ops"]:
        opdef = reg.lookup(nd_["op"])
        node = SameDiffOp(nd_["name"], nd_["op"], opdef.fn, nd_["inputs"],
                          nd_["outputs"], _json_restore(nd_["kwargs"]),
                          nd_.get("needs_key", False))
        sd._ops[node.name] = node
        for i, oname in enumerate(node.outputs):
            sd._producer[oname] = (node.name, i)
    sd._op_order = graph["op_order"]
    sd._loss_variables = graph.get("loss_variables", [])
    for name, arr in arrays.items():
        if not name.startswith("__updater__/"):
            sd._arrays[name] = jnp.asarray(arr)
    tc = graph.get("training_config")
    if tc:
        sd.training_config = TrainingConfig(
            updater=IUpdater.from_dict(tc["updater"]),
            l1=tc["l1"], l2=tc["l2"], weight_decay=tc["weight_decay"],
            data_set_feature_mapping=tc["data_set_feature_mapping"],
            data_set_label_mapping=tc["data_set_label_mapping"],
            loss_variables=tc["loss_variables"], minimize=tc["minimize"])
    return sd
