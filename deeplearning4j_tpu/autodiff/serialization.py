"""SameDiff graph serialization.

Reference: FlatBuffers save/load (`SameDiff.java:1485, 5465-5727`,
schemas `libnd4j/include/graph/scheme/*.fbs`). TPU-native format: a zip
holding `graph.json` (variables + op nodes by registry name) and `arrays.npz`
(VARIABLE/CONSTANT values + optional updater state) — same round-trip
guarantees (OpValidation checks serialization equality), human-inspectable,
no schema compiler. Ops recorded from raw Python lambdas (``_record_fn``)
are rejected at save time, mirroring the reference's requirement that every
node be a registered op.
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..ops.registry import OpRegistry

FORMAT_VERSION = 1


def _json_safe(v: Any):
    from .subgraph import SubGraph
    if isinstance(v, SubGraph):
        return {"__subgraph__": v.to_dict()}
    if isinstance(v, (jnp.dtype, np.dtype)):
        return {"__dtype__": str(v)}
    if isinstance(v, type) and hasattr(jnp, getattr(v, "__name__", "")):
        return {"__dtype__": v.__name__}
    if isinstance(v, (jnp.ndarray, np.ndarray)):
        return {"__array__": np.asarray(v).tolist(),
                "__adtype__": str(v.dtype)}
    if isinstance(v, (tuple, list)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def _json_restore(v: Any):
    if isinstance(v, dict):
        if "__subgraph__" in v:
            from .subgraph import SubGraph
            return SubGraph.from_dict(v["__subgraph__"])
        if "__dtype__" in v:
            return jnp.dtype(v["__dtype__"])
        if "__array__" in v:
            return jnp.asarray(v["__array__"], dtype=v["__adtype__"])
        return {k: _json_restore(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_json_restore(x) for x in v]
    return v


def save(sd, path, save_updater_state: bool = False):
    from .samediff import SameDiff, VariableType

    reg = OpRegistry.get()
    nodes = []
    for name in sd._op_order:
        node = sd._ops[name]
        if not reg.has(node.op_name):
            raise ValueError(
                f"op {node.name!r} ({node.op_name}) was recorded from a raw "
                f"function and cannot be serialized; register it as a named op")
        nodes.append({
            "name": node.name, "op": node.op_name, "inputs": node.inputs,
            "outputs": node.outputs, "kwargs": _json_safe(node.kwargs),
            "needs_key": node.needs_key,
        })

    graph = {
        "format_version": FORMAT_VERSION,
        "variables": [
            {"name": v.name, "type": v.var_type.value, "shape": v.shape,
             "dtype": v.dtype}
            for v in sd._vars.values()
        ],
        "ops": nodes,
        "op_order": sd._op_order,
        "loss_variables": sd._loss_variables,
        "training_config": _training_config_dict(sd.training_config),
    }

    arrays = {n: np.asarray(a) for n, a in sd._arrays.items()}
    if save_updater_state and sd._updater_state is not None:
        import jax
        flat, _ = jax.tree_util.tree_flatten(sd._updater_state)
        for i, leaf in enumerate(flat):
            arrays[f"__updater__/{i}"] = np.asarray(leaf)

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("graph.json", json.dumps(graph, indent=1))
        buf = io.BytesIO()
        np.savez(buf, **{k.replace("/", "__SLASH__"): v
                         for k, v in arrays.items()})
        z.writestr("arrays.npz", buf.getvalue())


def _training_config_dict(tc):
    if tc is None:
        return None
    return {
        "updater": tc.updater.to_dict(),
        "l1": tc.l1, "l2": tc.l2, "weight_decay": tc.weight_decay,
        "data_set_feature_mapping": list(tc.data_set_feature_mapping),
        "data_set_label_mapping": list(tc.data_set_label_mapping),
        "loss_variables": list(tc.loss_variables),
        "minimize": tc.minimize,
    }


# ---------------------------------------------------------------------------
# Reference-format FlatBuffers writer (the SameDiff.asFlatBuffers analog:
# `SameDiff.java:5465-5727`; schemas `libnd4j/include/graph/scheme/*.fbs`).
# Emits a FlatGraph the JVM reference AND our own reader
# (`modelimport/samediff_fb.py`) can load: variables (VARIABLE/CONSTANT
# with ndarrays, PLACEHOLDER with shapes, ARRAY stubs), FlatNodes with
# inputPaired wiring + per-op arg packing, lossVariables, trainingConfig
# JSON, and per-param UpdaterState.
# ---------------------------------------------------------------------------

_FB_DTYPES = {"bool": 1, "float16": 3, "float32": 5, "float64": 6,
              "int8": 7, "int16": 8, "int32": 9, "int64": 10,
              "uint8": 11, "uint16": 12, "uint32": 13, "uint64": 14,
              "bfloat16": 17}
_FB_OPTYPE_CUSTOM = 21   # OpType.CUSTOM (utils.fbs)
_FB_ALL_DIMS = 2147483647


def _fb_dtype_enum(dt) -> int:
    name = np.dtype(dt).name if not hasattr(dt, "name") else dt.name
    try:
        return _FB_DTYPES[str(name)]
    except KeyError:
        raise ValueError(f"dtype {dt} has no FlatBuffers DType enum")


def _fb_end_vector(b, n):
    try:
        return b.EndVector()          # flatbuffers >= 2.0
    except TypeError:                 # pragma: no cover — legacy runtime
        return b.EndVector(n)


def _fb_str_vector(b, strings):
    offs = [b.CreateString(s) for s in strings]
    b.StartVector(4, len(offs), 4)
    for o in reversed(offs):
        b.PrependUOffsetTRelative(o)
    return _fb_end_vector(b, len(offs))


def _fb_table_vector(b, offs):
    b.StartVector(4, len(offs), 4)
    for o in reversed(offs):
        b.PrependUOffsetTRelative(o)
    return _fb_end_vector(b, len(offs))


def _fb_flat_array(b, arr) -> int:
    """FlatArray table: nd4j shapeInfo [rank, *shape, *strides, extras,
    ews, order] + raw C-order buffer."""
    arr = np.ascontiguousarray(arr)
    rank = arr.ndim
    strides = []
    if rank:
        acc = 1
        for d in reversed(arr.shape):
            strides.insert(0, acc)
            acc *= d
    info = np.asarray([rank, *arr.shape, *strides, 0, 1, 99], np.int64)
    buf_off = b.CreateByteVector(arr.tobytes())
    info_off = b.CreateNumpyVector(info)
    b.StartObject(4)
    b.PrependUOffsetTRelativeSlot(1, buf_off, 0)
    b.PrependUOffsetTRelativeSlot(0, info_off, 0)
    b.PrependInt8Slot(2, _fb_dtype_enum(arr.dtype), 0)
    b.PrependInt8Slot(3, 0, 0)   # ByteOrder.LE
    return b.EndObject()


def _fb_int_pair(b, first, second) -> int:
    b.StartObject(2)
    b.PrependInt32Slot(1, int(second), 0)
    b.PrependInt32Slot(0, int(first), 0)
    return b.EndObject()


# Per-op argument packing: kwargs -> (i_args, t_args, b_args, dimensions).
# These are the exact inverses of the reader's _CONVERTERS
# (modelimport/samediff_fb.py), so writer->reader round-trips losslessly.

def _pack_matmul(kw):
    return dict(i_args=[1 if kw.get("transpose_a") else 0,
                        1 if kw.get("transpose_b") else 0],
                t_args=[float(kw.get("alpha", 1.0))]), \
        {"transpose_a", "transpose_b", "alpha"}


def _pack_softmax(kw):
    return dict(i_args=[int(kw.get("axis", -1))]), {"axis"}


def _pack_reduction(kw):
    out = {}
    dims = kw.get("dims")
    out["dimensions"] = ([int(d) for d in dims] if dims is not None
                         else [_FB_ALL_DIMS])
    if kw.get("keep_dims"):
        out["b_args"] = [True]
    return out, {"dims", "keep_dims"}


_FB_PACKERS = {
    "matmul": _pack_matmul,
    "softmax": _pack_softmax,
    "log_softmax": _pack_softmax,
    "reduce_mean": _pack_reduction, "reduce_sum": _pack_reduction,
    "reduce_max": _pack_reduction, "reduce_min": _pack_reduction,
    "reduce_prod": _pack_reduction, "reduce_norm2": _pack_reduction,
    "argmax": _pack_reduction, "argmin": _pack_reduction,
}


def _fb_pack_kwargs(node, opdef):
    """kwargs -> FlatNode arg vectors; unencodable non-default kwargs fail
    loudly (same contract as the JSON path's raw-function rejection)."""
    import inspect
    packer = _FB_PACKERS.get(node.op_name)
    if packer is not None:
        packed, known = packer(node.kwargs)
        extra = {k: v for k, v in node.kwargs.items() if k not in known}
    else:
        packed, extra = {}, dict(node.kwargs)
    if extra:
        # kwargs equal to the op's declared defaults carry no information
        def _is_default(param, v):
            if param.default is inspect.Parameter.empty:
                return False
            try:
                return bool(param.default == v)
            except (TypeError, ValueError):
                # array-valued kwarg: `default == v` broadcasts and bool()
                # raises "truth value is ambiguous" — treat as non-default
                # so the clean ValueError below names the offending op
                return False
        try:
            sig = inspect.signature(opdef.fn)
            extra = {k: v for k, v in extra.items()
                     if not (k in sig.parameters
                             and _is_default(sig.parameters[k], v))}
        except (TypeError, ValueError):  # builtins without signatures
            pass
    if extra:
        raise ValueError(
            f"op {node.name!r} ({node.op_name}): kwargs {sorted(extra)} "
            f"have no FlatBuffers arg packing; extend _FB_PACKERS (and the "
            f"reader's _CONVERTERS) to serialize this op faithfully")
    return packed


def save_flatbuffers(sd, path, save_updater_state: bool = False):
    """Write the graph as a reference-format FlatGraph ``.fb`` file."""
    import flatbuffers

    from .samediff import VariableType

    reg = OpRegistry.get()
    b = flatbuffers.Builder(4096)

    # ids: op nodes 1..N in recorded order; leaf variables after
    op_ids = {name: i + 1 for i, name in enumerate(sd._op_order)}
    var_ids = {}
    for opn in sd._op_order:
        for idx, out in enumerate(sd._ops[opn].outputs):
            var_ids[out] = (op_ids[opn], idx)
    next_id = len(sd._op_order) + 1
    for v in sd._vars.values():
        if v.name not in var_ids:
            var_ids[v.name] = (next_id, 0)
            next_id += 1

    # -- FlatNodes --------------------------------------------------------
    node_offs = []
    for opn in sd._op_order:
        node = sd._ops[opn]
        if not reg.has(node.op_name):
            raise ValueError(
                f"op {node.name!r} ({node.op_name}) was recorded from a raw "
                f"function and cannot be serialized; register it as a named "
                f"op")
        if node.needs_key:
            raise ValueError(
                f"op {node.name!r} ({node.op_name}) consumes RNG state; "
                f"random ops are not serializable to the reference format")
        packed = _fb_pack_kwargs(node, reg.lookup(node.op_name))

        name_off = b.CreateString(node.name)
        opname_off = b.CreateString(node.op_name)
        outnames_off = _fb_str_vector(b, node.outputs)
        pair_offs = [_fb_int_pair(b, *var_ids[i]) for i in node.inputs]
        inputs_off = _fb_table_vector(b, pair_offs)
        vec_offs = {}
        if packed.get("t_args"):
            vec_offs["t"] = b.CreateNumpyVector(
                np.asarray(packed["t_args"], np.float64))
        if packed.get("i_args"):
            vec_offs["i"] = b.CreateNumpyVector(
                np.asarray(packed["i_args"], np.int64))
        if packed.get("b_args"):
            ba = packed["b_args"]
            b.StartVector(1, len(ba), 1)
            for x in reversed(ba):
                b.PrependBool(bool(x))
            vec_offs["b"] = _fb_end_vector(b, len(ba))
        if packed.get("dimensions"):
            vec_offs["d"] = b.CreateNumpyVector(
                np.asarray(packed["dimensions"], np.int32))

        b.StartObject(24)
        b.PrependInt32Slot(0, op_ids[opn], 0)
        b.PrependUOffsetTRelativeSlot(1, name_off, 0)
        b.PrependInt8Slot(2, _FB_OPTYPE_CUSTOM, 0)
        b.PrependUOffsetTRelativeSlot(6, inputs_off, 0)
        if "t" in vec_offs:
            b.PrependUOffsetTRelativeSlot(8, vec_offs["t"], 0)
        if "i" in vec_offs:
            b.PrependUOffsetTRelativeSlot(9, vec_offs["i"], 0)
        if "b" in vec_offs:
            b.PrependUOffsetTRelativeSlot(10, vec_offs["b"], 0)
        if "d" in vec_offs:
            b.PrependUOffsetTRelativeSlot(11, vec_offs["d"], 0)
        b.PrependUOffsetTRelativeSlot(15, outnames_off, 0)
        b.PrependUOffsetTRelativeSlot(16, opname_off, 0)
        node_offs.append(b.EndObject())

    # -- FlatVariables ----------------------------------------------------
    _VT = {VariableType.VARIABLE: 0, VariableType.CONSTANT: 1,
           VariableType.ARRAY: 2, VariableType.PLACEHOLDER: 3}
    var_offs = []
    for v in sd._vars.values():
        name_off = b.CreateString(v.name)
        arr_off = None
        if v.var_type in (VariableType.VARIABLE, VariableType.CONSTANT):
            if v.name not in sd._arrays:
                raise ValueError(f"{v.var_type.value} {v.name!r} has no "
                                 f"array value to serialize")
            arr_off = _fb_flat_array(b, np.asarray(sd._arrays[v.name]))
        shape_off = None
        if v.shape is not None:
            # dynamic dims (None) are written as -1, the reference marker
            shape_off = b.CreateNumpyVector(
                np.asarray([-1 if s is None else int(s) for s in v.shape],
                           np.int64))
        id_off = _fb_int_pair(b, *var_ids[v.name])
        b.StartObject(10)
        b.PrependUOffsetTRelativeSlot(0, id_off, 0)
        b.PrependUOffsetTRelativeSlot(1, name_off, 0)
        b.PrependInt8Slot(2, _fb_dtype_enum(v.dtype), 0)
        if shape_off is not None:
            b.PrependUOffsetTRelativeSlot(3, shape_off, 0)
        if arr_off is not None:
            b.PrependUOffsetTRelativeSlot(4, arr_off, 0)
        b.PrependInt8Slot(6, _VT[v.var_type], 0)
        var_offs.append(b.EndObject())

    # -- UpdaterState ------------------------------------------------------
    upd_offs = []
    if save_updater_state and sd._updater_state is not None:
        # updater state shape: {state_key: {param_name: array}}
        state = sd._updater_state
        by_param = {}
        for key in sorted(state):
            for pname, arr in state[key].items():
                by_param.setdefault(pname, []).append((key, arr))
        for pname, pairs in sorted(by_param.items()):
            pn_off = b.CreateString(pname)
            keys_off = _fb_str_vector(b, [k for k, _ in pairs])
            vals_off = _fb_table_vector(
                b, [_fb_flat_array(b, np.asarray(a)) for _, a in pairs])
            b.StartObject(3)
            b.PrependUOffsetTRelativeSlot(0, pn_off, 0)
            b.PrependUOffsetTRelativeSlot(1, keys_off, 0)
            b.PrependUOffsetTRelativeSlot(2, vals_off, 0)
            upd_offs.append(b.EndObject())

    # -- FlatGraph ---------------------------------------------------------
    from .samediff import VariableType as _VTenum
    placeholders = [v.name for v in sd._vars.values()
                    if v.var_type == _VTenum.PLACEHOLDER]
    vars_off = _fb_table_vector(b, var_offs)
    nodes_off = _fb_table_vector(b, node_offs)
    ph_off = _fb_str_vector(b, placeholders)
    loss_off = _fb_str_vector(b, sd._loss_variables)
    tc = _training_config_dict(sd.training_config)
    tc_off = b.CreateString(json.dumps(tc)) if tc is not None else None
    upd_vec_off = _fb_table_vector(b, upd_offs) if upd_offs else None

    b.StartObject(9)
    b.PrependUOffsetTRelativeSlot(1, vars_off, 0)
    b.PrependUOffsetTRelativeSlot(2, nodes_off, 0)
    b.PrependUOffsetTRelativeSlot(5, ph_off, 0)
    b.PrependUOffsetTRelativeSlot(6, loss_off, 0)
    if tc_off is not None:
        b.PrependUOffsetTRelativeSlot(7, tc_off, 0)
    if upd_vec_off is not None:
        b.PrependUOffsetTRelativeSlot(8, upd_vec_off, 0)
    b.Finish(b.EndObject())

    with open(path, "wb") as f:
        f.write(bytes(b.Output()))


def load(path):
    from ..learning import IUpdater
    from .samediff import SameDiff, SDVariable, SameDiffOp, VariableType
    from .training import TrainingConfig

    with zipfile.ZipFile(path) as z:
        graph = json.loads(z.read("graph.json"))
        with z.open("arrays.npz") as f:
            npz = np.load(io.BytesIO(f.read()), allow_pickle=False)
            arrays = {k.replace("__SLASH__", "/"): npz[k] for k in npz.files}

    sd = SameDiff()
    for vd in graph["variables"]:
        v = SDVariable(sd, vd["name"], VariableType(vd["type"]),
                       tuple(vd["shape"]) if vd["shape"] else None, vd["dtype"])
        sd._vars[v.name] = v
    reg = OpRegistry.get()
    for nd_ in graph["ops"]:
        opdef = reg.lookup(nd_["op"])
        node = SameDiffOp(nd_["name"], nd_["op"], opdef.fn, nd_["inputs"],
                          nd_["outputs"], _json_restore(nd_["kwargs"]),
                          nd_.get("needs_key", False))
        sd._ops[node.name] = node
        for i, oname in enumerate(node.outputs):
            sd._producer[oname] = (node.name, i)
    sd._op_order = graph["op_order"]
    sd._loss_variables = graph.get("loss_variables", [])
    for name, arr in arrays.items():
        if not name.startswith("__updater__/"):
            sd._arrays[name] = jnp.asarray(arr)
    tc = graph.get("training_config")
    if tc:
        sd.training_config = TrainingConfig(
            updater=IUpdater.from_dict(tc["updater"]),
            l1=tc["l1"], l2=tc["l2"], weight_decay=tc["weight_decay"],
            data_set_feature_mapping=tc["data_set_feature_mapping"],
            data_set_label_mapping=tc["data_set_label_mapping"],
            loss_variables=tc["loss_variables"], minimize=tc["minimize"])
    return sd
