"""Op validation framework: declarative per-op TestCases.

Reference: `nd4j/.../autodiff/validation/OpValidation.java:117-232` —
`validate(TestCase)` checks (a) forward vs expected, (b) analytic vs
numeric gradients (GradCheckUtil central difference), (c) serialization
round-trip equality, and (d) records per-op coverage so CI can report
untested ops. Same four checks here, over registered jax ops and the
SameDiff zip format.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import OpRegistry


class TestCase:
    """Declarative op test (reference validation/TestCase.java)."""

    def __init__(self, op_name: str, inputs: Sequence[Any] = (),
                 kwargs: Optional[Dict] = None):
        self.op_name = op_name
        self.inputs = [jnp.asarray(i) for i in inputs]
        self.kwargs = kwargs or {}
        self.expected: Optional[Any] = None
        self.expected_fn: Optional[Callable] = None
        self.gradient_check = False
        self.serialization_check = True
        self.tolerance = 1e-5
        self.grad_tolerance = 1e-3

    def expect(self, value) -> "TestCase":
        self.expected = value
        return self

    def expect_fn(self, fn: Callable) -> "TestCase":
        """Expected output computed from a reference (numpy) function."""
        self.expected_fn = fn
        return self

    def grad_check(self, enabled: bool = True) -> "TestCase":
        self.gradient_check = enabled
        return self

    def no_serialization(self) -> "TestCase":
        self.serialization_check = False
        return self

    def tol(self, t: float) -> "TestCase":
        self.tolerance = t
        return self


class OpValidation:
    """validate(TestCase) + coverage accounting."""

    _validated: set = set()
    _lock = threading.Lock()

    @staticmethod
    def validate(tc: TestCase) -> Optional[str]:
        """Runs all enabled checks; returns None on success, else the
        failure description (reference returns an error string too)."""
        reg = OpRegistry.get()
        opdef = reg.lookup(tc.op_name)
        errors: List[str] = []

        out = opdef.fn(*tc.inputs, **tc.kwargs)

        # (a) forward vs expected
        expected = tc.expected
        if expected is None and tc.expected_fn is not None:
            expected = tc.expected_fn(*[np.asarray(i) for i in tc.inputs])
        if expected is not None:
            got = out[0] if isinstance(out, (tuple, list)) and \
                not isinstance(expected, (tuple, list)) else out
            try:
                if isinstance(expected, (tuple, list)):
                    for g, e in zip(got, expected):
                        np.testing.assert_allclose(np.asarray(g),
                                                   np.asarray(e),
                                                   atol=tc.tolerance,
                                                   rtol=tc.tolerance)
                else:
                    np.testing.assert_allclose(np.asarray(got),
                                               np.asarray(expected),
                                               atol=tc.tolerance,
                                               rtol=tc.tolerance)
            except AssertionError as e:
                errors.append(f"forward mismatch: {e}")

        # (b) analytic vs numeric gradient (central difference)
        if tc.gradient_check and opdef.differentiable:
            err = OpValidation._grad_check(opdef.fn, tc)
            if err:
                errors.append(err)

        # (c) serialization round-trip through the SameDiff zip format
        if tc.serialization_check:
            err = OpValidation._serialization_check(tc, out)
            if err:
                errors.append(err)

        if not errors:
            with OpValidation._lock:
                OpValidation._validated.add(opdef.name)
            return None
        return f"{tc.op_name}: " + "; ".join(errors)

    @staticmethod
    def _grad_check(fn, tc: TestCase, eps: float = 1e-2) -> Optional[str]:
        # eps balances f32 round-off vs truncation: 1e-2 keeps the central
        # difference's signal above float32 summation noise (GradCheckUtil
        # uses 1e-6 but computes in f64)
        diff_idx = [i for i, x in enumerate(tc.inputs)
                    if jnp.issubdtype(x.dtype, jnp.floating)]
        if not diff_idx:
            return None

        def scalar_fn(*diff_inputs):
            full = list(tc.inputs)
            for i, v in zip(diff_idx, diff_inputs):
                full[i] = v
            out = fn(*full, **tc.kwargs)
            if isinstance(out, (tuple, list)):
                out = out[0]
            return jnp.sum(out.astype(jnp.float64)
                           if jnp.issubdtype(out.dtype, jnp.floating)
                           else out)

        diff_inputs = [tc.inputs[i].astype(jnp.float32) for i in diff_idx]
        analytic = jax.grad(scalar_fn,
                            argnums=tuple(range(len(diff_idx))))(*diff_inputs)
        for k, (x, g) in enumerate(zip(diff_inputs, analytic)):
            flat = np.asarray(x, np.float64).ravel()
            g_flat = np.asarray(g, np.float64).ravel()
            # probe a bounded sample of coordinates (reference subsampling)
            idxs = range(len(flat)) if len(flat) <= 32 else \
                np.linspace(0, len(flat) - 1, 32).astype(int)
            for j in idxs:
                xp = flat.copy()
                xm = flat.copy()
                xp[j] += eps
                xm[j] -= eps
                args_p = list(diff_inputs)
                args_m = list(diff_inputs)
                args_p[k] = jnp.asarray(xp.reshape(x.shape), jnp.float32)
                args_m[k] = jnp.asarray(xm.reshape(x.shape), jnp.float32)
                numeric = (float(scalar_fn(*args_p)) -
                           float(scalar_fn(*args_m))) / (2 * eps)
                if abs(numeric - g_flat[j]) > tc.grad_tolerance * \
                        max(1.0, abs(numeric), abs(g_flat[j])):
                    return (f"gradient mismatch input {k} elem {j}: "
                            f"analytic={g_flat[j]:.6g} "
                            f"numeric={numeric:.6g}")
        return None

    @staticmethod
    def _serialization_check(tc: TestCase, eager_out) -> Optional[str]:
        import io
        import tempfile
        import os
        from .samediff import SameDiff

        sd = SameDiff.create()
        vars_ = [sd.constant(np.asarray(x), f"in{i}")
                 for i, x in enumerate(tc.inputs)]
        try:
            out_var = sd._record(tc.op_name, vars_, **tc.kwargs)
        except Exception as e:
            return f"graph-record failed: {type(e).__name__}: {e}"
        out_var.rename("out")
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "op.sdz")
            try:
                sd.save(path)
                sd2 = SameDiff.load(path)
                r2 = sd2.output({}, ["out"])["out"].numpy()
            except Exception as e:
                return f"serialization round-trip failed: " \
                       f"{type(e).__name__}: {e}"
        ref = eager_out[0] if isinstance(eager_out, (tuple, list)) \
            else eager_out
        try:
            np.testing.assert_allclose(r2, np.asarray(ref),
                                       atol=tc.tolerance, rtol=tc.tolerance)
        except AssertionError as e:
            return f"post-serialization output mismatch: {e}"
        return None

    # -- coverage accounting (reference :117-232) -------------------------
    @staticmethod
    def validated_ops() -> List[str]:
        with OpValidation._lock:
            return sorted(OpValidation._validated)

    @staticmethod
    def coverage_report() -> Dict[str, Any]:
        reg = OpRegistry.get()
        all_ops = set(reg.names())
        validated = set(OpValidation.validated_ops())
        return {
            "validated": len(validated & all_ops),
            "total": len(all_ops),
            "unvalidated": sorted(all_ops - validated),
        }
