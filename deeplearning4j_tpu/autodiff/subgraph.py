"""Serializable sub-graphs for control-flow op bodies.

Reference: the reference executes If/While with sub-graph bodies inside the
session interpreter (`InferenceSession.java:828`, `ADRs/0020 - New Control
flow.md` — bodies are named sub-scopes of the flat graph). TPU-native
redesign: a body is recorded once into a standalone `SubGraph` (registered
ops only, so it serializes), and the parent graph holds it as a static
kwarg of a `cond`/`while_loop`/`scan` node. At execution the sub-graph is
traced *inside* `lax.cond`/`lax.while_loop`/`lax.scan`, so XLA compiles
native control flow — no Enter/Exit/Merge frames, no interpreter.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from ..ops.registry import OpRegistry


class SubGraph:
    """A recorded, registry-only graph fragment: callable + serializable.

    `captured` lists parent-graph variable names the body closed over;
    their values are appended after the explicit args at call time (they
    become implicit constants of the XLA control-flow region, exactly how
    lax handles closure capture)."""

    def __init__(self, placeholders: List[str], outputs: List[str],
                 nodes: List[dict], constants: Dict[str, Any],
                 captured: List[str] = None):
        self.placeholders = placeholders
        self.outputs = outputs
        self.nodes = nodes          # {name, op, inputs, outputs, kwargs}
        self.constants = constants  # name -> jnp array
        self.captured = captured or []

    # -- recording --------------------------------------------------------
    @staticmethod
    def record(fn: Callable, n_args: int, arg_prefix: str = "arg"
               ) -> Tuple["SubGraph", int]:
        """Trace `fn` over fresh placeholders into a SubGraph.

        Returns (subgraph, n_outputs). The body must use only registered
        ops (same rule serialization enforces on the main graph). Parent
        variables referenced by closure are detected and recorded in
        `.captured` — the parent passes their values as extra operands."""
        from .samediff import SameDiff

        sub = SameDiff.create()
        phs = [sub.placeholder(f"{arg_prefix}{i}") for i in range(n_args)]
        out = fn(sub, *phs) if _wants_sd(fn) else fn(*phs)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        reg = OpRegistry.get()
        nodes = []
        internal = {p.name for p in phs} | set(sub._arrays)
        captured: List[str] = []
        for name in sub._op_order:
            node = sub._ops[name]
            if not reg.has(node.op_name):
                raise ValueError(
                    f"control-flow body op {node.name!r} ({node.op_name}) is "
                    f"not a registered op and cannot be serialized")
            if node.needs_key:
                raise ValueError("stochastic ops (dropout etc.) are not "
                                 "supported inside control-flow bodies")
            for i in node.inputs:
                if i is not None and i not in internal and i not in captured:
                    captured.append(i)
            internal.update(node.outputs)
            nodes.append({"name": node.name, "op": node.op_name,
                          "inputs": node.inputs, "outputs": node.outputs,
                          "kwargs": node.kwargs})
        constants = {n: a for n, a in sub._arrays.items()}
        sg = SubGraph([p.name for p in phs], [o.name for o in outs],
                      nodes, constants, captured)
        return sg, len(outs)

    # -- execution --------------------------------------------------------
    def __call__(self, *args):
        reg = OpRegistry.get()
        env: Dict[str, Any] = dict(self.constants)
        for name, val in zip(self.placeholders + self.captured, args):
            env[name] = val
        for nd in self.nodes:
            fn = reg.lookup(nd["op"]).fn
            ins = [env[i] if i is not None else None for i in nd["inputs"]]
            res = fn(*ins, **nd["kwargs"])
            if len(nd["outputs"]) == 1:
                env[nd["outputs"][0]] = res
            else:
                for o, r in zip(nd["outputs"], res):
                    env[o] = r
        outs = tuple(env[o] for o in self.outputs)
        return outs[0] if len(outs) == 1 else outs

    def call_tuple(self, *args) -> Tuple:
        out = self(*args)
        return out if isinstance(out, tuple) else (out,)

    # -- serde ------------------------------------------------------------
    def to_dict(self) -> dict:
        from .serialization import _json_safe
        return {
            "placeholders": self.placeholders,
            "outputs": self.outputs,
            "captured": self.captured,
            "nodes": [{**n, "kwargs": _json_safe(n["kwargs"])}
                      for n in self.nodes],
            "constants": {k: {"data": np.asarray(v).tolist(),
                              "dtype": str(np.asarray(v).dtype)}
                          for k, v in self.constants.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "SubGraph":
        from .serialization import _json_restore
        return SubGraph(
            placeholders=list(d["placeholders"]),
            outputs=list(d["outputs"]),
            nodes=[{**n, "kwargs": _json_restore(n["kwargs"])}
                   for n in d["nodes"]],
            constants={k: jnp.asarray(v["data"], dtype=v["dtype"])
                       for k, v in d["constants"].items()},
            captured=list(d.get("captured", [])))


def _wants_sd(fn) -> bool:
    """Body fns may optionally take the sub-SameDiff as first arg
    (`lambda sd, x: sd.math.sin(x)` style, matching reference bodies that
    receive the SameDiff instance)."""
    import inspect
    try:
        params = list(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        return False
    return bool(params) and params[0] in ("sd", "samediff")
