"""Listener framework for SameDiff training.

Reference: `org/nd4j/autodiff/listeners/` — Listener/BaseListener lifecycle
with impls HistoryListener, ScoreListener, ProfilingListener (chrome trace),
CheckpointListener, OpBenchmarkListener. Op-level hooks don't exist under
XLA (ops fuse into one program), so the surface is iteration/epoch-level —
the hooks the reference's production listeners actually use.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional


class BaseListener:
    def iteration_done(self, sd, iteration: int, epoch: int, loss: float):
        pass

    def epoch_done(self, sd, epoch: int):
        pass


class ScoreListener(BaseListener):
    """Logs loss every N iterations (reference ScoreListener)."""

    def __init__(self, frequency: int = 10, log_fn=print):
        self.frequency = frequency
        self.log_fn = log_fn

    def iteration_done(self, sd, iteration, epoch, loss):
        if iteration % self.frequency == 0:
            self.log_fn(f"iter {iteration} epoch {epoch}: loss {loss:.6f}")


class HistoryListener(BaseListener):
    def __init__(self):
        self.losses: List[float] = []

    def iteration_done(self, sd, iteration, epoch, loss):
        self.losses.append(loss)


class CheckpointListener(BaseListener):
    """Periodic model save with retention (reference CheckpointListener)."""

    def __init__(self, directory: str, save_every_n_iterations: int = None,
                 save_every_n_epochs: int = None, keep_last: int = 3):
        self.directory = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, sd, tag: str):
        path = os.path.join(self.directory, f"checkpoint_{tag}.zip")
        sd.save(path, save_updater_state=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iteration_done(self, sd, iteration, epoch, loss):
        if self.every_iter and iteration > 0 and iteration % self.every_iter == 0:
            self._save(sd, f"iter{iteration}")

    def epoch_done(self, sd, epoch):
        if self.every_epoch and (epoch + 1) % self.every_epoch == 0:
            self._save(sd, f"epoch{epoch}")


class ProfilingListener(BaseListener):
    """Chrome-trace JSON writer (reference ProfilingListener:51).

    Per-op events are folded into one "train_step" event per iteration (XLA
    fuses the graph); deep per-op profiles come from jax.profiler, which this
    listener can trigger for a window of iterations.
    """

    def __init__(self, output_path: str, warmup: int = 1,
                 jax_trace_dir: Optional[str] = None,
                 jax_trace_iters: int = 0):
        self.output_path = output_path
        self.warmup = warmup
        self.events: List[dict] = []
        self._last_ts = None
        self.jax_trace_dir = jax_trace_dir
        self.jax_trace_iters = jax_trace_iters
        self._tracing = False

    def iteration_done(self, sd, iteration, epoch, loss):
        now = time.time() * 1e6  # chrome trace uses microseconds
        if self._last_ts is not None and iteration >= self.warmup:
            self.events.append({
                "name": "train_step", "ph": "X", "pid": 0, "tid": 0,
                "ts": self._last_ts, "dur": now - self._last_ts,
                "args": {"iteration": iteration, "epoch": epoch, "loss": loss},
            })
        self._last_ts = now
        if self.jax_trace_dir and self.jax_trace_iters:
            import jax
            if iteration == self.warmup and not self._tracing:
                jax.profiler.start_trace(self.jax_trace_dir)
                self._tracing = True
            elif self._tracing and iteration >= self.warmup + self.jax_trace_iters:
                jax.profiler.stop_trace()
                self._tracing = False

    def epoch_done(self, sd, epoch):
        with open(self.output_path, "w") as f:
            json.dump({"traceEvents": self.events}, f)
