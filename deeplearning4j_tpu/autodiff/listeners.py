"""Listener framework for SameDiff training.

Reference: `org/nd4j/autodiff/listeners/` — Listener/BaseListener lifecycle
with impls HistoryListener, ScoreListener, ProfilingListener (chrome trace),
CheckpointListener, OpBenchmarkListener. Op-level hooks don't exist under
XLA (ops fuse into one program), so the surface is iteration/epoch-level —
the hooks the reference's production listeners actually use.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional


class BaseListener:
    def iteration_done(self, sd, iteration: int, epoch: int, loss: float):
        pass

    def epoch_done(self, sd, epoch: int):
        pass


class ScoreListener(BaseListener):
    """Logs loss every N iterations (reference ScoreListener)."""

    def __init__(self, frequency: int = 10, log_fn=print):
        self.frequency = frequency
        self.log_fn = log_fn

    def iteration_done(self, sd, iteration, epoch, loss):
        if iteration % self.frequency == 0:
            self.log_fn(f"iter {iteration} epoch {epoch}: loss {loss:.6f}")


class HistoryListener(BaseListener):
    def __init__(self):
        self.losses: List[float] = []

    def iteration_done(self, sd, iteration, epoch, loss):
        self.losses.append(loss)


class CheckpointListener(BaseListener):
    """Periodic model save with retention (reference CheckpointListener)."""

    def __init__(self, directory: str, save_every_n_iterations: int = None,
                 save_every_n_epochs: int = None, keep_last: int = 3):
        self.directory = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, sd, tag: str):
        path = os.path.join(self.directory, f"checkpoint_{tag}.zip")
        sd.save(path, save_updater_state=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iteration_done(self, sd, iteration, epoch, loss):
        if self.every_iter and iteration > 0 and iteration % self.every_iter == 0:
            self._save(sd, f"iter{iteration}")

    def epoch_done(self, sd, epoch):
        if self.every_epoch and (epoch + 1) % self.every_epoch == 0:
            self._save(sd, f"epoch{epoch}")


class ProfilingListener(BaseListener):
    """Chrome-trace JSON writer (reference ProfilingListener:51).

    Per-op events are folded into one "train_step" event per iteration (XLA
    fuses the graph); deep per-op profiles come from jax.profiler, which this
    listener can trigger for a window of iterations.
    """

    def __init__(self, output_path: str, warmup: int = 1,
                 jax_trace_dir: Optional[str] = None,
                 jax_trace_iters: int = 0):
        self.output_path = output_path
        self.warmup = warmup
        self.events: List[dict] = []
        self._last_ts = None
        self.jax_trace_dir = jax_trace_dir
        self.jax_trace_iters = jax_trace_iters
        self._tracing = False

    def iteration_done(self, sd, iteration, epoch, loss):
        now = time.time() * 1e6  # chrome trace uses microseconds
        if self._last_ts is not None and iteration >= self.warmup:
            self.events.append({
                "name": "train_step", "ph": "X", "pid": 0, "tid": 0,
                "ts": self._last_ts, "dur": now - self._last_ts,
                "args": {"iteration": iteration, "epoch": epoch, "loss": loss},
            })
        self._last_ts = now
        if self.jax_trace_dir and self.jax_trace_iters:
            import jax
            if iteration == self.warmup and not self._tracing:
                jax.profiler.start_trace(self.jax_trace_dir)
                self._tracing = True
            elif self._tracing and iteration >= self.warmup + self.jax_trace_iters:
                jax.profiler.stop_trace()
                self._tracing = False

    def epoch_done(self, sd, epoch):
        with open(self.output_path, "w") as f:
            json.dump({"traceEvents": self.events}, f)


class UIListener(BaseListener):
    """Streams SameDiff training into the UI StatsStorage (reference
    autodiff/listeners/impl/UIListener.java writing the same storage the
    DL4J StatsListener feeds)."""

    def __init__(self, storage, session_id: str = None,
                 update_frequency: int = 1):
        import time as _t
        self.storage = storage
        self.session_id = session_id or f"samediff_{int(_t.time())}"
        self.update_frequency = update_frequency
        self._static_sent = False

    def iteration_done(self, sd, iteration, epoch, loss):
        import numpy as _np
        if iteration % self.update_frequency:
            return
        if not self._static_sent:
            self.storage.put_static_info(self.session_id, {
                "model_class": "SameDiff",
                "n_layers": len(sd._ops),
                "n_params": int(sum(
                    _np.prod(_np.asarray(a).shape)
                    for n, a in sd._arrays.items()
                    if sd._vars[n].var_type.value == "VARIABLE")),
                "start_time": time.time(),
            })
            self._static_sent = True
        record = {"iteration": int(iteration), "epoch": int(epoch),
                  "time": time.time(), "score": float(loss), "params": {}}
        for name, arr in sd._arrays.items():
            v = sd._vars.get(name)
            if v is None or v.var_type.value != "VARIABLE":
                continue
            a = _np.asarray(arr)
            record["params"][name] = {
                "l2": float(_np.linalg.norm(a)),
                "mean_mag": float(_np.mean(_np.abs(a)))}
        self.storage.put_update(self.session_id, record)


class ExecDebuggingListener(BaseListener):
    """Logs per-iteration loss + variable summaries (reference
    ExecDebuggingListener; per-op prints don't exist under whole-graph XLA
    compilation, so the granularity is per-step)."""

    def __init__(self, log_fn=print, print_arrays: bool = False):
        self.log_fn = log_fn
        self.print_arrays = print_arrays

    def iteration_done(self, sd, iteration, epoch, loss):
        import numpy as _np
        self.log_fn(f"[exec-debug] iter={iteration} epoch={epoch} "
                    f"loss={loss:.6f}")
        if self.print_arrays:
            for name, arr in sd._arrays.items():
                a = _np.asarray(arr)
                self.log_fn(f"  {name}: shape={a.shape} "
                            f"min={a.min():.4g} max={a.max():.4g} "
                            f"mean={a.mean():.4g}")


class OpBenchmarkListener(BaseListener):
    """Wall-time per training step (reference OpBenchmarkListener — per-op
    times fuse away under XLA; the jitted step IS the op)."""

    def __init__(self):
        self.times: List[float] = []
        self._last = None

    def iteration_done(self, sd, iteration, epoch, loss):
        now = time.perf_counter()
        if self._last is not None:
            self.times.append(now - self._last)
        self._last = now

    def average_seconds(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0


class ArraySavingListener(BaseListener):
    """Dumps variable arrays every N iterations (reference
    ArraySavingListener) for offline diffing."""

    def __init__(self, directory: str, frequency: int = 1):
        self.directory = directory
        self.frequency = frequency
        os.makedirs(directory, exist_ok=True)

    def iteration_done(self, sd, iteration, epoch, loss):
        import numpy as _np
        if iteration % self.frequency:
            return
        path = os.path.join(self.directory, f"iter_{iteration}.npz")
        _np.savez(path, **{n.replace("/", "__"): _np.asarray(a)
                           for n, a in sd._arrays.items()})
