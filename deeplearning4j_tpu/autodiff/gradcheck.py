"""Gradient checking.

Reference: `autodiff/validation/GradCheckUtil.java` (675 lines) — central
difference vs analytic gradients, the gate for every op's `doDiff`. Here the
analytic side is jax.grad and the check validates *our graph recording +
trace* (and any custom Pallas kernels' VJPs) rather than per-op rules.
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(fn: Callable, args: Sequence, eps: float = 1e-3,
                    rtol: float = 5e-3, atol: float = 2e-3,
                    argnums: Sequence[int] = None) -> bool:
    """Central-difference check of jax.grad(fn) for scalar-output fn.

    Matches GradCheckUtil's method (eps=1e-6, f64 there). TPU-native f32
    limits the numeric side to ~1e-3 absolute accuracy (rounding error
    ~6e-8*|f|/eps), so tolerances are wider; genuinely wrong gradients are
    off by O(1) and still fail loudly.
    """
    args = [jnp.asarray(a, jnp.float32) if not isinstance(a, jnp.ndarray)
            else a for a in args]
    argnums = tuple(argnums) if argnums is not None else tuple(range(len(args)))
    analytic = jax.grad(fn, argnums=argnums)(*args)
    if not isinstance(analytic, tuple):
        analytic = (analytic,)
    for k, argnum in enumerate(argnums):
        a = np.asarray(args[argnum], np.float64)
        flat = a.ravel()
        num = np.zeros_like(flat)
        for i in range(flat.size):
            plus, minus = flat.copy(), flat.copy()
            plus[i] += eps
            minus[i] -= eps
            args_p = list(args)
            args_m = list(args)
            args_p[argnum] = jnp.asarray(plus.reshape(a.shape), jnp.float32)
            args_m[argnum] = jnp.asarray(minus.reshape(a.shape), jnp.float32)
            num[i] = (float(fn(*args_p)) - float(fn(*args_m))) / (2 * eps)
        ana = np.asarray(analytic[k], np.float64).ravel()
        if not np.allclose(ana, num, rtol=rtol, atol=max(atol, eps)):
            max_err = np.max(np.abs(ana - num))
            raise AssertionError(
                f"gradient mismatch on arg {argnum}: max abs err {max_err:.3e}\n"
                f"analytic: {ana}\nnumeric:  {num}")
    return True


def check_samediff_gradients(sd, placeholders: Dict, loss_name: str,
                             wrt: Sequence[str] = None, eps: float = 1e-3,
                             rtol: float = 5e-3, atol: float = 2e-3) -> bool:
    """Gradient-check a recorded SameDiff graph's loss wrt its VARIABLEs."""
    wrt = list(wrt) if wrt is not None else \
        [v.name for v in sd.trainable_variables()]
    ph = {k: jnp.asarray(getattr(v, "jax", lambda: v)())
          if hasattr(v, "jax") else jnp.asarray(v)
          for k, v in placeholders.items()}

    for name in wrt:
        base = sd._arrays[name]

        def loss_of(x, _name=name):
            variables = dict(sd._arrays)
            variables[_name] = x
            out = sd._trace(variables, ph, [loss_name])[0]
            return jnp.sum(out)

        check_gradients(loss_of, [base], eps=eps, rtol=rtol, atol=atol)
    return True
