"""SameDiff training: TrainingConfig + fit loop.

Reference: `org/nd4j/autodiff/samediff/TrainingConfig.java` (569 lines) and
`internal/TrainingSession.java:74` (`trainingIteration`).

TPU-native: the whole training iteration — forward, backward, regularization,
updater, parameter update — is ONE jitted function, so XLA fuses it into a
single TPU program per step (the reference runs a Java interpreter loop with
one native call per op). Parameters are donated to avoid HBM copies.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..learning import Adam, IUpdater
from ..ndarray.ndarray import NDArray


@dataclasses.dataclass
class TrainingConfig:
    updater: IUpdater = dataclasses.field(default_factory=Adam)
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    data_set_feature_mapping: Sequence[str] = ()
    data_set_label_mapping: Sequence[str] = ()
    loss_variables: Sequence[str] = ()
    minimize: bool = True
    #: activation rematerialization: "none" keeps all forward activations
    #: for backward; "layer"/"dots_saveable" wrap the whole loss graph in
    #: jax.checkpoint (the graph has no layer boundaries to cut at, so both
    #: modes recompute; "dots_saveable" keeps matmul outputs). None resolves
    #: the Environment default (DL4J_TPU_REMAT).
    remat: Optional[str] = None
    #: micro-batches per optimizer step (gradient accumulation over the
    #: leading placeholder dim); 0/None resolves the Environment default.
    #: Exact full-batch equivalence holds for batch-MEAN-reduced losses.
    grad_accum: int = 0


@dataclasses.dataclass
class LossCurve:
    losses: List[float]

    def mean_loss(self):
        return sum(self.losses) / max(len(self.losses), 1)


@dataclasses.dataclass
class History:
    """Reference `autodiff/listeners/records/History.java`."""
    loss_curves: List[LossCurve]
    epochs: int
    iterations: int
    train_time_ms: float

    def final_loss(self) -> float:
        return self.loss_curves[-1].losses[-1] if self.loss_curves else float("nan")


def build_train_step(sd, config: TrainingConfig,
                     placeholders: Sequence[str]) -> Callable:
    """Compile one training iteration into a single jitted step.

    step(params, updater_state, iteration, ph) -> (params', state', loss)
    """
    loss_names = list(config.loss_variables or sd.loss_variables())
    if not loss_names:
        raise ValueError("TrainingConfig has no loss variables")
    trainable = [v.name for v in sd.trainable_variables()]
    placeholders = tuple(placeholders)

    def loss_fn(params, ph):
        variables = dict(sd._arrays)
        variables.update(params)
        outs = sd._trace(variables, ph, loss_names)
        loss = sum(jnp.sum(o) for o in outs)
        if config.l2 > 0:
            loss = loss + config.l2 * sum(jnp.sum(p * p)
                                          for p in params.values())
        if config.l1 > 0:
            loss = loss + config.l1 * sum(jnp.sum(jnp.abs(p))
                                          for p in params.values())
        return loss

    from ..common.environment import environment
    remat = getattr(config, "remat", None)
    if remat is None:
        remat = environment().training_remat()
    if remat and remat != "none":
        # rematerialize: backward recomputes the graph's forward instead of
        # storing activations (SameDiff graphs have no layer boundaries, so
        # the whole loss is one checkpoint region — the models/bert.py recipe)
        policy = (jax.checkpoint_policies.dots_saveable
                  if remat == "dots_saveable" else None)
        loss_fn = jax.checkpoint(loss_fn, policy=policy)

    k = int(getattr(config, "grad_accum", 0) or 0)
    if k <= 0:
        k = environment().training_grad_accum()

    def grads_of(params, ph):
        if k <= 1:
            return jax.value_and_grad(loss_fn)(params, ph)
        # gradient accumulation: scan k micro-batches (leading placeholder
        # dim split), average grads/loss — exact for batch-mean losses

        def split(a):
            if a.shape[0] % k:
                raise ValueError(
                    f"grad_accum={k} does not divide batch dim "
                    f"{a.shape[0]} (shape {a.shape})")
            return a.reshape((k, a.shape[0] // k) + a.shape[1:])

        mph = jax.tree_util.tree_map(split, ph)

        def body(carry, micro):
            gsum, lsum = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, micro)
            return (jax.tree_util.tree_map(jnp.add, gsum, grads),
                    lsum + loss), None

        zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
        (gsum, lsum), _ = jax.lax.scan(
            body, (zero_g, jnp.zeros((), jnp.float32)), mph)
        return lsum / k, jax.tree_util.tree_map(lambda g: g / k, gsum)

    def step(params, updater_state, iteration, ph):
        loss, grads = grads_of(params, ph)
        update, updater_state = config.updater.apply(grads, updater_state,
                                                     iteration)
        sign = 1.0 if config.minimize else -1.0
        # decoupled (AdamW-style) weight decay, independent of the lr schedule
        new_params = jax.tree_util.tree_map(
            lambda p, u: p - sign * u.astype(p.dtype)
            - config.weight_decay * p,
            params, update)
        return new_params, updater_state, loss

    # counted_jit: SameDiff train steps now register compile events
    # (dl4j_compiles_total{kind=sdtrain}) and restart-compile through the
    # persistent-compilation-cache backstop like every other entry point
    from ..runtime.inference import counted_jit
    return counted_jit(step, tag=f"sdtrain:{id(sd)}:k{k}:{remat}",
                       donate_argnums=(0, 1)), trainable


def fit(sd, iterator=None, num_epochs: int = 1, placeholders_fn=None,
        listeners: Sequence[Any] = ()) -> History:
    """Train from a DataSetIterator (reference SameDiff.fit, :1692-1766).

    The iterator yields DataSet objects; features/labels are bound to
    placeholders via TrainingConfig mappings.
    """
    config = sd.training_config
    if config is None:
        raise ValueError("call set_training_config first")
    f_map = list(config.data_set_feature_mapping)
    l_map = list(config.data_set_label_mapping)
    ph_names = tuple(sorted(f_map + l_map))

    step, trainable = build_train_step(sd, config, ph_names)
    params = {n: sd._arrays[n] for n in trainable}
    state = sd._updater_state if sd._updater_state is not None \
        else config.updater.init(params)

    all_listeners = list(sd._listeners) + list(listeners)
    curves = []
    iteration = 0
    t0 = time.time()

    from ..common.environment import environment
    from ..common.tracing import span
    reg = environment().metrics()
    tel = reg.enabled
    if tel:
        steps_c = reg.counter("dl4j_train_steps_total",
                              "Optimizer steps taken",
                              labels=("path",)).labels(path="samediff")
        samples_c = reg.counter("dl4j_train_samples_total",
                                "Training samples consumed",
                                labels=("path",)).labels(path="samediff")
        loss_g = reg.gauge("dl4j_train_loss", "Most recent training loss")

    for epoch in range(num_epochs):
        losses = []
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            with span("train/data_wait"):
                ph = {}
                feats = ds.features if isinstance(ds.features, (list, tuple)) \
                    else [ds.features]
                labs = ds.labels if isinstance(ds.labels, (list, tuple)) \
                    else [ds.labels]
                for name, arr in zip(f_map, feats):
                    ph[name] = arr.jax() if isinstance(arr, NDArray) else jnp.asarray(arr)
                for name, arr in zip(l_map, labs):
                    ph[name] = arr.jax() if isinstance(arr, NDArray) else jnp.asarray(arr)
            with span("train/dispatch"):
                params, state, loss = step(params, state, iteration, ph)
            # donated buffers are now invalid — repoint graph arrays before
            # listeners (which may call sd.output / save) run
            for n, p in params.items():
                sd._arrays[n] = p
            sd._updater_state = state
            with span("train/device"):
                loss_val = float(loss)  # host sync: device time lands here
            losses.append(loss_val)
            sd._last_batch_size = next(
                (int(v.shape[0]) for v in ph.values()
                 if getattr(v, "ndim", 0) >= 1), 0)
            if tel:
                steps_c.inc()
                samples_c.inc(sd._last_batch_size)
                loss_g.set(loss_val)
            for lst in all_listeners:
                if hasattr(lst, "iteration_done"):
                    lst.iteration_done(sd, iteration, epoch, loss_val)
            iteration += 1
        curves.append(LossCurve(losses))
        for lst in all_listeners:
            if hasattr(lst, "epoch_done"):
                lst.epoch_done(sd, epoch)
    # write trained params back into the graph
    for n, p in params.items():
        sd._arrays[n] = p
    sd._updater_state = state
    return History(curves, num_epochs, iteration, (time.time() - t0) * 1000)
