"""Define-then-run autodiff graph layer (SameDiff analog)."""
from .samediff import SameDiff, SDVariable, VariableType  # noqa: F401
from .training import TrainingConfig, History  # noqa: F401
