"""Minimal protobuf wire-format codec for model import.

The import layer parses TensorFlow GraphDef (.pb) and ONNX (.onnx) files
without requiring the tensorflow/onnx runtimes: both formats are plain
protobuf, and the wire format is simple (varint-keyed fields with four wire
types). Reference counterpart: the generated protobuf classes under
`nd4j/nd4j-backends/nd4j-api-parent/nd4j-api/src/main/java/org/nd4j/ir/`
and the shaded TF/ONNX protos the Kotlin importers consume.

This is a *schemaless* decoder: `decode()` returns `{field_number: [values]}`
where each value is an int (varint), bytes (length-delimited), or raw 4/8
byte little-endian scalars. The framework-specific importers interpret
fields by number according to the public .proto schemas.

A tiny encoder is included so tests can synthesize ONNX files without the
onnx package.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

Value = Union[int, bytes]
Fields = Dict[int, List[Value]]

# wire types
VARINT = 0
FIXED64 = 1
LENGTH = 2
FIXED32 = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def decode(buf: bytes) -> Fields:
    """Decode one message into {field_number: [raw values]}.

    varint fields -> int; fixed32/fixed64 -> bytes (4/8, little-endian);
    length-delimited -> bytes (sub-message, string, or packed array —
    caller interprets).
    """
    fields: Fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == VARINT:
            val, pos = _read_varint(buf, pos)
        elif wtype == LENGTH:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == FIXED32:
            val = buf[pos:pos + 4]
            pos += 4
        elif wtype == FIXED64:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wtype} (field {fnum})")
        fields.setdefault(fnum, []).append(val)
    return fields


# ---------------------------------------------------------------- accessors
def first(fields: Fields, num: int, default=None):
    vals = fields.get(num)
    return vals[0] if vals else default


def all_(fields: Fields, num: int) -> List[Value]:
    return fields.get(num, [])


def as_str(val, default: str = "") -> str:
    if val is None:
        return default
    return val.decode("utf-8", errors="replace")


def as_int64(val: int) -> int:
    """Interpret a raw varint as two's-complement int64."""
    if val >= 1 << 63:
        val -= 1 << 64
    return val


def as_float32(val: bytes) -> float:
    return struct.unpack("<f", val)[0]


def as_float64(val: bytes) -> float:
    return struct.unpack("<d", val)[0]


def ints(fields: Fields, num: int, signed: bool = True) -> List[int]:
    """Repeated int field: handles both packed and unpacked encodings."""
    out: List[int] = []
    for v in fields.get(num, []):
        if isinstance(v, bytes):  # packed
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(as_int64(x) if signed else x)
        else:
            out.append(as_int64(v) if signed else v)
    return out


def floats(fields: Fields, num: int) -> List[float]:
    """Repeated float field (packed fixed32 or unpacked)."""
    out: List[float] = []
    for v in fields.get(num, []):
        if isinstance(v, bytes) and len(v) != 4:
            out.extend(struct.unpack(f"<{len(v)//4}f", v))
        elif isinstance(v, bytes):
            out.append(as_float32(v))
        else:  # should not happen for float fields
            out.append(float(v))
    return out


def doubles(fields: Fields, num: int) -> List[float]:
    out: List[float] = []
    for v in fields.get(num, []):
        if isinstance(v, bytes) and len(v) != 8:
            out.extend(struct.unpack(f"<{len(v)//8}d", v))
        elif isinstance(v, bytes):
            out.append(as_float64(v))
    return out


# ---------------------------------------------------------------- encoder
class Writer:
    """Append-only protobuf message writer (for test fixtures)."""

    def __init__(self):
        self._parts: List[bytes] = []

    @staticmethod
    def _varint(v: int) -> bytes:
        if v < 0:
            v += 1 << 64
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def int_(self, num: int, v: int) -> "Writer":
        self._parts.append(self._varint(num << 3 | VARINT))
        self._parts.append(self._varint(v))
        return self

    def float_(self, num: int, v: float) -> "Writer":
        self._parts.append(self._varint(num << 3 | FIXED32))
        self._parts.append(struct.pack("<f", v))
        return self

    def bytes_(self, num: int, v: bytes) -> "Writer":
        self._parts.append(self._varint(num << 3 | LENGTH))
        self._parts.append(self._varint(len(v)))
        self._parts.append(v)
        return self

    def str_(self, num: int, v: str) -> "Writer":
        return self.bytes_(num, v.encode("utf-8"))

    def msg(self, num: int, w: "Writer") -> "Writer":
        return self.bytes_(num, w.build())

    def packed_ints(self, num: int, vals) -> "Writer":
        body = b"".join(self._varint(v) for v in vals)
        return self.bytes_(num, body)

    def build(self) -> bytes:
        return b"".join(self._parts)
