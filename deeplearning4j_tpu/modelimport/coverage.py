"""Importer coverage accounting against the reference mapping rulesets.

The reference ships declarative import rules per framework op
(`nd4j/samediff-import/samediff-import-tensorflow/src/main/resources/
tensorflow-mapping-ruleset.pbtxt`, `.../samediff-import-onnx/.../
onnx-mapping-ruleset.pbtxt`).  This module parses those rulesets'
``inputFrameworkOpName`` inventories and diffs them against the registered
mapping rules, the same enforced-parity pattern as
``tests/test_op_parity.py`` for the op registry.

Three buckets:
- mapped: a `@mapper` rule exists
- structural: handled below the mapping layer (parser constants/
  placeholders, while-frame lowering) or precluded by the frozen-graph
  import contract
- exempt: not expressible as a static-shape XLA program (data-dependent
  output shapes) or requiring runtime graph state; each carries a reason
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Set

# Reference checkout root: overridable so coverage accounting works on
# any layout, not just this build image.
REFERENCE_ROOT = os.environ.get("REFERENCE_ROOT", "/root/reference")
TF_RULESET = os.path.join(
    REFERENCE_ROOT, "nd4j/samediff-import/samediff-import-tensorflow/"
    "src/main/resources/tensorflow-mapping-ruleset.pbtxt")
ONNX_RULESET = os.path.join(
    REFERENCE_ROOT, "nd4j/samediff-import/samediff-import-onnx/"
    "src/main/resources/onnx-mapping-ruleset.pbtxt")

# Handled below the mapping-rule layer.
TF_STRUCTURAL: Dict[str, str] = {
    "Const": "parser folds to ctx.const_np (tf/parser.py)",
    "Placeholder": "parser binds to SameDiff placeholders (tf/parser.py)",
    "PlaceholderWithDefault": "parser binds placeholder (tf/parser.py)",
    "LoopCond": "consumed by while-frame lowering (tf/while_frames.py)",
    "NextIteration": "consumed by while-frame lowering "
                     "(tf/while_frames.py)",
    "Variable": "frozen inference graphs only: freezing rewrites "
                "variables to Const (import contract, tf/importer.py)",
    "VariableV2": "frozen inference graphs only (see Variable)",
}

# Not expressible as a static-shape XLA program / runtime state.
TF_EXEMPT: Dict[str, str] = {
    "Unique": "output shape is data-dependent (# distinct values)",
    "UniqueV2": "data-dependent output shape",
    "UniqueWithCounts": "data-dependent output shape",
    "UniqueWithCountsV2": "data-dependent output shape",
    "Where": "1-arg Where: output rows = # nonzero, data-dependent",
    "ListDiff": "output shape is data-dependent (set difference)",
    "IteratorGetNext": "tf.data runtime state; feed tensors instead",
    "IteratorV2": "tf.data runtime state; feed tensors instead",
    "If": "TF2 functional control flow: branches live in the GraphDef "
          "function library, which frozen TF1-style inference graphs "
          "(the import contract) inline before freezing",
    "While": "TF2 functional while: see If; TF1 frame loops ARE lowered "
             "(tf/while_frames.py)",
}
# TensorArray family: per-step runtime list state inside TF1 loops. The
# while-frame lowering scans fixed-shape carries instead; graphs that
# thread TensorArrays are rejected loudly.
for _ta in ("TensorArrayV3", "TensorArrayConcat", "TensorArrayConcatV2",
            "TensorArrayConcatV3", "TensorArrayGather",
            "TensorArrayGatherV2", "TensorArrayGatherV3", "TensorArrayRead",
            "TensorArrayReadV2", "TensorArrayReadV3", "TensorArrayScatter",
            "TensorArrayScatterV2", "TensorArrayScatterV3",
            "TensorArraySize", "TensorArraySizeV2", "TensorArraySizeV3",
            "TensorArraySplit", "TensorArraySplitV2", "TensorArraySplitV3",
            "TensorArrayWriteV3"):
    TF_EXEMPT[_ta] = ("TF1 TensorArray runtime list state; while-frame "
                      "lowering uses fixed-shape scan carries")

ONNX_STRUCTURAL: Dict[str, str] = {}

ONNX_EXEMPT: Dict[str, str] = {
    "NonZero": "output shape is data-dependent (# nonzero elements)",
    "If": "subgraph attributes: the hand-rolled wire parser reads flat "
          "graphs; export with inlined branches",
    "Loop": "subgraph attributes + dynamic trip counts (see If)",
    "SequenceAt": "runtime tensor-sequence state",
    "SequenceConstruct": "runtime tensor-sequence state",
    "SequenceEmpty": "runtime tensor-sequence state",
    "SequenceErase": "runtime tensor-sequence state",
    "SequenceInsert": "runtime tensor-sequence state",
    "SequenceLength": "runtime tensor-sequence state",
    "SequenceRemove": "runtime tensor-sequence state",
}


def ruleset_op_names(path: str) -> Set[str]:
    with open(path) as f:
        return set(re.findall(r'inputFrameworkOpName:\s*"([^"]+)"',
                              f.read()))


def report(framework: str) -> dict:
    """Coverage report: mapped/structural/exempt/missing vs the ruleset."""
    from .ir import _MAPPERS
    if framework == "tensorflow":
        import deeplearning4j_tpu.modelimport.tf.importer  # noqa: F401
        ruleset = ruleset_op_names(TF_RULESET)
        structural, exempt = TF_STRUCTURAL, TF_EXEMPT
    elif framework == "onnx":
        import deeplearning4j_tpu.modelimport.onnx.importer  # noqa: F401
        ruleset = ruleset_op_names(ONNX_RULESET)
        structural, exempt = ONNX_STRUCTURAL, ONNX_EXEMPT
    else:
        raise ValueError(framework)
    mapped = set(_MAPPERS.get(framework, {}))
    covered = (mapped | set(structural)) & ruleset
    missing = sorted(ruleset - mapped - set(structural) - set(exempt))
    denom = len(ruleset)
    return {
        "framework": framework,
        "ruleset_total": denom,
        "mapped": sorted(mapped & ruleset),
        "structural": {k: v for k, v in structural.items() if k in ruleset},
        "exempt": {k: v for k, v in exempt.items() if k in ruleset},
        "missing": missing,
        "covered_pct": round(100.0 * len(covered) / denom, 1),
        "accounted_pct": round(
            100.0 * (len(covered) + len(set(exempt) & ruleset)) / denom, 1),
    }


def main():  # pragma: no cover — CLI convenience
    import json
    for fw in ("tensorflow", "onnx"):
        r = report(fw)
        print(json.dumps({k: (len(v) if isinstance(v, (list, dict)) else v)
                          for k, v in r.items()}, indent=None))
        if r["missing"]:
            print(f"  missing[{fw}]: {' '.join(r['missing'])}")


if __name__ == "__main__":  # pragma: no cover
    main()
