"""Minimal generic FlatBuffers table walking, shared by the wire-format
readers (SameDiff ``.fb``, TFLite ``.tflite``).

Slot numbers are the field declaration indices from the respective .fbs
schemas (vtable offset = 4 + 2*slot); readers stay schema-less — no
generated classes, just the ``flatbuffers`` runtime Table.
"""
from __future__ import annotations

import struct
from typing import List, Optional

import flatbuffers.table
from flatbuffers import number_types as N


def tbl(buf: bytes, pos: int) -> flatbuffers.table.Table:
    return flatbuffers.table.Table(buf, pos)


def root(buf: bytes) -> flatbuffers.table.Table:
    (off,) = struct.unpack_from("<I", buf, 0)
    return tbl(buf, off)


def off(t, slot: int) -> int:
    return t.Offset(4 + 2 * slot)


def i8(t, slot, default=0):
    o = off(t, slot)
    return t.Get(N.Int8Flags, t.Pos + o) if o else default


def i32(t, slot, default=0):
    o = off(t, slot)
    return t.Get(N.Int32Flags, t.Pos + o) if o else default


def u32(t, slot, default=0):
    o = off(t, slot)
    return t.Get(N.Uint32Flags, t.Pos + o) if o else default


def i64(t, slot, default=0):
    o = off(t, slot)
    return t.Get(N.Int64Flags, t.Pos + o) if o else default


def f32(t, slot, default=0.0):
    o = off(t, slot)
    return t.Get(N.Float32Flags, t.Pos + o) if o else default


def f64(t, slot, default=0.0):
    o = off(t, slot)
    return t.Get(N.Float64Flags, t.Pos + o) if o else default


def string(t, slot) -> Optional[str]:
    o = off(t, slot)
    return t.String(t.Pos + o).decode("utf-8") if o else None


def subtable(t, slot):
    o = off(t, slot)
    return tbl(t.Bytes, t.Indirect(t.Pos + o)) if o else None


def union_table(t, slot):
    """A union value field: same indirection as a subtable."""
    return subtable(t, slot)


def vec_len(t, slot) -> int:
    o = off(t, slot)
    return t.VectorLen(o) if o else 0


def vec_table(t, slot, i):
    o = off(t, slot)
    return tbl(t.Bytes, t.Indirect(t.Vector(o) + i * 4))


def vec_scalar(t, slot, flags, width) -> list:
    o = off(t, slot)
    if not o:
        return []
    v, n = t.Vector(o), t.VectorLen(o)
    return [t.Get(flags, v + width * i) for i in range(n)]


def vec_i32(t, slot):
    return vec_scalar(t, slot, N.Int32Flags, 4)


def vec_i64(t, slot):
    return vec_scalar(t, slot, N.Int64Flags, 8)


def vec_f32(t, slot):
    return vec_scalar(t, slot, N.Float32Flags, 4)


def vec_f64(t, slot):
    return vec_scalar(t, slot, N.Float64Flags, 8)


def vec_bool(t, slot):
    return [bool(b) for b in vec_scalar(t, slot, N.BoolFlags, 1)]


def vec_str(t, slot) -> List[str]:
    o = off(t, slot)
    if not o:
        return []
    v, n = t.Vector(o), t.VectorLen(o)
    return [t.String(v + 4 * i).decode("utf-8") for i in range(n)]


def vec_bytes(t, slot) -> bytes:
    o = off(t, slot)
    if not o:
        return b""
    v, n = t.Vector(o), t.VectorLen(o)
    return bytes(t.Bytes[v:v + n])
