"""Reader for reference-produced SameDiff FlatBuffers graphs (``.fb``).

The reference serializes SameDiff graphs as FlatBuffers ``FlatGraph`` tables
(writer: ``nd4j/.../autodiff/samediff/SameDiff.java:5465-5727`` ``asFlatGraph``;
schema: ``libnd4j/include/graph/scheme/graph.fbs`` / ``node.fbs`` /
``variable.fbs`` / ``array.fbs``).  This module reads those files directly —
no generated FlatBuffers classes, just the wire format walked with the
``flatbuffers`` runtime ``Table`` — and rebuilds the graph as a native
:class:`~deeplearning4j_tpu.autodiff.samediff.SameDiff`, so a ``.fb``
exported from the JVM executes as one XLA program on TPU.

Scope: inference graphs (variables + constants + placeholders + op nodes).
Training metadata (updaterState, trainingConfig JSON) is surfaced on the
returned object but not converted into an optimizer.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable
from .flatbuf import (i8 as _i8, i32 as _i32, i64 as _i64, root as _root,
                      string as _string, subtable as _subtable,
                      vec_bool as _vec_bool, vec_bytes as _vec_bytes,
                      vec_f64 as _vec_f64, vec_i32 as _vec_i32,
                      vec_i64 as _vec_i64, vec_len as _vec_len,
                      vec_str as _vec_str, vec_table as _vec_table)


# --- DType enum (array.fbs) -> numpy -------------------------------------

_DTYPES = {
    1: np.bool_, 3: np.float16, 5: np.float32, 6: np.float64,
    7: np.int8, 8: np.int16, 9: np.int32, 10: np.int64,
    11: np.uint8, 12: np.uint16, 13: np.uint32, 14: np.uint64,
}
try:  # BFLOAT16 = 17 (array.fbs); ml_dtypes ships with jax
    import ml_dtypes as _mld
    _DTYPES[17] = _mld.bfloat16
except ImportError:  # pragma: no cover
    pass


def _flat_array(t) -> np.ndarray:
    """Decode a FlatArray table: nd4j shapeInfo + raw byte buffer.

    shapeInfo layout (libnd4j ``shape.h``): ``[rank, *shape, *strides,
    extras, elementWiseStride, order]``.  The reference writes the raw
    buffer in the array's own ordering (``BaseNDArray.toFlatArray`` dups
    with ``this.ordering()``), so the trailing order char (99='c',
    102='f') decides how the dense buffer maps onto the shape.
    """
    return _decode_flat_array(_vec_i64(t, 0), _vec_bytes(t, 1),
                              _i8(t, 2, 5), _i8(t, 3, 0))


def _decode_flat_array(info, buf, dt, order) -> np.ndarray:
    """Pure decode: (shapeInfo, buffer, DType enum, ByteOrder) -> ndarray."""
    np_dt = _DTYPES.get(dt)
    if np_dt is None:
        raise ValueError(f"unsupported FlatArray dtype enum {dt}")
    rank = int(info[0]) if info else 0
    shape = tuple(int(d) for d in info[1:1 + rank])
    arr = np.frombuffer(buf, dtype=np_dt)
    if order == 1:
        arr = arr.byteswap()
    n = int(np.prod(shape)) if shape else 1
    if arr.size < n:
        raise ValueError(f"FlatArray buffer too small: {arr.size} < {n}")
    mem_order = "C"
    if rank > 1 and len(info) >= 2 * rank + 4:
        order_char = int(info[-1])
        if order_char == 102:
            mem_order = "F"
        elif order_char not in (99, 0):
            raise ValueError(
                f"unrecognized shapeInfo order char {order_char} "
                f"(expected 99 'c' or 102 'f')")
    return np.asarray(arr[:n].reshape(shape, order=mem_order), order="C")


# ---------------------------------------------------------------------------
# Schema-level records
# ---------------------------------------------------------------------------

_ALL_DIMS = 2147483647  # Integer.MAX_VALUE: reference marker for "all dims"


class FlatNodeRec:
    """One FlatNode (node.fbs) with the fields execution needs."""

    def __init__(self, t):
        self.id = _i32(t, 0)
        self.name = _string(t, 1)
        self.op_type = _i8(t, 2)
        self.op_num = _i64(t, 3)
        self.inputs: List[Tuple[int, int]] = []
        for i in range(_vec_len(t, 6)):  # inputPaired
            p = _vec_table(t, 6, i)
            self.inputs.append((_i32(p, 0), _i32(p, 1)))
        if not self.inputs:  # legacy `input:[int]` encoding
            self.inputs = [(i, 0) for i in _vec_i32(t, 5)]
        self.t_args = _vec_f64(t, 8)      # extraParams
        self.i_args = _vec_i64(t, 9)      # extraInteger
        self.b_args = _vec_bool(t, 10)    # extraBools
        self.dimensions = _vec_i32(t, 11)
        self.output_names = _vec_str(t, 15)
        self.op_name = _string(t, 16)
        sc = _subtable(t, 18)
        self.scalar = _flat_array(sc) if sc is not None else None


class FlatVariableRec:
    """One FlatVariable (variable.fbs)."""

    def __init__(self, t):
        idp = _subtable(t, 0)
        self.id = (_i32(idp, 0), _i32(idp, 1)) if idp is not None else (0, 0)
        self.name = _string(t, 1)
        self.dtype = _i8(t, 2, 5)
        self.shape = _vec_i64(t, 3)
        nd = _subtable(t, 4)
        self.array = _flat_array(nd) if nd is not None else None
        # VarType: 0=VARIABLE 1=CONSTANT 2=ARRAY 3=PLACEHOLDER
        self.var_type = _i8(t, 6)


class UpdaterStateRec:
    """One UpdaterState (graph.fbs): per-parameter optimizer state."""

    def __init__(self, t):
        self.param_name = _string(t, 0)
        self.keys = _vec_str(t, 1)
        self.values = [_flat_array(_vec_table(t, 2, i))
                       for i in range(_vec_len(t, 2))]


class FlatGraphFile:
    """Parsed FlatGraph (graph.fbs) — raw records before SameDiff rebuild."""

    def __init__(self, data: bytes):
        g = _root(data)
        self.graph_id = _i64(g, 0)
        self.variables = [FlatVariableRec(_vec_table(g, 1, i))
                          for i in range(_vec_len(g, 1))]
        self.nodes = [FlatNodeRec(_vec_table(g, 2, i))
                      for i in range(_vec_len(g, 2))]
        self.placeholders = _vec_str(g, 5)
        self.loss_variables = _vec_str(g, 6)
        self.training_config = _string(g, 7)
        self.updater_state = [UpdaterStateRec(_vec_table(g, 8, i))
                              for i in range(_vec_len(g, 8))]


# ---------------------------------------------------------------------------
# Op conversion: FlatNode -> registered op + kwargs
# ---------------------------------------------------------------------------

def _dims_arg(node: FlatNodeRec) -> Optional[List[int]]:
    dims = node.dimensions or [int(d) for d in node.i_args]
    if not dims or _ALL_DIMS in dims:
        return None
    return list(dims)


def _conv_matmul(node):
    ia = list(node.i_args) + [0, 0, 0]
    ta = list(node.t_args) + [1.0, 0.0]
    kw = {}
    if ia[0]:
        kw["transpose_a"] = True
    if ia[1]:
        kw["transpose_b"] = True
    if ta[0] != 1.0:
        kw["alpha"] = float(ta[0])
    return "matmul", kw


def _conv_softmax(node):
    axis = int(node.i_args[0]) if node.i_args else -1
    # keep the node's own op (softmax vs log_softmax) — only the axis
    # arg needs decoding
    return node.op_name or "softmax", {"axis": axis}


def _reduction(op_name):
    def conv(node):
        kw: Dict[str, Any] = {}
        d = _dims_arg(node)
        if d is not None:
            kw["dims"] = d
        if node.b_args and node.b_args[0]:
            kw["keep_dims"] = True
        return op_name, kw
    return conv


# opName -> converter.  Anything absent falls back to a bare registry call
# with no kwargs (correct for elementwise/pairwise ops, which is the long
# tail of what asFlatGraph emits).
_CONVERTERS = {
    "matmul": _conv_matmul,
    "mmul": _conv_matmul,
    "softmax": _conv_softmax,
    "log_softmax": _conv_softmax,
    "reduce_mean": _reduction("reduce_mean"),
    "mean": _reduction("reduce_mean"),
    "reduce_sum": _reduction("reduce_sum"),
    "sum": _reduction("reduce_sum"),
    "reduce_max": _reduction("reduce_max"),
    "max": _reduction("reduce_max"),
    "reduce_min": _reduction("reduce_min"),
    "min": _reduction("reduce_min"),
    "reduce_prod": _reduction("reduce_prod"),
    "norm2": _reduction("reduce_norm2"),
    "argmax": _reduction("argmax"),
    "argmin": _reduction("argmin"),
    # reference gruCell declares 4 outputs (r, u, c, h); the 1-output
    # registry 'gruCell' is the h-only convenience, so route to the
    # full-output port
    "gruCell": lambda node: ("gru_block_cell", {}),
}

# Legacy nodes (opType != CUSTOM) sometimes omit opName; resolve the few
# (opType, opNum) pairs the reference writer emits for them.
# Sources: libnd4j legacy_ops.h op enumerations.
_LEGACY_NAMES = {
    (3, 29): "tanh", (3, 10): "sigmoid", (3, 35): "exp", (3, 36): "log",
    (1, 12): "abs", (1, 6): "neg", (2, 0): "isnan",
    (5, 0): "reduce_mean", (6, 0): "reduce_sum", (6, 3): "reduce_max",
    (6, 4): "reduce_min", (6, 8): "reduce_prod",
    (9, 0): "argmax", (9, 1): "argmin",
}


class SameDiffFbImport:
    """Rebuild a native SameDiff from a parsed FlatGraph."""

    def __init__(self, flat: FlatGraphFile):
        self.flat = flat
        self.sd = SameDiff()
        # (node_id, out_idx) -> SDVariable
        self._by_id: Dict[Tuple[int, int], SDVariable] = {}

    def convert(self) -> SameDiff:
        from ..ops.registry import OpRegistry
        reg = OpRegistry.get()
        ph = set(self.flat.placeholders)
        node_ids = {n.id for n in self.flat.nodes}
        for v in self.flat.variables:
            if v.var_type == 2 or (v.id[0] in node_ids and v.array is None
                                   and v.name not in ph):
                continue  # ARRAY: produced by a node during conversion
            if v.var_type == 3 or v.name in ph:
                shape = (tuple(None if s < 0 else int(s) for s in v.shape)
                         if v.shape else None)
                dt = _DTYPES.get(v.dtype, np.float32)
                var = self.sd.placeholder(v.name, shape=shape,
                                          dtype=np.dtype(dt).name)
            elif v.var_type == 1:
                var = self.sd.constant(np.asarray(v.array), name=v.name)
            elif v.var_type == 0:
                if v.array is None:
                    raise ValueError(f"VARIABLE '{v.name}' has no ndarray")
                var = self.sd.var(v.name, value=np.asarray(v.array))
            else:
                continue
            self._by_id[v.id] = var

        for node in self._topo_order():
            ins = []
            for key in node.inputs:
                src = self._by_id.get(key)
                if src is None:
                    raise ValueError(
                        f"node '{node.name}' input {key} unresolved "
                        f"(cyclic or unsupported producer)")
                ins.append(src)
            op_name = node.op_name or _LEGACY_NAMES.get(
                (node.op_type, node.op_num))
            if op_name is None:
                raise ValueError(
                    f"node '{node.name}': no opName and unknown legacy pair "
                    f"(opType={node.op_type}, opNum={node.op_num})")
            conv = _CONVERTERS.get(op_name)
            if conv is not None:
                reg_name, kwargs = conv(node)
            else:
                reg_name, kwargs = op_name, {}
            if not reg.has(reg_name):
                raise ValueError(
                    f"node '{node.name}': op '{reg_name}' not registered")
            out_names = list(node.output_names) or [node.name]
            if node.scalar is not None and not ins:
                out = self.sd.constant(np.asarray(node.scalar),
                                       name=out_names[0])
                outs = (out,)
            else:
                if node.scalar is not None:
                    ins.append(self.sd.constant(np.asarray(node.scalar),
                                                name=f"{node.name}_scalar"))
                out = self.sd._record(reg_name, ins,
                                      n_outputs=len(out_names),
                                      out_names=out_names, **kwargs)
                outs = out if isinstance(out, tuple) else (out,)
            for i, v in enumerate(outs):
                self._by_id[(node.id, i)] = v
        return self.sd

    def _topo_order(self) -> List[FlatNodeRec]:
        """Nodes in producer-before-consumer order (writer order is close
        but not guaranteed — InferenceSession resolves lazily)."""
        pending = {n.id: n for n in self.flat.nodes}
        done = set(self._by_id)
        order: List[FlatNodeRec] = []
        while pending:
            progressed = False
            for nid in list(pending):
                n = pending[nid]
                if all(k in done or k[0] not in pending for k in n.inputs):
                    order.append(n)
                    done.add((nid, 0))
                    del pending[nid]
                    progressed = True
            if not progressed:
                raise ValueError(
                    f"cyclic FlatGraph: unresolved nodes "
                    f"{[pending[i].name for i in pending]}")
        return order


def load_samediff_fb(path: str) -> SameDiff:
    """Load a reference-produced SameDiff ``.fb`` file as a native SameDiff.

    The returned graph executes under jit via ``sd.output(...)``; loss
    variables and placeholders from the file are preserved as
    ``sd.fb_loss_variables`` / placeholder vars.
    """
    with open(path, "rb") as f:
        data = f.read()
    flat = FlatGraphFile(data)
    sd = SameDiffFbImport(flat).convert()
    sd.fb_loss_variables = list(flat.loss_variables)
    sd._loss_variables = list(flat.loss_variables)
    sd.fb_training_config = flat.training_config
    if flat.updater_state:
        # rebuild the native layout {state_key: {param: array}} so a
        # restored graph resumes training exactly where it stopped
        state: Dict[str, Dict[str, Any]] = {}
        for rec in flat.updater_state:
            for key, arr in zip(rec.keys, rec.values):
                state.setdefault(key, {})[rec.param_name] = arr
        sd._updater_state = state
        sd.fb_updater_state = {
            rec.param_name: dict(zip(rec.keys, rec.values))
            for rec in flat.updater_state}
    return sd
