"""Framework-agnostic import IR + mapping-rule registry.

Reference: `nd4j/samediff-import/samediff-import-api/src/main/kotlin/org/nd4j/
samediff/frameworkimport/ImportGraph.kt:68` (importGraph walks IRGraph nodes,
resolving each through a mapping-rule registry into SameDiff ops) and the
per-framework `IRGraph/IRNode/IROpDef` abstractions (ADRs 0003/0004/0005).

TPU-native redesign: mapping rules emit *registered ops* (pure jax fns) into
a SameDiff graph, so the imported model whole-graph-compiles under XLA like
a natively built one. Shape-ish constant inputs (reshape targets, axes,
perms) are folded into static kwargs at import time — XLA wants static
shapes, so the importer is where TF/ONNX dynamism dies.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable
from ..ops.registry import OpRegistry


class ImportException(Exception):
    pass


@dataclasses.dataclass
class IRNode:
    """One foreign-graph node in framework-neutral form."""
    name: str
    op_type: str
    inputs: List[str]            # producer tensor names (foreign naming)
    outputs: List[str]           # tensor names this node produces
    attrs: Dict[str, Any]
    control_inputs: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class IRGraph:
    """Parsed foreign graph, before mapping."""
    framework: str
    nodes: List[IRNode]
    initializers: Dict[str, np.ndarray]          # weights/consts by tensor name
    inputs: Dict[str, Any]                       # name -> (shape, dtype)
    outputs: List[str]

    def node_map(self) -> Dict[str, IRNode]:
        m = {}
        for n in self.nodes:
            for o in n.outputs:
                m[o] = n
        return m


# --------------------------------------------------------------- registry
# framework -> op_type -> mapper(node, ctx) (ImportGraph's OpMappingRegistry)
_MAPPERS: Dict[str, Dict[str, Callable]] = {}


def mapper(framework: str, *op_types: str):
    def deco(fn):
        reg = _MAPPERS.setdefault(framework, {})
        for t in op_types:
            reg[t] = fn
        return fn
    return deco


def get_mapper(framework: str, op_type: str) -> Optional[Callable]:
    return _MAPPERS.get(framework, {}).get(op_type)


def unmapped_error(framework: str, unmapped) -> "ImportException":
    """Unmapped-op error, annotated with documented exemption reasons."""
    unmapped = sorted(unmapped)
    try:
        from .coverage import ONNX_EXEMPT, TF_EXEMPT
        exempt = TF_EXEMPT if framework == "tensorflow" else ONNX_EXEMPT
    except Exception as e:  # annotations are garnish; never mask the
        import warnings      # unmapped-ops diagnostic — but don't be silent
        warnings.warn(f"coverage exemption annotations unavailable: {e!r}")
        exempt = {}
    notes = [f"{t}: {exempt[t]}" for t in unmapped if t in exempt]
    return ImportException(
        f"no {framework} mapping rule for op type(s): {unmapped}"
        + ("".join(f"\n  - {n}" for n in notes) if notes else ""))


def supported_ops(framework: str) -> List[str]:
    return sorted(_MAPPERS.get(framework, {}))


class ImportContext:
    """Carries the target SameDiff graph during a mapping pass.

    Mapping rules call `ctx.emit(...)` (registered-op node), `ctx.bind(...)`
    (alias a foreign tensor name to an SDVariable) and `ctx.const_value(...)`
    (static fold of a constant input).
    """

    def __init__(self, graph: IRGraph, sd: Optional[SameDiff] = None,
                 import_weights_as_variables: bool = False):
        self.graph = graph
        self.sd = sd or SameDiff.create()
        self.vars: Dict[str, SDVariable] = {}      # foreign tensor name -> var
        self.const_np: Dict[str, np.ndarray] = dict(graph.initializers)
        self._as_variables = import_weights_as_variables
        self._node_map = graph.node_map()
        # static shape/dtype propagation (jax.eval_shape as we emit) — lets
        # Shape/Size/Rank fold to constants, which kills TF graphs' dynamic
        # reshape chains (XLA requires static shapes anyway)
        self._var_aval: Dict[str, jax.ShapeDtypeStruct] = {}

    # -- variable plumbing ------------------------------------------------
    def bind(self, tensor_name: str, var: SDVariable,
             aval: Optional[jax.ShapeDtypeStruct] = None):
        self.vars[tensor_name] = var
        if aval is not None:
            self._var_aval[var.name] = aval
        elif var.shape is not None and var.name not in self._var_aval:
            self._var_aval[var.name] = jax.ShapeDtypeStruct(
                var.shape, np.dtype(var.dtype))

    def aval(self, tensor_name: str) -> Optional[jax.ShapeDtypeStruct]:
        """Static shape/dtype of a foreign tensor, if known."""
        if tensor_name in self.const_np:
            a = np.asarray(self.const_np[tensor_name])
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        v = self.vars.get(tensor_name)
        return self._var_aval.get(v.name) if v is not None else None

    def has(self, tensor_name: str) -> bool:
        return tensor_name in self.vars or tensor_name in self.const_np

    def get(self, tensor_name: str) -> SDVariable:
        """SDVariable for a foreign tensor, materializing consts on demand."""
        if tensor_name in self.vars:
            return self.vars[tensor_name]
        if tensor_name in self.const_np:
            arr = self.const_np[tensor_name]
            safe = tensor_name.replace(":", "_")
            if self._as_variables and np.issubdtype(arr.dtype, np.floating) \
                    and arr.ndim >= 1:
                v = self.sd.var(safe, arr)
            else:
                v = self.sd.constant(arr, safe)
            self.bind(tensor_name, v)
            return v
        raise ImportException(f"tensor {tensor_name!r} not yet produced — "
                              f"graph not topologically ordered?")

    def const_value(self, tensor_name: str) -> np.ndarray:
        """Static value of a constant input (for shapes/axes/perms)."""
        if tensor_name in self.const_np:
            return self.const_np[tensor_name]
        raise ImportException(
            f"input {tensor_name!r} must be a graph constant (static shape/"
            f"axis data) for TPU import, but is computed at runtime")

    def maybe_const(self, tensor_name: str) -> Optional[np.ndarray]:
        return self.const_np.get(tensor_name)

    def producer(self, tensor_name: str) -> Optional[IRNode]:
        return self._node_map.get(tensor_name)

    # -- emission ---------------------------------------------------------
    def _infer_avals(self, op_name, inputs, n_outputs, kwargs):
        """Propagate static shapes through the emitted op via jax.eval_shape."""
        in_avals = []
        for v in inputs:
            if v is None:
                in_avals.append(None)
                continue
            a = self._var_aval.get(v.name)
            if a is None:
                return None
            in_avals.append(a)
        try:
            fn = functools.partial(OpRegistry.get().lookup(op_name).fn, **kwargs)
            out = jax.eval_shape(fn, *in_avals)
        except Exception:
            return None
        if n_outputs == 1:
            return [out]
        return list(out)

    def emit(self, op_name: str, inputs: Sequence[SDVariable],
             out_tensor: str, n_outputs: int = 1, **kwargs):
        """Record a registered op; bind its output(s) to foreign name(s)."""
        safe = out_tensor.replace(":", "_")
        out = self.sd._record(op_name, list(inputs), n_outputs=n_outputs,
                              out_name=safe, **kwargs)
        avals = self._infer_avals(op_name, inputs, n_outputs, kwargs)
        if n_outputs == 1:
            self.bind(out_tensor, out,
                      aval=avals[0] if avals else None)
        return out

    def emit_multi(self, op_name: str, inputs: Sequence[SDVariable],
                   out_tensors: Sequence[str], **kwargs):
        outs = self.sd._record(op_name, list(inputs),
                               n_outputs=len(out_tensors), **kwargs)
        if len(out_tensors) == 1:
            outs = (outs,)
        avals = self._infer_avals(op_name, inputs, len(out_tensors), kwargs)
        for i, (t, v) in enumerate(zip(out_tensors, outs)):
            self.bind(t, v, aval=avals[i] if avals else None)
        return outs


def run_import(graph: IRGraph, sd: Optional[SameDiff] = None,
               import_weights_as_variables: bool = False) -> ImportContext:
    """The ImportGraph.importGraph analog: walk nodes, apply mapping rules."""
    ctx = ImportContext(graph, sd, import_weights_as_variables)
    for name, spec in graph.inputs.items():
        shape, dtype = spec
        ctx.bind(name, ctx.sd.placeholder(name.replace(":", "_"),
                                          shape=shape, dtype=dtype))
    unmapped = sorted({n.op_type for n in graph.nodes
                       if get_mapper(graph.framework, n.op_type) is None})
    if unmapped:
        raise unmapped_error(graph.framework, unmapped)
    for node in graph.nodes:
        fn = get_mapper(graph.framework, node.op_type)
        fn(node, ctx)
    return ctx
