"""TF GraphDef -> SameDiff importer (frozen inference graphs).

Reference: `nd4j/samediff-import/samediff-import-tensorflow/.../
TensorflowFrameworkImporter.kt` + `ImportGraph.kt:218` (runImport), legacy
`org/nd4j/imports/graphmapper/tf/TFGraphMapper.java:901`.

TPU-native pipeline: parse (protoio) -> constant-fold the shape-computation
subgraph with numpy -> map remaining nodes onto registered jax ops -> the
result is an ordinary SameDiff graph that whole-graph-compiles under jit.
The reference instead interprets imported graphs node-by-node; here import
fidelity and XLA compilation are the same artifact.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...autodiff.samediff import SameDiff
from ...ndarray.ndarray import NDArray
from ..ir import (IRGraph, IRNode, ImportContext, ImportException, get_mapper)
from . import mappings  # noqa: F401 — registers the mapping rules
from . import mappings_extra  # noqa: F401 — long-tail ruleset coverage
from .parser import parse_graphdef, _np_dtype
from .slicing import build_index_spec, apply_spec_np


def _fold_reduce(fn):
    def f(node, ins, attrs):
        axes = tuple(int(a) for a in np.atleast_1d(ins[1]))
        return fn(ins[0], axis=axes or None,
                  keepdims=bool(attrs.get("keep_dims", False)))
    return f


def _fold_strided_slice(node, ins, attrs):
    spec = build_index_spec(
        np.asarray(ins[1]).tolist(), np.asarray(ins[2]).tolist(),
        np.asarray(ins[3]).tolist() if len(ins) > 3 else None,
        begin_mask=int(attrs.get("begin_mask", 0)),
        end_mask=int(attrs.get("end_mask", 0)),
        ellipsis_mask=int(attrs.get("ellipsis_mask", 0)),
        new_axis_mask=int(attrs.get("new_axis_mask", 0)),
        shrink_axis_mask=int(attrs.get("shrink_axis_mask", 0)),
        rank=np.asarray(ins[0]).ndim)
    return apply_spec_np(np.asarray(ins[0]), spec)


def _fold_cast(node, ins, attrs):
    dt = attrs.get("DstT")
    return np.asarray(ins[0]).astype(_np_dtype(dt[1])
                                     if isinstance(dt, tuple) else np.float32)


# numpy constant-folding rules for the shape-computation subgraph
_FOLD = {
    "Add": lambda n, i, a: i[0] + i[1],
    "AddV2": lambda n, i, a: i[0] + i[1],
    "Sub": lambda n, i, a: i[0] - i[1],
    "Mul": lambda n, i, a: i[0] * i[1],
    "Div": lambda n, i, a: i[0] / i[1],
    "RealDiv": lambda n, i, a: i[0] / i[1],
    "FloorDiv": lambda n, i, a: i[0] // i[1],
    "FloorMod": lambda n, i, a: np.mod(i[0], i[1]),
    "Maximum": lambda n, i, a: np.maximum(i[0], i[1]),
    "Minimum": lambda n, i, a: np.minimum(i[0], i[1]),
    "Neg": lambda n, i, a: -i[0],
    "Sqrt": lambda n, i, a: np.sqrt(i[0]),
    "Square": lambda n, i, a: np.square(i[0]),
    "Equal": lambda n, i, a: i[0] == i[1],
    "Greater": lambda n, i, a: i[0] > i[1],
    "Less": lambda n, i, a: i[0] < i[1],
    "Cast": _fold_cast,
    "Reshape": lambda n, i, a: np.reshape(i[0], [int(s) for s in i[1]]),
    "Transpose": lambda n, i, a: np.transpose(i[0], [int(p) for p in i[1]]),
    "ExpandDims": lambda n, i, a: np.expand_dims(i[0], int(i[1])),
    "Squeeze": lambda n, i, a: np.squeeze(
        i[0], tuple(a.get("squeeze_dims") or a.get("axis") or []) or None),
    "Pack": lambda n, i, a: np.stack(i, axis=int(a.get("axis", 0))),
    "ConcatV2": lambda n, i, a: np.concatenate(i[:-1], axis=int(i[-1])),
    "StridedSlice": _fold_strided_slice,
    "Slice": lambda n, i, a: np.asarray(i[0])[tuple(
        slice(int(b), None if int(s) == -1 else int(b) + int(s))
        for b, s in zip(i[1], i[2]))],
    "GatherV2": lambda n, i, a: np.take(i[0], i[1],
                                        axis=int(i[2]) if len(i) > 2 else 0),
    "Range": lambda n, i, a: np.arange(i[0], i[1], i[2]),
    "Fill": lambda n, i, a: np.full([int(d) for d in i[0]], i[1]),
    "Tile": lambda n, i, a: np.tile(i[0], [int(r) for r in i[1]]),
    "Prod": _fold_reduce(np.prod),
    "Sum": _fold_reduce(np.sum),
    "Max": _fold_reduce(np.max),
    "Min": _fold_reduce(np.min),
    "Select": lambda n, i, a: np.where(i[0], i[1], i[2]),
    "SelectV2": lambda n, i, a: np.where(i[0], i[1], i[2]),
    "ZerosLike": lambda n, i, a: np.zeros_like(i[0]),
    "OnesLike": lambda n, i, a: np.ones_like(i[0]),
}


def _toposort(nodes: List[IRNode], known: set) -> List[IRNode]:
    by_out = {o: n for n in nodes for o in n.outputs}
    order: List[IRNode] = []
    state: Dict[str, int] = {}  # node name -> 0 visiting, 1 done

    def visit(n: IRNode):
        s = state.get(n.name)
        if s == 1:
            return
        if s == 0:
            raise ImportException(f"cycle through node {n.name!r} — "
                                  f"raw TF control flow is not importable; "
                                  f"freeze/lower the graph first")
        state[n.name] = 0
        for t in n.inputs:
            if t in known:
                continue
            prod = by_out.get(t)
            if prod is None and ":" in t:
                # secondary outputs (e.g. Switch:1) alias the :0 producer
                prod = by_out.get(t.split(":")[0] + ":0")
            if prod is not None:
                visit(prod)
        state[n.name] = 1
        order.append(n)

    for n in nodes:
        visit(n)
    return order


class ImportedGraph:
    """Result of an import: a SameDiff graph + tensor-name bindings."""

    def __init__(self, sd: SameDiff, ctx: ImportContext,
                 inputs: Dict[str, str], outputs: Dict[str, str]):
        self.sd = sd
        self.ctx = ctx
        self.inputs = inputs     # foreign tensor name -> placeholder var name
        self.outputs = outputs   # foreign tensor name -> sd var name

    def _resolve_feed(self, feeds: Dict) -> Dict[str, np.ndarray]:
        ph = {}
        short = {k.split(":")[0]: v for k, v in self.inputs.items()}
        for k, v in feeds.items():
            if k in self.inputs:
                ph[self.inputs[k]] = v
            elif k in short:
                ph[short[k]] = v
            else:
                ph[k] = v
        return ph

    def output(self, feeds: Dict, outputs: Optional[Sequence[str]] = None
               ) -> Dict[str, NDArray]:
        """Run the imported graph (SameDiff.output under the hood)."""
        names = list(outputs) if outputs else list(self.outputs)
        sd_names = []
        for n in names:
            for cand in (n, n + ":0") if ":" not in n else (n,):
                if cand in self.outputs:
                    sd_names.append(self.outputs[cand])
                    break
                if cand in self.ctx.vars:
                    sd_names.append(self.ctx.vars[cand].name)
                    break
            else:
                raise KeyError(f"unknown output tensor {n!r}")
        res = self.sd.output(self._resolve_feed(feeds), sd_names)
        return {n: res[s] for n, s in zip(names, sd_names)}


class TFGraphImporter:
    """Import a frozen TF GraphDef (.pb file or bytes)."""

    def __init__(self, pb, input_shapes: Optional[Dict[str, Tuple]] = None,
                 outputs: Optional[List[str]] = None):
        if isinstance(pb, (str, os.PathLike)):
            with open(pb, "rb") as f:
                pb = f.read()
        self.graph = parse_graphdef(pb, input_shapes=input_shapes,
                                    outputs=outputs)

    def import_graph(self, sd: Optional[SameDiff] = None,
                     import_weights_as_variables: bool = False
                     ) -> ImportedGraph:
        g = self.graph

        # TF1 while frames (Enter/Merge/Switch/... cycles) lower to single
        # while_loop nodes before the acyclic pass; nested frames lower
        # innermost-first via graph rewriting (see while_frames.plan_frames)
        from .while_frames import plan_frames
        plans, g = plan_frames(g)

        unmapped = sorted({n.op_type for n in g.nodes
                           if get_mapper(g.framework, n.op_type) is None
                           and n.op_type not in _FOLD
                           and n.op_type != "_TF1WhileFrame"})
        if unmapped:
            from ..ir import unmapped_error
            raise unmapped_error("tensorflow", unmapped)
        ctx = ImportContext(g, sd, import_weights_as_variables)
        inputs = {}
        for name, (shape, dtype) in g.inputs.items():
            if shape is None or any(s is None for s in shape):
                raise ImportException(
                    f"placeholder {name!r} has dynamic shape {shape}; pass "
                    f"concrete input_shapes (static shapes are required for "
                    f"XLA)")
            v = ctx.sd.placeholder(name.replace(":", "_").split(":")[0],
                                   shape=shape, dtype=dtype)
            ctx.bind(name, v)
            inputs[name] = v.name

        known = set(g.initializers) | set(g.inputs)
        for node in _toposort(g.nodes, known):
            if node.op_type == "_TF1WhileFrame":
                plans[node.attrs["plan"]].emit(ctx)
                continue
            folder = _FOLD.get(node.op_type)
            if folder is not None and all(i in ctx.const_np
                                          for i in node.inputs):
                ins = [np.asarray(ctx.const_np[i]) for i in node.inputs]
                out = folder(node, ins, node.attrs)
                ctx.const_np[node.outputs[0]] = np.asarray(out)
                continue
            rule = get_mapper(g.framework, node.op_type)
            if rule is None:
                raise ImportException(
                    f"op {node.op_type!r} is only constant-foldable but has "
                    f"non-constant inputs (node {node.name!r})")
            rule(node, ctx)

        outputs = {}
        for t in g.outputs:
            if t in ctx.vars:
                outputs[t] = ctx.vars[t].name
            elif t in ctx.const_np:
                outputs[t] = ctx.get(t).name
        return ImportedGraph(ctx.sd, ctx, inputs, outputs)


def import_tf_graph(pb, input_shapes=None, outputs=None,
                    import_weights_as_variables: bool = False
                    ) -> ImportedGraph:
    """One-call TF .pb import (reference TFGraphMapper.importGraph analog)."""
    return TFGraphImporter(pb, input_shapes, outputs).import_graph(
        import_weights_as_variables=import_weights_as_variables)
