"""TensorFlow GraphDef (.pb) wire-format parser -> IRGraph.

Parses the public tensorflow/core/framework protos (graph.proto,
node_def.proto, attr_value.proto, tensor.proto, tensor_shape.proto) with the
schemaless decoder in `protoio.py` — no tensorflow runtime required.

Reference counterpart: the shaded TF protos consumed by
`nd4j/samediff-import/samediff-import-tensorflow` and the legacy
`org/nd4j/imports/graphmapper/tf/TFGraphMapper.java`.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import protoio as pio
from ..ir import IRGraph, IRNode, ImportException

# tensorflow DataType enum -> numpy dtype
_TF_DTYPES = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
    6: np.int8, 7: object, 9: np.int64, 10: np.bool_, 17: np.uint16,
    19: np.float16, 22: np.uint32, 23: np.uint64,
}


def _np_dtype(tf_enum: int):
    if tf_enum == 14:  # DT_BFLOAT16
        import ml_dtypes
        return ml_dtypes.bfloat16
    try:
        return _TF_DTYPES[tf_enum]
    except KeyError:
        raise ImportException(f"unsupported TF dtype enum {tf_enum}")


def parse_tensor_shape(buf: bytes) -> Optional[Tuple[int, ...]]:
    """TensorShapeProto: dim=2 {size=1}, unknown_rank=3."""
    f = pio.decode(buf)
    if pio.first(f, 3):
        return None
    dims = []
    for d in pio.all_(f, 2):
        df = pio.decode(d)
        size = pio.as_int64(pio.first(df, 1, 0))
        dims.append(None if size == -1 else size)
    return tuple(dims)


def parse_tensor(buf: bytes) -> np.ndarray:
    """TensorProto -> numpy (tensor_content raw bytes or typed *_val arrays)."""
    f = pio.decode(buf)
    dtype = _np_dtype(pio.first(f, 1, 1))
    shape_buf = pio.first(f, 2)
    shape = parse_tensor_shape(shape_buf) if shape_buf is not None else ()
    if shape is None:
        raise ImportException("TensorProto with unknown rank")
    content = pio.first(f, 4)
    if content:
        arr = np.frombuffer(content, dtype=dtype)
        return arr.reshape(shape)
    # typed value fields
    if dtype == np.float32:
        vals = np.asarray(pio.floats(f, 5), np.float32)
    elif dtype == np.float64:
        vals = np.asarray(pio.doubles(f, 6), np.float64)
    elif dtype in (np.int32, np.int16, np.int8, np.uint8, np.uint16):
        vals = np.asarray(pio.ints(f, 7), dtype)
    elif dtype == np.int64:
        vals = np.asarray(pio.ints(f, 10), np.int64)
    elif dtype == np.bool_:
        vals = np.asarray(pio.ints(f, 11), np.bool_)
    elif dtype == np.float16 or dtype.__name__ == "bfloat16":
        raw = np.asarray(pio.ints(f, 13), np.uint16)
        vals = raw.view(dtype) if raw.size else np.asarray([], dtype)
    elif dtype == object:  # DT_STRING
        vals = np.asarray([s.decode("utf-8", "replace")
                           for s in pio.all_(f, 8)], object)
    else:
        vals = np.asarray(pio.ints(f, 7, signed=False), dtype)
    n = int(np.prod(shape)) if shape else 1
    if vals.size == 0:
        return np.zeros(shape, dtype if dtype != object else object)
    if vals.size == 1 and n != 1:   # splat value broadcast over shape
        return np.full(shape, vals[0], dtype if dtype != object else object)
    return vals.reshape(shape)


def parse_attr_value(buf: bytes) -> Any:
    """AttrValue: s=2 i=3 f=4 b=5 type=6 shape=7 tensor=8 list=1 placeholder=9."""
    f = pio.decode(buf)
    if 2 in f:
        raw = pio.first(f, 2)
        try:
            return raw.decode("utf-8")
        except UnicodeDecodeError:
            return raw
    if 3 in f:
        return pio.as_int64(pio.first(f, 3))
    if 4 in f:
        return pio.as_float32(pio.first(f, 4))
    if 5 in f:
        return bool(pio.first(f, 5))
    if 6 in f:
        return ("dtype", pio.first(f, 6))
    if 7 in f:
        return ("shape", parse_tensor_shape(pio.first(f, 7)))
    if 8 in f:
        return parse_tensor(pio.first(f, 8))
    if 1 in f:
        lf = pio.decode(pio.first(f, 1))
        if 3 in lf:
            return pio.ints(lf, 3)
        if 4 in lf:
            return pio.floats(lf, 4)
        if 2 in lf:
            return [s.decode("utf-8", "replace") for s in pio.all_(lf, 2)]
        if 5 in lf:
            return [bool(b) for b in pio.ints(lf, 5)]
        if 6 in lf:
            return ("dtypes", pio.ints(lf, 6))
        if 7 in lf:
            return ("shapes", [parse_tensor_shape(s) for s in pio.all_(lf, 7)])
        return []
    if 9 in f:
        return ("placeholder", pio.as_str(pio.first(f, 9)))
    if 10 in f:
        return ("func", None)
    return None


def _norm(ref: str) -> str:
    """Normalize a NodeDef input ref: 'x' -> 'x:0' (keep '^ctrl' as is)."""
    if ref.startswith("^"):
        return ref
    return ref if ":" in ref else ref + ":0"


def parse_graphdef(data: bytes,
                   input_shapes: Optional[Dict[str, Tuple]] = None,
                   outputs: Optional[List[str]] = None) -> IRGraph:
    """GraphDef bytes -> IRGraph.

    `input_shapes`: concrete static shapes for placeholders (TPU import
    requires static shapes; overrides any -1/unknown dims in the graph).
    `outputs`: requested output tensor names ('node' or 'node:i'); defaults
    to terminal nodes (consumed by nobody).
    """
    g = pio.decode(data)
    if 2 in g and pio.all_(g, 2):
        lib = pio.decode(pio.first(g, 2))
        if 1 in lib:  # FunctionDefLibrary.function
            raise ImportException(
                "GraphDef contains a function library (PartitionedCall-style "
                "graph); freeze with aggressive inlining first")
    nodes: List[IRNode] = []
    initializers: Dict[str, np.ndarray] = {}
    inputs: Dict[str, Any] = {}
    input_shapes = input_shapes or {}

    for raw in pio.all_(g, 1):
        nf = pio.decode(raw)
        name = pio.as_str(pio.first(nf, 1))
        op = pio.as_str(pio.first(nf, 2))
        in_refs = [pio.as_str(s) for s in pio.all_(nf, 3)]
        data_in = [_norm(r) for r in in_refs if not r.startswith("^")]
        ctrl_in = [r[1:] for r in in_refs if r.startswith("^")]
        attrs: Dict[str, Any] = {}
        for entry in pio.all_(nf, 5):
            ef = pio.decode(entry)
            key = pio.as_str(pio.first(ef, 1))
            if key.startswith("_"):
                continue
            val_buf = pio.first(ef, 2)
            attrs[key] = parse_attr_value(val_buf) if val_buf else None

        if op == "Const":
            initializers[name + ":0"] = attrs.get("value")
            continue
        if op in ("Placeholder", "PlaceholderWithDefault"):
            shape = input_shapes.get(name)
            if shape is None:
                sh = attrs.get("shape")
                shape = sh[1] if isinstance(sh, tuple) and sh[0] == "shape" \
                    else None
            dt = attrs.get("dtype")
            np_dt = _np_dtype(dt[1]) if isinstance(dt, tuple) else np.float32
            dtype_name = "float32" if np_dt == object else np.dtype(np_dt).name
            inputs[name + ":0"] = (shape, dtype_name)
            continue
        nodes.append(IRNode(name=name, op_type=op, inputs=data_in,
                            outputs=[name + ":0"], attrs=attrs,
                            control_inputs=ctrl_in))

    if outputs:
        out_names = [_norm(o) for o in outputs]
    else:
        consumed = {i for n in nodes for i in n.inputs}
        out_names = [n.outputs[0] for n in nodes
                     if n.outputs[0] not in consumed]
    return IRGraph(framework="tensorflow", nodes=nodes,
                   initializers=initializers, inputs=inputs,
                   outputs=out_names)
