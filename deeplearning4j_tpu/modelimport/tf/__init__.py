from .importer import TFGraphImporter, import_tf_graph

__all__ = ["TFGraphImporter", "import_tf_graph"]
