"""TF1 while-loop frame conversion: Enter/Merge/Switch/Exit cycles ->
one `while_loop` node with SubGraph cond/body.

Reference: the session interpreter executes these frames directly with
FrameIter bookkeeping (`InferenceSession.java:828`); TPU-native import
instead *recognizes* each frame statically and lowers it to the registered
`while_loop` op (lax.while_loop) — the frame ops disappear, XLA compiles a
native loop.

Frame anatomy (per TF control-flow spec, one frame per while):
  Enter_i(init_i) -> Merge_i(Enter_i, NextIteration_i) ->
  cond nodes -> LoopCond -> Switch_i(Merge_i, LoopCond)
  Switch_i:1 -> body nodes -> NextIteration_i        (loop taken)
  Switch_i:0 -> Exit_i                               (loop done)
Nested frames lower innermost-first: each planned frame is replaced in the
graph by a synthetic `_TF1WhileFrame` node, so an outer frame's body simply
contains an already-lowered inner `while_loop` (arbitrary nesting depth,
matching the reference interpreter's FrameIter stack semantics).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ...autodiff.samediff import SameDiff
from ...autodiff.subgraph import SubGraph
from ...ops.registry import OpRegistry
from ..ir import IRGraph, IRNode, ImportContext, ImportException, get_mapper


def find_frames(nodes: List[IRNode]) -> Dict[str, List[IRNode]]:
    """frame_name -> Enter nodes."""
    frames: Dict[str, List[IRNode]] = {}
    for n in nodes:
        if n.op_type == "Enter":
            fname = n.attrs.get("frame_name")
            fname = fname if isinstance(fname, str) else str(fname)
            frames.setdefault(fname, []).append(n)
    return frames


class WhileFrame:
    """One recognized while frame + its structural nodes."""

    def __init__(self, frame_name: str, nodes: List[IRNode]):
        self.frame_name = frame_name
        by_out = {o: n for n in nodes for o in n.outputs}
        all_enters = [n for n in nodes if n.op_type == "Enter" and
                      str(n.attrs.get("frame_name")) == frame_name]
        enter_outs = {n.outputs[0] for n in all_enters}
        # loop-variable Enters feed a Merge; is_constant Enters carry
        # loop-invariant captures and stay in the outer graph (identity)
        self.merges = [n for n in nodes if n.op_type == "Merge" and
                       any(i in enter_outs for i in n.inputs)]
        self.enters = []
        for m in self.merges:
            e = next(by_out[i] for i in m.inputs if i in enter_outs)
            self.enters.append(e)
        merge_outs = {m.outputs[0] for m in self.merges}
        self.loop_conds = [n for n in nodes if n.op_type == "LoopCond" and
                           self._feeds_from(n, merge_outs, by_out)]
        if len(self.loop_conds) != 1:
            raise ImportException(
                f"while frame {frame_name!r}: expected 1 LoopCond, found "
                f"{len(self.loop_conds)} (nested/irregular frames are not "
                f"supported)")
        self.loop_cond = self.loop_conds[0]
        lc_out = self.loop_cond.outputs[0]
        self.switches = [n for n in nodes if n.op_type == "Switch" and
                         lc_out in n.inputs]
        # map each switch to its loop-var index via its Merge input
        merge_idx = {m.outputs[0]: i for i, m in enumerate(self.merges)}
        self.switch_for_var: Dict[int, IRNode] = {}
        for s in self.switches:
            for i in s.inputs:
                if i in merge_idx:
                    self.switch_for_var[merge_idx[i]] = s
        switch_names = {s.name for s in self.switches}
        self.exits = {}
        self.next_iters = {}
        for n in nodes:
            if n.op_type == "Exit":
                src = n.inputs[0].split(":")[0]
                if src in switch_names:
                    idx = next(i for i, s in self.switch_for_var.items()
                               if s.name == src)
                    self.exits[idx] = n
            if n.op_type == "NextIteration":
                for m_i, m in enumerate(self.merges):
                    if n.outputs[0] in m.inputs:
                        self.next_iters[m_i] = n
        self.structural = ({n.name for n in self.enters} |
                          {n.name for n in self.merges} |
                          {self.loop_cond.name} | switch_names |
                          {n.name for n in self.exits.values()} |
                          {n.name for n in self.next_iters.values()})

    @staticmethod
    def _feeds_from(node, sources, by_out):
        """Backward reachability within the frame: memoized, and stops at
        Enter nodes (frame boundaries) so sibling loops upstream don't
        alias into this frame."""
        seen = set()
        stack = [node]
        while stack:
            n = stack.pop()
            if n.name in seen:
                continue
            seen.add(n.name)
            for i in n.inputs:
                if i in sources:
                    return True
                prod = by_out.get(i)
                if prod is not None and prod.op_type != "Enter":
                    stack.append(prod)
        return False

    def n_vars(self) -> int:
        return len(self.merges)


def _interior(frame: WhileFrame, nodes: List[IRNode],
              start_tensors, stop_names) -> List[IRNode]:
    """Nodes forward-reachable from start_tensors up to (exclusive) the
    structural stop set, in original order."""
    by_out = {o: n for n in nodes for o in n.outputs}
    consumers: Dict[str, List[IRNode]] = {}
    for n in nodes:
        for i in n.inputs:
            consumers.setdefault(i, []).append(n)
    seen = set()
    work = list(start_tensors)
    while work:
        t = work.pop()
        for n in consumers.get(t, []):
            if n.name in stop_names or n.name in seen:
                continue
            seen.add(n.name)
            work.extend(n.outputs)
    return [n for n in nodes if n.name in seen]


def _build_subgraph(graph: IRGraph, interior: List[IRNode],
                    var_aliases: Dict[str, int], n_vars: int,
                    out_tensors: List[str], prefix: str,
                    plans: List["FramePlan"] = ()
                    ) -> Tuple[SubGraph, List[str]]:
    """Map interior TF nodes into a SubGraph whose placeholders are the
    loop variables; external tensors become captured names. `plans` holds
    already-lowered inner frames (`_TF1WhileFrame` interior nodes)."""
    sub_sd = SameDiff.create()
    ctx = ImportContext(
        IRGraph(framework="tensorflow", nodes=interior,
                initializers=graph.initializers, inputs={}, outputs=[]),
        sub_sd)
    phs = [sub_sd.placeholder(f"{prefix}{i}") for i in range(n_vars)]
    for tensor, idx in var_aliases.items():
        ctx.bind(tensor, phs[idx])

    produced = {o for n in interior for o in n.outputs} | set(var_aliases)
    captured: List[str] = []
    # interior inputs AND requested outputs may live outside the frame
    # (e.g. a loop var whose update is a loop-invariant outer expression)
    for t in [i for n in interior for i in n.inputs] + list(out_tensors):
        if t not in produced and t not in graph.initializers and \
                t not in captured:
            captured.append(t)
    # captured outer tensors appear as extra placeholders named verbatim
    for c in captured:
        ctx.bind(c, sub_sd.placeholder(c.replace(":", "_")))

    # graph-rewriting (nested frames) can leave interior out of order
    from .importer import _toposort
    for node in _toposort(interior, set(var_aliases) | set(captured)):
        if node.op_type == "_TF1WhileFrame":
            plans[node.attrs["plan"]].emit(ctx)
            continue
        rule = get_mapper("tensorflow", node.op_type)
        if rule is None:
            raise ImportException(
                f"no mapping rule for {node.op_type!r} inside while frame")
        rule(node, ctx)

    reg = OpRegistry.get()
    sg_nodes = []
    for name in sub_sd._op_order:
        op_node = sub_sd._ops[name]
        if not reg.has(op_node.op_name):
            raise ImportException(
                f"unserializable op {op_node.op_name!r} in while frame")
        sg_nodes.append({"name": op_node.name, "op": op_node.op_name,
                         "inputs": op_node.inputs,
                         "outputs": op_node.outputs,
                         "kwargs": op_node.kwargs})
    outs = [ctx.get(t).name for t in out_tensors]
    # loop-var placeholders are positional; captures ride the while_loop
    # op's capture mechanism (values appended after the loop vars)
    sg = SubGraph(placeholders=[p.name for p in phs], outputs=outs,
                  nodes=sg_nodes, constants=dict(sub_sd._arrays),
                  captured=[c.replace(":", "_") for c in captured])
    return sg, captured


class _NestedFrame(Exception):
    """Raised when a frame's interior still contains another (un-lowered)
    frame — plan_frames defers it until the inner frame is rewritten."""


class FramePlan:
    """Pre-built lowering of one while frame (SubGraphs are static — only
    the init/capture VALUES need the outer import context)."""

    _STRUCTURAL_OPS = ("Enter", "Merge", "Switch", "Exit", "NextIteration",
                       "LoopCond")

    def __init__(self, graph: IRGraph, frame: WhileFrame,
                 plans: List["FramePlan"] = ()):
        n = frame.n_vars()
        nodes = graph.nodes

        merge_alias = {m.outputs[0]: i for i, m in enumerate(frame.merges)}
        cond_stop = frame.structural
        cond_interior = _interior(frame, nodes, list(merge_alias), cond_stop)

        body_alias = dict(merge_alias)
        for idx, s in frame.switch_for_var.items():
            body_alias[f"{s.name}:1"] = idx
        body_interior = _interior(frame, nodes, list(body_alias), cond_stop)

        for node in cond_interior + body_interior:
            if node.op_type in self._STRUCTURAL_OPS and \
                    node.name not in frame.structural:
                # an is_constant Enter of an ALREADY-lowered inner frame is
                # a plain identity pass-through (the Enter mapper handles
                # it); a live inner frame also exposes Merge/LoopCond here
                # and still defers
                if node.op_type == "Enter":
                    continue
                raise _NestedFrame(frame.frame_name)

        self.cond_sg, cond_caps = _build_subgraph(
            graph, cond_interior, merge_alias, n,
            [frame.loop_cond.inputs[0]], "c", plans)

        body_outs = []
        for i in range(n):
            t = frame.next_iters[i].inputs[0] if i in frame.next_iters \
                else frame.merges[i].outputs[0]  # un-advanced var
            body_outs.append(t)
        self.body_sg, body_caps = _build_subgraph(
            graph, body_interior, body_alias, n, body_outs, "b", plans)

        self.cap_union: List[str] = []
        for c in cond_caps + body_caps:
            if c not in self.cap_union:
                self.cap_union.append(c)
        self.cap_names = [c.replace(":", "_") for c in self.cap_union]
        self.n = n
        self.init_tensors = [e.inputs[0] for e in frame.enters]
        self.exit_binds = {i: x.outputs[0] for i, x in frame.exits.items()}
        self.consumed = (frame.structural |
                         {x.name for x in cond_interior} |
                         {x.name for x in body_interior})
        self.out_tensors = [self.exit_binds[i]
                            for i in sorted(self.exit_binds)]

    def emit(self, ctx: ImportContext):
        init_vars = [ctx.get(t) for t in self.init_tensors]
        cap_vars = [ctx.get(c) for c in self.cap_union]
        outs = ctx.sd._record("while_loop", init_vars + cap_vars,
                              n_outputs=self.n, cond_graph=self.cond_sg,
                              body_graph=self.body_sg, n_loop_vars=self.n,
                              cap_names=self.cap_names)
        if self.n == 1:
            outs = (outs,)
        for i, tensor in self.exit_binds.items():
            ctx.bind(tensor, outs[i])


def plan_frames(graph: IRGraph) -> Tuple[List[FramePlan], IRGraph]:
    """Recognize and pre-lower every while frame, innermost-first.

    Each planned frame's nodes are replaced by one synthetic
    `_TF1WhileFrame` node, so outer frames see inner loops as ordinary
    single nodes (arbitrary nesting). Returns (plans, rewritten graph);
    the synthetic node's attrs["plan"] indexes into plans.
    """
    plans: List[FramePlan] = []
    while True:
        pending = find_frames(graph.nodes)
        # a lowered frame leaves its is_constant (loop-invariant) Enters
        # behind as identity pass-throughs — only frames that still have a
        # Merge-fed loop variable remain to be planned
        merges_in = {i for n in graph.nodes if n.op_type == "Merge"
                     for i in n.inputs}
        pending = {f: ens for f, ens in pending.items()
                   if any(e.outputs[0] in merges_in for e in ens)}
        if not pending:
            return plans, graph
        progressed = False
        for fname in list(pending):
            try:
                plan = FramePlan(graph, WhileFrame(fname, graph.nodes),
                                 plans)
            except _NestedFrame:
                continue  # an inner frame must lower first
            idx = len(plans)
            plans.append(plan)
            kept = [n for n in graph.nodes if n.name not in plan.consumed]
            kept.append(IRNode(
                name=f"__while_frame_{idx}", op_type="_TF1WhileFrame",
                inputs=list(plan.init_tensors) + list(plan.cap_union),
                outputs=list(plan.out_tensors), attrs={"plan": idx}))
            graph = IRGraph(framework=graph.framework, nodes=kept,
                            initializers=graph.initializers,
                            inputs=graph.inputs, outputs=graph.outputs)
            progressed = True
        if not progressed:
            raise ImportException(
                f"could not lower while frames {sorted(pending)} — "
                f"mutually nested or irregular frame structure")
